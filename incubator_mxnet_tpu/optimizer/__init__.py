"""mx.optimizer — optimizers with fused XLA update kernels.

Reference: python/mxnet/optimizer/ (20 optimizers dispatching to fused C++
update ops, src/operator/optimizer_op.cc:49-1095 — sgd_update, sgd_mom_update,
adam_update, lamb_update_phase1/2, multi-tensor variants, multi-precision
fp32 master weights).

TPU-native design: each optimizer's update rule is ONE pure jax function
jitted with buffer donation — weight and state buffers are donated so XLA
updates in place (≙ the reference's in-place FCompute updates). Scalars
(lr, wd, momentum...) enter as traced args so schedule changes never
recompile. Multi-precision keeps an fp32 master copy for fp16/bf16 weights
(≙ mp_sgd_update / MultiPrecision in the reference).
"""
from __future__ import annotations

import functools

import numpy as _np

from ..base import MXNetError
from ..ndarray import NDArray, _wrap, zeros
from ..lr_scheduler import LRScheduler

__all__ = [
    "Optimizer", "register", "create", "SGD", "Signum", "SGLD", "DCASGD",
    "NAG", "AdaGrad", "AdaDelta", "Adam", "AdamW", "Adamax", "Nadam", "FTML",
    "FTRL", "LARS", "LAMB", "LANS", "RMSProp", "AdaBelief", "Updater",
    "get_updater",
]

_REGISTRY = {}


def __getattr__(name):
    # lazy submodule: `optimizer.sharded` (the ZeRO shard layer for
    # mx.fault.elastic) stays off the base optimizer import path
    if name == "sharded":
        import importlib
        mod = importlib.import_module(".sharded", __name__)
        globals()["sharded"] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def register(klass):
    """≙ mx.optimizer.register."""
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    """≙ mx.optimizer.create('sgd', ...)."""
    if isinstance(name, Optimizer):
        return name
    name = name.lower()
    if name not in _REGISTRY:
        raise MXNetError(f"unknown optimizer {name!r}: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


def _f32(x):
    """Cast a host float OR traced scalar to f32 (kernels accept both, so
    traced lr/wd never force a retrace)."""
    import jax.numpy as jnp
    return jnp.asarray(x, jnp.float32)


def _jit_update(fn, donate=()):
    """Jit an update kernel donating weight+state buffers so XLA aliases
    them in place (≙ the reference's in-place FCompute updates)."""
    import jax
    from .. import sanitize as _sanitize
    return _sanitize.maybe_wrap_donated(
        jax.jit(fn, donate_argnums=donate), donate,
        f"optimizer.{getattr(fn, '__name__', 'update')}")


class Optimizer:
    """Base optimizer (≙ python/mxnet/optimizer/optimizer.py Optimizer)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=None, lr_scheduler=None,
                 multi_precision=False, param_dict=None,
                 aggregate_num=None, use_fused_step=True, **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate if learning_rate is not None else 0.01
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None and learning_rate is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.param_dict = param_dict or {}
        self.idx2name = param_idx2name or {}
        self.lr_mult = {}
        self.wd_mult = {}
        self.num_update = 0
        self._index_update_count = {}
        self._all_index_update_counts = {0: self._index_update_count}
        self._jitted = {}

    # ------------------------------------------------------------------
    # lr / wd plumbing (≙ Optimizer._get_lrs/_get_wds)
    # ------------------------------------------------------------------
    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("cannot set lr directly when lr_scheduler is set")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = 0
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.learning_rate
        param = self.param_dict.get(index)
        if param is not None:
            lr *= getattr(param, "lr_mult", 1.0)
        else:
            lr *= self.lr_mult.get(self.idx2name.get(index, index), 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        param = self.param_dict.get(index)
        if param is not None:
            wd *= getattr(param, "wd_mult", 1.0)
        else:
            wd *= self.wd_mult.get(self.idx2name.get(index, index), 1.0)
        return wd

    # ------------------------------------------------------------------
    # state
    # ------------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        """fp32 master copy for low-precision weights (≙ mp_* ops)."""
        if self.multi_precision and str(weight.dtype) in ("float16", "bfloat16"):
            master = weight.astype("float32")
            return (master, self.create_state(index, master))
        return self.create_state(index, weight)

    # ------------------------------------------------------------------
    # update
    # ------------------------------------------------------------------
    def _preprocess(self, grad_raw, wd):
        """rescale + clip; returns a jax expression fragment used in kernels."""
        import jax.numpy as jnp
        g = grad_raw * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    def update(self, index, weight, grad, state):
        """Single-param update; mutates weight (and state) NDArrays."""
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self.step_one(index, weight, grad, state, lr, wd)

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and str(weight.dtype) in ("float16", "bfloat16"):
            master, inner = state
            self._update_count(index)
            lr, wd = self._get_lr(index), self._get_wd(index)
            grad32 = grad.astype("float32")
            self.step_one(index, master, grad32, inner, lr, wd)
            weight._set_arr(master._arr.astype(weight.dtype))
            return
        self.update(index, weight, grad, state)

    def step_one(self, index, weight, grad, state, lr, wd):
        raise NotImplementedError

    # allow lists (≙ reference update(self, indices, weights, grads, states))
    def update_all(self, indices, weights, grads, states):
        for i, w, g, s in zip(indices, weights, grads, states):
            self.update(i, w, g, s)

    # True for rules whose step_one is trace-pure given (lr, wd[, t]) —
    # the Adam family passes its bias-correction step count as the traced
    # `t` argument. Rules with other per-step host state (Nadam's
    # m_schedule, SGLD's fresh RNG key) stay on the per-param path.
    _fused_safe = False

    @classmethod
    def _step_takes_t(cls):
        """Cached per-class: does step_one accept the traced step count?"""
        cached = cls.__dict__.get("_takes_t_cache")
        if cached is None:
            import inspect
            cached = "t" in inspect.signature(cls.step_one).parameters
            cls._takes_t_cache = cached
        return cached

    def _hyper_fingerprint(self):
        """Scalar hyperparameters baked into fused traces (momentum, rho,
        epsilon, ...): changing any of them must miss the jit cache."""
        skip = {"lr", "wd", "num_update", "rescale_grad"}
        return tuple(sorted(
            (k, v) for k, v in self.__dict__.items()
            if not k.startswith("_") and k not in skip
            and isinstance(v, (int, float, bool))))

    def fused_update_all_bulked(self, items):
        """Multi-tensor fused update routed through the op-bulking segment.

        When the engine is bulking (ops/segment.py), the update must join
        the SAME deferred program as the forward/backward: the whole train
        iteration then compiles into one donated, self-feeding XLA
        executable — parameter buffers alias across iterations, so their
        device layouts stay fixed and no cross-program relayout copies
        happen (the silent >10x tax of chaining separately-compiled
        programs). Falls back (returns False) when bulking is off or the
        rule is ineligible; the caller then uses `fused_update_all`.
        """
        from ..ops import segment as _seg
        from ..ops.registry import invoke
        import jax
        import jax.tree_util as jtu
        from ..ndarray import NDArray

        if not _seg.enabled():
            return False
        if not self._fused_safe or not items or self.multi_precision:
            return False
        if (type(self).update is not Optimizer.update
                or type(self).update_multi_precision
                is not Optimizer.update_multi_precision):
            return False
        for index, _, _, _ in items:
            self._update_count(index)
        indices = tuple(i for i, _, _, _ in items)
        takes_t = type(self)._step_takes_t()
        lrs = _np.asarray([self._get_lr(i) for i in indices], _np.float32)
        wds = _np.asarray([self._get_wd(i) for i in indices], _np.float32)
        ts = (_np.asarray([self._index_update_count[i] for i in indices],
                          _np.float32) if takes_t else None)

        def _raw(x):
            d = x._data
            return d  # concrete buffer or pending _LazyVal — both fine

        nw = len(items)
        sbuf_trees = [_state_tree_raw(s) for _, _, _, s in items]
        flat_s, sdef = jtu.tree_flatten(sbuf_trees)
        ns = len(flat_s)
        opt = self

        def f(*flat):
            from ..ndarray import _wrap
            wb = flat[:nw]
            gb = flat[nw:2 * nw]
            sb = jtu.tree_unflatten(sdef, list(flat[2 * nw:2 * nw + ns]))
            lr_args = flat[2 * nw + ns]
            wd_args = flat[2 * nw + ns + 1]
            rescale = flat[2 * nw + ns + 2]
            t_args = flat[2 * nw + ns + 3] if takes_t else None
            prev = opt.rescale_grad
            # deliberate trace-time swap (exposes the traced rescale to
            # step_one's _preprocess), restored in finally below
            opt.rescale_grad = rescale  # mxlint: disable=trace-closure-mutation
            try:
                new_w, new_s = [], []
                for k, idx in enumerate(indices):
                    w = _wrap(wb[k])
                    g = _wrap(gb[k])
                    st = _wrap_state(sb[k])
                    if takes_t:
                        opt.step_one(idx, w, g, st, lr_args[k], wd_args[k],
                                     t=t_args[k])
                    else:
                        opt.step_one(idx, w, g, st, lr_args[k], wd_args[k])
                    new_w.append(w._arr)
                    new_s.append(_state_bufs(st))
            finally:
                opt.rescale_grad = prev  # mxlint: disable=trace-closure-mutation -- restore of the trace-time swap
            return tuple(new_w) + tuple(jtu.tree_leaves(new_s))

        key = ("fused_all_bulk", self, indices, self.clip_gradient,
               self._hyper_fingerprint(), sdef, takes_t)
        args = (tuple(_raw(w) for _, w, _, _ in items)
                + tuple(_raw(g) for _, _, g, _ in items)
                + tuple(flat_s)
                + (lrs, wds, _np.float32(self.rescale_grad))
                + ((ts,) if takes_t else ()))
        try:
            outs = invoke(f, args, name="fused_update", multi_out=True,
                          key=key)
        except Exception:
            # roll back the update counts: the fallback path (caller) will
            # re-increment them — without this, every failing step would
            # double-advance num_update/t (wrong bias correction + lr)
            for index in indices:
                self._index_update_count[index] -= 1
            self.num_update = max(self._index_update_count.values(),
                                  default=0)
            return False
        new_w = outs[:nw]
        new_s_leaves = outs[nw:]
        for (idx, w_nd, g_nd, state), wv in zip(items, new_w):
            w_nd._set_arr(wv._data)
        leaf_vals = [o._data for o in new_s_leaves]
        new_trees = jtu.tree_unflatten(sdef, leaf_vals)
        for (_, _, _, state), tree in zip(items, new_trees):
            _state_adopt(state, tree)
        # the optimizer step is the natural iteration boundary: flush so
        # each loop iteration is ONE stable program (async dispatch — the
        # host does not block), instead of accumulating iterations until
        # the op cap forces a monster compile
        _seg.flush_all()
        return True

    def fused_update_all(self, items):
        """Multi-tensor fused update (≙ multi_sgd/multi_mp_sgd ops,
        optimizer_op.cc:353-493, preloaded_multi_sgd.cc): ONE jitted XLA
        computation updates every parameter. `items` is a list of
        (index, weight, grad, state) with all weights initialized.

        lr/wd enter as traced scalars so lr schedules never retrace; the
        cache is keyed on everything the trace bakes in (hyperparams,
        rescale/clip, item count).

        Returns True if the fused path ran; False → caller falls back to
        per-param updates.
        """
        import jax
        if not self._fused_safe or not items or self.multi_precision:
            return False
        # subclasses overriding update()/update_multi_precision() (the MXNet
        # extension point) must keep getting called per-param
        if (type(self).update is not Optimizer.update
                or type(self).update_multi_precision
                is not Optimizer.update_multi_precision):
            return False
        for index, _, _, _ in items:
            self._update_count(index)
        lrs = [_np.float32(self._get_lr(i)) for i, _, _, _ in items]
        wds = [_np.float32(self._get_wd(i)) for i, _, _, _ in items]
        opt = self
        indices = tuple(i for i, _, _, _ in items)
        takes_t = type(self)._step_takes_t()
        ts = ([_np.float32(self._index_update_count[i]) for i in indices]
              if takes_t else None)

        key = ("fused_all", indices, self.clip_gradient,
               self._hyper_fingerprint())
        cached = self._jitted.get(key)
        if cached is None:
            def f(wbufs, gbufs, sbufs, lr_args, wd_args, rescale, t_args):
                # expose the traced rescale to step_one's _preprocess; the
                # inner kernel cache detects the tracer and keys on "traced"
                prev = opt.rescale_grad
                # deliberate trace-time swap, restored in finally below
                opt.rescale_grad = rescale  # mxlint: disable=trace-closure-mutation
                try:
                    new_w, new_s = [], []
                    for k, (idx, wb, gb, sb, lr, wd) in enumerate(zip(
                            indices, wbufs, gbufs, sbufs, lr_args, wd_args)):
                        w = _wrap(wb)
                        g = _wrap(gb)
                        st = _wrap_state(sb)
                        if t_args is not None:
                            opt.step_one(idx, w, g, st, lr, wd, t=t_args[k])
                        else:
                            opt.step_one(idx, w, g, st, lr, wd)
                        new_w.append(w._arr)
                        new_s.append(_state_bufs(st))
                    return new_w, new_s
                finally:
                    opt.rescale_grad = prev  # mxlint: disable=trace-closure-mutation -- restore of the trace-time swap

            from .. import sanitize as _sanitize
            cached = _sanitize.maybe_wrap_donated(
                jax.jit(f, donate_argnums=(0, 2)), (0, 2),
                "optimizer.aggregate_step")
            self._jitted[key] = cached

        wbufs = [w._arr for _, w, _, _ in items]
        gbufs = [g._arr for _, _, g, _ in items]
        sbufs = [_state_bufs(s) for _, _, _, s in items]
        new_w, new_s = cached(wbufs, gbufs, sbufs, lrs, wds,
                              _np.float32(self.rescale_grad), ts)
        for (idx, w_nd, g_nd, state), wb, sb in zip(items, new_w, new_s):
            w_nd._set_arr(wb)
            _state_restore(state, sb)
        return True

    def _kernel(self, name, fn, donate=()):
        # rescale_grad/clip_gradient are closed over by the kernel body, so
        # the compiled fn is only valid for their current values — key the
        # cache on them (Trainer.step rewrites rescale_grad per batch size).
        # Inside a fused trace rescale_grad is a tracer: return the raw fn
        # so it inlines into the enclosing jit — caching a nested jit whose
        # closure captured an outer tracer would leak it across traces.
        rs = self.rescale_grad
        if not isinstance(rs, (int, float)):
            return fn
        key = (name, rs, self.clip_gradient)
        k = self._jitted.get(key)
        if k is None:
            k = _jit_update(fn, donate)
            self._jitted[key] = k
        return k


def _state_bufs(state):
    """Extract raw buffers from a (possibly nested-tuple) NDArray state."""
    if state is None:
        return None
    if isinstance(state, tuple):
        return tuple(_state_bufs(s) for s in state)
    return state._arr


def _wrap_state(bufs):
    from ..ndarray import _wrap
    if bufs is None:
        return None
    if isinstance(bufs, tuple):
        return tuple(_wrap_state(b) for b in bufs)
    return _wrap(bufs)


def _state_restore(state, bufs):
    if state is None:
        return
    if isinstance(state, tuple):
        for s, b in zip(state, bufs):
            _state_restore(s, b)
        return
    state._set_arr(bufs)


def _state_tree_raw(state):
    """Like _state_bufs but lazy-friendly: pending buffers stay pending."""
    if state is None:
        return None
    if isinstance(state, tuple):
        return tuple(_state_tree_raw(s) for s in state)
    return state._data


def _state_adopt(state, tree):
    """Adopt (possibly pending) new buffers into the state NDArrays."""
    if state is None:
        return
    if isinstance(state, tuple):
        for s, b in zip(state, tree):
            _state_adopt(s, b)
        return
    state._set_arr(tree)


# ---------------------------------------------------------------------------
# SGD family
# ---------------------------------------------------------------------------
@register
class SGD(Optimizer):
    """SGD + momentum (≙ optimizer/sgd.py; kernel optimizer_op.cc sgd_update/
    sgd_mom_update)."""

    _fused_safe = True

    def __init__(self, learning_rate=0.01, momentum=0.0, lazy_update=False,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, dtype=weight.dtype)

    def step_one(self, index, weight, grad, state, lr, wd):
        import jax.numpy as jnp

        def k_mom(w, g, mom, lr, wd, momentum):
            g = self._preprocess(g, wd) + wd * w
            mom = momentum * mom - lr * g
            return w + mom, mom

        def k_plain(w, g, lr, wd):
            g = self._preprocess(g, wd) + wd * w
            return w - lr * g

        if state is not None:
            new_w, new_m = self._kernel("mom", k_mom, donate=(0, 2))(
                weight._arr, grad._arr, state._arr,
                _f32(lr), _f32(wd), _f32(self.momentum))
            weight._set_arr(new_w)
            state._set_arr(new_m)
        else:
            weight._set_arr(self._kernel("plain", k_plain, donate=(0,))(
                weight._arr, grad._arr, _f32(lr), _f32(wd)))


@register
class Signum(Optimizer):
    """≙ optimizer/signum.py (signsgd/signum kernels)."""

    _fused_safe = True

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, dtype=weight.dtype)

    def step_one(self, index, weight, grad, state, lr, wd):
        import jax.numpy as jnp

        def k(w, g, mom, lr, wd, momentum, wd_lh):
            g = self._preprocess(g, wd)
            mom = momentum * mom - (1 - momentum) * (g + wd * w)
            w = (1 - lr * wd_lh) * w + lr * jnp.sign(mom)
            return w, mom

        def k_sign(w, g, lr, wd, wd_lh):
            g = self._preprocess(g, wd) + wd * w
            return (1 - lr * wd_lh) * w - lr * jnp.sign(g)

        if state is not None:
            new_w, new_m = self._kernel("signum", k, donate=(0, 2))(
                weight._arr, grad._arr, state._arr, _f32(lr),
                _f32(wd), _f32(self.momentum),
                _f32(self.wd_lh))
            weight._set_arr(new_w)
            state._set_arr(new_m)
        else:
            weight._set_arr(self._kernel("signsgd", k_sign, donate=(0,))(
                weight._arr, grad._arr, _f32(lr), _f32(wd),
                _f32(self.wd_lh)))


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (≙ optimizer/sgld.py)."""

    def __init__(self, learning_rate=0.01, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)

    def step_one(self, index, weight, grad, state, lr, wd):
        import jax
        from .. import random as _random
        key = _random.next_key()

        def k(w, g, key, lr, wd):
            import jax.numpy as jnp
            g = self._preprocess(g, wd) + wd * w
            noise = jax.random.normal(key, w.shape, w.dtype) * jnp.sqrt(lr)
            return w - lr / 2 * g + noise

        weight._set_arr(self._kernel("sgld", k, donate=(0,))(
            weight._arr, grad._arr, key, _f32(lr), _f32(wd)))


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (≙ optimizer/dcasgd.py)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = zeros(weight.shape, dtype=weight.dtype) \
            if self.momentum != 0.0 else None
        prev = weight.copy()
        return (mom, prev)

    def step_one(self, index, weight, grad, state, lr, wd):
        mom, prev = state

        def k(w, g, pw, lr, wd, lamda):
            g = self._preprocess(g, wd) + wd * w
            comp = g + lamda * g * g * (w - pw)
            return w - lr * comp, w

        def k_mom(w, g, m, pw, lr, wd, momentum, lamda):
            g = self._preprocess(g, wd) + wd * w
            comp = g + lamda * g * g * (w - pw)
            m = momentum * m - lr * comp
            return w + m, m, w

        if mom is not None:
            new_w, new_m, new_prev = self._kernel("dcasgd_m", k_mom, donate=(0, 2, 3))(
                weight._arr, grad._arr, mom._arr, prev._arr, _f32(lr),
                _f32(wd), _f32(self.momentum),
                _f32(self.lamda))
            mom._set_arr(new_m)
        else:
            new_w, new_prev = self._kernel("dcasgd", k, donate=(0, 2))(
                weight._arr, grad._arr, prev._arr, _f32(lr),
                _f32(wd), _f32(self.lamda))
        weight._set_arr(new_w)
        prev._set_arr(new_prev)


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (≙ optimizer/nag.py, nag_mom_update)."""

    _fused_safe = True

    def __init__(self, learning_rate=0.01, momentum=0.9, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype=weight.dtype)

    def step_one(self, index, weight, grad, state, lr, wd):
        def k(w, g, mom, lr, wd, momentum):
            g = self._preprocess(g, wd) + wd * w
            mom = momentum * mom + g
            return w - lr * (g + momentum * mom), mom

        new_w, new_m = self._kernel("nag", k, donate=(0, 2))(
            weight._arr, grad._arr, state._arr, _f32(lr),
            _f32(wd), _f32(self.momentum))
        weight._set_arr(new_w)
        state._set_arr(new_m)


# ---------------------------------------------------------------------------
# adaptive family
# ---------------------------------------------------------------------------
@register
class AdaGrad(Optimizer):
    _fused_safe = True

    def __init__(self, learning_rate=0.01, epsilon=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype=weight.dtype)

    def step_one(self, index, weight, grad, state, lr, wd):
        import jax.numpy as jnp

        def k(w, g, hist, lr, wd, eps):
            g = self._preprocess(g, wd) + wd * w
            hist = hist + g * g
            return w - lr * g / (jnp.sqrt(hist) + eps), hist

        new_w, new_h = self._kernel("adagrad", k, donate=(0, 2))(
            weight._arr, grad._arr, state._arr, _f32(lr),
            _f32(wd), _f32(self.epsilon))
        weight._set_arr(new_w)
        state._set_arr(new_h)


@register
class AdaDelta(Optimizer):
    _fused_safe = True

    def __init__(self, learning_rate=1.0, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype),
                zeros(weight.shape, dtype=weight.dtype))

    def step_one(self, index, weight, grad, state, lr, wd):
        import jax.numpy as jnp
        acc_g, acc_delta = state

        def k(w, g, ag, ad, lr, wd, rho, eps):
            g = self._preprocess(g, wd) + wd * w
            ag = rho * ag + (1 - rho) * g * g
            delta = jnp.sqrt(ad + eps) / jnp.sqrt(ag + eps) * g
            ad = rho * ad + (1 - rho) * delta * delta
            return w - lr * delta, ag, ad

        new_w, new_ag, new_ad = self._kernel("adadelta", k, donate=(0, 2, 3))(
            weight._arr, grad._arr, acc_g._arr, acc_delta._arr,
            _f32(lr), _f32(wd), _f32(self.rho),
            _f32(self.epsilon))
        weight._set_arr(new_w)
        acc_g._set_arr(new_ag)
        acc_delta._set_arr(new_ad)


class _AdamBase(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype),
                zeros(weight.shape, dtype=weight.dtype))


@register
class Adam(_AdamBase):
    """≙ optimizer/adam.py (adam_update kernel, optimizer_op.cc)."""

    _fused_safe = True

    def step_one(self, index, weight, grad, state, lr, wd, t=None):
        import jax.numpy as jnp
        mean, var = state
        if t is None:
            t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr_t = lr * (coef2 ** 0.5) / coef1

        def k(w, g, m, v, lr, wd, b1, b2, eps):
            g = self._preprocess(g, wd) + wd * w
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            return w - lr * m / (jnp.sqrt(v) + eps), m, v

        new_w, new_m, new_v = self._kernel("adam", k, donate=(0, 2, 3))(
            weight._arr, grad._arr, mean._arr, var._arr, _f32(lr_t),
            _f32(wd), _f32(self.beta1), _f32(self.beta2),
            _f32(self.epsilon))
        weight._set_arr(new_w)
        mean._set_arr(new_m)
        var._set_arr(new_v)


@register
class AdamW(_AdamBase):
    """Decoupled weight decay (≙ contrib/adamw.cc multi_adamw)."""

    _fused_safe = True

    def step_one(self, index, weight, grad, state, lr, wd, t=None):
        import jax.numpy as jnp
        mean, var = state
        if t is None:
            t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr_t = lr * (coef2 ** 0.5) / coef1

        def k(w, g, m, v, lr, base_lr, wd, b1, b2, eps):
            g = self._preprocess(g, 0.0)
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            return w - lr * m / (jnp.sqrt(v) + eps) - base_lr * wd * w, m, v

        new_w, new_m, new_v = self._kernel("adamw", k, donate=(0, 2, 3))(
            weight._arr, grad._arr, mean._arr, var._arr, _f32(lr_t),
            _f32(lr), _f32(wd), _f32(self.beta1),
            _f32(self.beta2), _f32(self.epsilon))
        weight._set_arr(new_w)
        mean._set_arr(new_m)
        var._set_arr(new_v)


@register
class Adamax(_AdamBase):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, beta1=beta1, beta2=beta2,
                         **kwargs)

    def step_one(self, index, weight, grad, state, lr, wd, t=None):
        import jax.numpy as jnp
        mean, u = state
        if t is None:
            t = self._index_update_count[index]
        lr_t = lr / (1.0 - self.beta1 ** t)

        def k(w, g, m, u, lr, wd, b1, b2, eps):
            g = self._preprocess(g, wd) + wd * w
            m = b1 * m + (1 - b1) * g
            u = jnp.maximum(b2 * u, jnp.abs(g))
            return w - lr * m / (u + eps), m, u

        new_w, new_m, new_u = self._kernel("adamax", k, donate=(0, 2, 3))(
            weight._arr, grad._arr, mean._arr, u._arr, _f32(lr_t),
            _f32(wd), _f32(self.beta1), _f32(self.beta2),
            _f32(self.epsilon))
        weight._set_arr(new_w)
        mean._set_arr(new_m)
        u._set_arr(new_u)


@register
class Nadam(_AdamBase):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, beta1=beta1, beta2=beta2,
                         epsilon=epsilon, **kwargs)
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def step_one(self, index, weight, grad, state, lr, wd, t=None):
        import jax.numpy as jnp
        mean, var = state
        if t is None:
            t = self._index_update_count[index]
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1

        def k(w, g, m, v, lr, wd, b1, b2, eps, mt, ms, msn, t):
            g = self._preprocess(g, wd) + wd * w
            g_prime = g / (1.0 - ms)
            m = b1 * m + (1 - b1) * g
            m_prime = m / (1.0 - msn)
            v = b2 * v + (1 - b2) * g * g
            v_prime = v / (1.0 - b2 ** t)
            m_bar = (1.0 - mt) * g_prime + mt * m_prime
            return w - lr * m_bar / (jnp.sqrt(v_prime) + eps), m, v

        new_w, new_m, new_v = self._kernel("nadam", k, donate=(0, 2, 3))(
            weight._arr, grad._arr, mean._arr, var._arr, _f32(lr),
            _f32(wd), _f32(self.beta1), _f32(self.beta2),
            _f32(self.epsilon), _f32(momentum_t),
            _f32(self.m_schedule), _f32(m_schedule_next),
            _f32(t))
        weight._set_arr(new_w)
        mean._set_arr(new_m)
        var._set_arr(new_v)


@register
class AdaBelief(_AdamBase):
    """≙ contrib/adabelief.cc."""

    _fused_safe = True

    def step_one(self, index, weight, grad, state, lr, wd, t=None):
        import jax.numpy as jnp
        mean, var = state
        if t is None:
            t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr_t = lr * (coef2 ** 0.5) / coef1

        def k(w, g, m, v, lr, wd, b1, b2, eps):
            g = self._preprocess(g, wd) + wd * w
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * (g - m) * (g - m) + eps
            return w - lr * m / (jnp.sqrt(v) + eps), m, v

        new_w, new_m, new_v = self._kernel("adabelief", k, donate=(0, 2, 3))(
            weight._arr, grad._arr, mean._arr, var._arr, _f32(lr_t),
            _f32(wd), _f32(self.beta1), _f32(self.beta2),
            _f32(self.epsilon))
        weight._set_arr(new_w)
        mean._set_arr(new_m)
        var._set_arr(new_v)


@register
class FTML(Optimizer):
    """≙ optimizer/ftml.py (ftml_update kernel)."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype),  # d
                zeros(weight.shape, dtype=weight.dtype),  # v
                zeros(weight.shape, dtype=weight.dtype))  # z

    def step_one(self, index, weight, grad, state, lr, wd, t=None):
        import jax.numpy as jnp
        d, v, z = state
        if t is None:
            t = self._index_update_count[index]

        def k(w, g, d, v, z, lr, wd, b1, b2, eps, t):
            g = self._preprocess(g, wd) + wd * w
            v = b2 * v + (1 - b2) * g * g
            d_t = (1 - b1 ** t) / lr * (jnp.sqrt(v / (1 - b2 ** t)) + eps)
            sigma = d_t - b1 * d
            z = b1 * z + (1 - b1) * g - sigma * w
            return -z / d_t, d_t, v, z

        new_w, new_d, new_v, new_z = self._kernel("ftml", k, donate=(0, 2, 3, 4))(
            weight._arr, grad._arr, d._arr, v._arr, z._arr, _f32(lr),
            _f32(wd), _f32(self.beta1), _f32(self.beta2),
            _f32(self.epsilon), _f32(t))
        weight._set_arr(new_w)
        d._set_arr(new_d)
        v._set_arr(new_v)
        z._set_arr(new_z)


@register
class FTRL(Optimizer):
    """≙ optimizer/ftrl.py (ftrl_update kernel)."""

    _fused_safe = True

    def __init__(self, learning_rate=0.1, lamda1=0.01, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype),  # z
                zeros(weight.shape, dtype=weight.dtype))  # n

    def step_one(self, index, weight, grad, state, lr, wd):
        import jax.numpy as jnp
        z, n = state

        def k(w, g, z, n, lr, wd, lamda1, beta):
            g = self._preprocess(g, wd)
            sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / lr
            z = z + g - sigma * w
            n = n + g * g
            w = ((jnp.sign(z) * lamda1 - z)
                 / ((beta + jnp.sqrt(n)) / lr + wd)
                 * (jnp.abs(z) > lamda1))
            return w, z, n

        new_w, new_z, new_n = self._kernel("ftrl", k, donate=(0, 2, 3))(
            weight._arr, grad._arr, z._arr, n._arr, _f32(lr),
            _f32(wd), _f32(self.lamda1), _f32(self.beta))
        weight._set_arr(new_w)
        z._set_arr(new_z)
        n._set_arr(new_n)


@register
class RMSProp(Optimizer):
    """≙ optimizer/rmsprop.py (rmsprop_update / rmspropalex_update)."""

    _fused_safe = True

    def __init__(self, learning_rate=0.001, rho=0.9, momentum=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho = rho
        self.momentum = momentum
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, dtype=weight.dtype),  # n
                    zeros(weight.shape, dtype=weight.dtype),  # g
                    zeros(weight.shape, dtype=weight.dtype))  # delta
        return (zeros(weight.shape, dtype=weight.dtype),)

    def step_one(self, index, weight, grad, state, lr, wd):
        import jax.numpy as jnp

        if not self.centered:
            (n,) = state

            def k(w, g, n, lr, wd, rho, eps):
                g = self._preprocess(g, wd) + wd * w
                n = rho * n + (1 - rho) * g * g
                w = w - lr * g / (jnp.sqrt(n) + eps)
                return w, n

            new_w, new_n = self._kernel("rmsprop", k, donate=(0, 2))(
                weight._arr, grad._arr, n._arr, _f32(lr),
                _f32(wd), _f32(self.rho),
                _f32(self.epsilon))
            weight._set_arr(new_w)
            n._set_arr(new_n)
        else:
            n, gbar, delta = state

            def k(w, g, n, gb, d, lr, wd, rho, mom, eps):
                g = self._preprocess(g, wd) + wd * w
                n = rho * n + (1 - rho) * g * g
                gb = rho * gb + (1 - rho) * g
                d = mom * d - lr * g / (jnp.sqrt(n - gb * gb) + eps)
                return w + d, n, gb, d

            new_w, new_n, new_g, new_d = self._kernel("rmspropalex", k, donate=(0, 2, 3, 4))(
                weight._arr, grad._arr, n._arr, gbar._arr, delta._arr,
                _f32(lr), _f32(wd), _f32(self.rho),
                _f32(self.momentum), _f32(self.epsilon))
            if self.clip_weights:
                new_w = jnp.clip(new_w, -self.clip_weights, self.clip_weights)
            weight._set_arr(new_w)
            n._set_arr(new_n)
            gbar._set_arr(new_g)
            delta._set_arr(new_d)


# ---------------------------------------------------------------------------
# layer-wise adaptive (large-batch) family
# ---------------------------------------------------------------------------
@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (≙ optimizer/lars.py)."""

    _fused_safe = True

    def __init__(self, learning_rate=0.1, momentum=0.9, eta=0.001,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, dtype=weight.dtype)

    def step_one(self, index, weight, grad, state, lr, wd):
        import jax.numpy as jnp

        def k(w, g, mom, lr, wd, momentum, eta, eps):
            g = self._preprocess(g, wd)
            w_norm = jnp.sqrt(jnp.sum(w * w))
            g_norm = jnp.sqrt(jnp.sum(g * g))
            trust = jnp.where((w_norm > 0) & (g_norm > 0),
                              eta * w_norm / (g_norm + wd * w_norm + eps),
                              1.0)
            scaled_lr = lr * trust
            g = g + wd * w
            mom = momentum * mom + scaled_lr * g
            return w - mom, mom

        mom = state if state is not None else zeros(weight.shape,
                                                    dtype=weight.dtype)
        new_w, new_m = self._kernel("lars", k, donate=(0, 2))(
            weight._arr, grad._arr, mom._arr, _f32(lr),
            _f32(wd), _f32(self.momentum), _f32(self.eta),
            _f32(self.epsilon))
        weight._set_arr(new_w)
        if state is not None:
            state._set_arr(new_m)


@register
class LAMB(_AdamBase):
    """≙ optimizer/lamb.py (lamb_update_phase1/2, contrib/multi_lamb.cc)."""

    _fused_safe = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, beta1=beta1, beta2=beta2,
                         epsilon=epsilon, **kwargs)
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def step_one(self, index, weight, grad, state, lr, wd, t=None):
        import jax.numpy as jnp
        mean, var = state
        if t is None:
            t = self._index_update_count[index]

        def k(w, g, m, v, lr, wd, b1, b2, eps, t):
            g = self._preprocess(g, wd)
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            if self.bias_correction:
                mhat = m / (1 - b1 ** t)
                vhat = v / (1 - b2 ** t)
            else:
                mhat, vhat = m, v
            r = mhat / (jnp.sqrt(vhat) + eps) + wd * w
            w_norm = jnp.sqrt(jnp.sum(w * w))
            r_norm = jnp.sqrt(jnp.sum(r * r))
            if self.lower_bound is not None:
                w_norm = jnp.maximum(w_norm, self.lower_bound)
            if self.upper_bound is not None:
                w_norm = jnp.minimum(w_norm, self.upper_bound)
            ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
            return w - lr * ratio * r, m, v

        new_w, new_m, new_v = self._kernel("lamb", k, donate=(0, 2, 3))(
            weight._arr, grad._arr, mean._arr, var._arr, _f32(lr),
            _f32(wd), _f32(self.beta1), _f32(self.beta2),
            _f32(self.epsilon), _f32(t))
        weight._set_arr(new_w)
        mean._set_arr(new_m)
        var._set_arr(new_v)


@register
class LANS(LAMB):
    """Nesterov LAMB (≙ contrib/multi_lans.cc)."""

    _fused_safe = True

    def step_one(self, index, weight, grad, state, lr, wd, t=None):
        import jax.numpy as jnp
        mean, var = state
        if t is None:
            t = self._index_update_count[index]

        def k(w, g, m, v, lr, wd, b1, b2, eps, t):
            g = self._preprocess(g, wd)
            # grad normalization (LANS)
            g_norm = jnp.sqrt(jnp.sum(g * g))
            g = jnp.where(g_norm > 0, g / g_norm, g)
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            mhat = m / (1 - b1 ** t)
            vhat = v / (1 - b2 ** t)
            rm = mhat / (jnp.sqrt(vhat) + eps) + wd * w
            rg = g / (jnp.sqrt(vhat) + eps) + wd * w
            w_norm = jnp.sqrt(jnp.sum(w * w))
            rm_norm = jnp.sqrt(jnp.sum(rm * rm))
            rg_norm = jnp.sqrt(jnp.sum(rg * rg))
            ratio_m = jnp.where((w_norm > 0) & (rm_norm > 0),
                                w_norm / rm_norm, 1.0)
            ratio_g = jnp.where((w_norm > 0) & (rg_norm > 0),
                                w_norm / rg_norm, 1.0)
            w = w - lr * (b1 * ratio_m * rm + (1 - b1) * ratio_g * rg)
            return w, m, v

        new_w, new_m, new_v = self._kernel("lans", k, donate=(0, 2, 3))(
            weight._arr, grad._arr, mean._arr, var._arr, _f32(lr),
            _f32(wd), _f32(self.beta1), _f32(self.beta2),
            _f32(self.epsilon), _f32(t))
        weight._set_arr(new_w)
        mean._set_arr(new_m)
        var._set_arr(new_v)


# ---------------------------------------------------------------------------
# Updater (≙ python/mxnet/optimizer/updater.py — state serialization for
# kvstore-side optimizers)
# ---------------------------------------------------------------------------
class Updater:
    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if not isinstance(index, (list, tuple)):
            index, grad, weight = [index], [grad], [weight]
        for i, g, w in zip(index, grad, weight):
            if i not in self.states:
                self.states[i] = self.optimizer.create_state_multi_precision(i, w)
            self.optimizer.update_multi_precision(i, w, g, self.states[i])

    def get_states(self, dump_optimizer=False):
        import pickle
        state = {}
        for i, s in self.states.items():
            state[i] = _state_to_numpy(s)
        payload = (state, self.optimizer) if dump_optimizer else state
        return pickle.dumps(payload)

    def set_states(self, states):
        import pickle
        data = pickle.loads(states)
        if isinstance(data, tuple):
            data, self.optimizer = data
        from ..ndarray import array
        self.states = {i: _state_from_numpy(s) for i, s in data.items()}


def _state_to_numpy(s):
    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(_state_to_numpy(x) for x in s)
    return s.asnumpy()


def _state_from_numpy(s):
    from ..ndarray import array
    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(_state_from_numpy(x) for x in s)
    return array(s)


def get_updater(optimizer):
    return Updater(optimizer)
