"""mx.optimizer.sharded — ZeRO-1/2-style optimizer-state sharding over the
dp mesh axis.

The reference's parameter-server KVStore split optimizer-update work across
server shards (PAPER.md layer 0, ps-lite: each server owned a key range and
ran the updater for it). The SPMD-era equivalent is ZeRO: every data-parallel
replica owns ``1/dp`` of the optimizer state (and a master copy of its slice
of every parameter), updates only that shard, and the fresh parameters are
re-assembled with an all-gather. Memory for moments drops ~linearly with dp;
the reduce-scatter that feeds the shard update moves the same bytes an
allreduce would, split across ranks.

Layout: every parameter ``p`` of ``numel`` elements is flattened, padded to a
multiple of ``dp``, and viewed as a ``(dp, L)`` array with
``NamedSharding(mesh, P(axis, None))`` — row ``r`` (the shard rank ``r``
owns) lives on device ``r`` of the dp axis. The same ``(dp, L)`` layout holds
the fp32 master copy (``wshard``) and every optimizer-state leaf, so the
shard update is a pure elementwise program XLA partitions with zero
collectives. The layout survives mesh-size changes: `repartition` re-slices
the true ``numel`` elements onto a new dp (see `checkpoint.Repartition` for
the restore-time form).

`ShardedOptimizer` reuses the base optimizer's `step_one` rule exactly the
way `Optimizer.fused_update_all` does — wrapped buffers inside one jitted,
donated program with lr/wd (and the Adam family's bias-correction ``t``)
entering as traced scalars — so every `_fused_safe` rule (SGD, Adam, AdamW,
LAMB, ...) shards without a parallel reimplementation.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from . import Optimizer, create as _create_opt, _state_bufs, _wrap_state

__all__ = ["ShardedOptimizer", "shard_len", "to_shards", "from_shards",
           "repartition", "state_layout", "layout_spec_tree"]


def _mem_register(tree):
    """Census attribution (mx.inspect.memory): master/moment shards are
    the ZeRO trainer's resident set. Must never break the optimizer."""
    try:
        from ..inspect import memory as _mem
        _mem.register(tree, owner="optimizer_shards")
    except Exception:
        pass
    return tree


def shard_len(numel, dp):
    """Per-rank shard length: ceil(numel / dp) (the tail rank is padded)."""
    if dp < 1:
        raise MXNetError(f"dp must be >= 1, got {dp}")
    return -(-int(numel) // int(dp))


def to_shards(arr, dp):
    """Flatten + zero-pad a host/jax array to the (dp, L) shard view."""
    flat = _np.asarray(arr).reshape(-1)
    L = shard_len(flat.size, dp)
    if flat.size < dp * L:
        flat = _np.concatenate(
            [flat, _np.zeros(dp * L - flat.size, flat.dtype)])
    return flat.reshape(dp, L)


def from_shards(arr2d, numel, shape=None):
    """Invert to_shards: drop padding, restore the original shape."""
    flat = _np.asarray(arr2d).reshape(-1)[:int(numel)]
    return flat if shape is None else flat.reshape(shape)


def repartition(arr2d, numel, new_dp):
    """Re-slice a (dp_old, L_old) shard view onto new_dp ranks — the host
    half of the elastic-restart recipe (dtype-preserving; padding is
    recomputed, so uneven counts like dp=3 -> 2 round-trip exactly)."""
    return to_shards(from_shards(arr2d, numel), new_dp)


def state_layout(state):
    """JSON-safe structure marker for a (possibly nested-tuple) state:
    None stays None, every array leaf becomes the string "shard". Used by
    the checkpoint manifest so a resume can rebuild spec trees without
    reconstructing the optimizer first."""
    if state is None:
        return None
    if isinstance(state, (tuple, list)):
        return [state_layout(s) for s in state]
    return "shard"


def layout_spec_tree(layout, make_leaf):
    """Map a `state_layout` marker structure to a spec pytree, calling
    `make_leaf()` for every "shard" marker. Tuples/lists come back as
    lists — congruent with orbax's restored containers."""
    if layout is None:
        return None
    if isinstance(layout, (tuple, list)):
        return [layout_spec_tree(l, make_leaf) for l in layout]
    return make_leaf()


class ShardedOptimizer:
    """Wrap a base `Optimizer` so its states live sharded over the dp axis.

    ::

        sopt = ShardedOptimizer("sgd", mesh, momentum=0.9, learning_rate=0.1)
        wshard, meta = sopt.shard_params(params)    # (dp, L) master copies
        states = sopt.init_states(wshard)           # (dp, L) moments
        wshard, states = sopt.update(wshard, gshard, states)

    `mesh` is a raw `jax.sharding.Mesh` (or `parallel.Mesh`) with the dp
    axis named `axis`. Only `_fused_safe` rules are supported — the same
    criterion the multi-tensor fused path uses: `step_one` must be
    trace-pure given (lr, wd[, t]).
    """

    def __init__(self, optimizer, mesh, axis="dp", **opt_kwargs):
        base = (optimizer if isinstance(optimizer, Optimizer)
                else _create_opt(optimizer, **opt_kwargs))
        if not type(base)._fused_safe:
            raise MXNetError(
                f"{type(base).__name__} is not _fused_safe: its step_one "
                "carries per-step host state and cannot be shard-jitted")
        if base.multi_precision:
            raise MXNetError("ShardedOptimizer keeps its own fp32 master "
                             "shards; multi_precision must be off")
        self.base = base
        self.jax_mesh = getattr(mesh, "jax_mesh", mesh)
        self.axis = axis
        if axis not in self.jax_mesh.shape:
            raise MXNetError(f"mesh {dict(self.jax_mesh.shape)} has no "
                             f"{axis!r} axis")
        self.dp = int(self.jax_mesh.shape[axis])
        self._update_fn_cache = {}

    # ------------------------------------------------------------------
    def _sharding(self):
        import jax
        from jax.sharding import PartitionSpec as P
        return jax.sharding.NamedSharding(self.jax_mesh, P(self.axis, None))

    def place(self, arr2d):
        """Device-put a (dp, L) host view row-sharded over the dp axis."""
        import jax
        a = _np.asarray(arr2d)
        if a.ndim != 2 or a.shape[0] != self.dp:
            raise MXNetError(f"expected a ({self.dp}, L) shard view, got "
                             f"{a.shape}")
        return _mem_register(jax.device_put(a, self._sharding()))

    def shard_params(self, params):
        """params: dict name -> array. Returns (wshard, meta): the sharded
        fp32-master views and the {name: {numel, shape, dtype}} metadata a
        checkpoint needs to reassemble/repartition them."""
        wshard, meta = {}, {}
        for name, v in params.items():
            a = _np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)
            meta[name] = {"numel": int(a.size), "shape": list(a.shape),
                          "dtype": str(a.dtype)}
            wshard[name] = self.place(to_shards(a, self.dp))
        return wshard, meta

    def init_states(self, wshard):
        """Fresh sharded optimizer states, one (dp, L)-leaved tree per
        param (the base rule's create_state run on the shard view — zeros
        with the shard's shape/dtype, sharded like the master copy)."""
        from ..ndarray import _wrap
        states = {}
        for name, w in wshard.items():
            st = self.base.create_state(name, _wrap(w))
            states[name] = self._place_state(st)
        return states

    def _place_state(self, st):
        if st is None:
            return None
        if isinstance(st, (tuple, list)):
            return tuple(self._place_state(s) for s in st)
        # create_state returns NDArrays; keep raw sharded jax buffers
        import jax
        raw = st._arr if hasattr(st, "_arr") else _np.asarray(st)
        return _mem_register(jax.device_put(raw, self._sharding()))

    # ------------------------------------------------------------------
    def mem_per_replica_bytes(self, wshard, states):
        """Bytes of optimizer state (master shards + moments) ONE replica
        holds — the quantity ZeRO shrinks ~linearly with dp. Measured from
        the actual per-device buffers, not shapes."""
        total = 0
        for leaf in self._leaves(wshard) + self._leaves(states):
            if hasattr(leaf, "addressable_shards"):
                total += int(leaf.addressable_shards[0].data.nbytes)
            else:
                total += int(_np.asarray(leaf).nbytes) // self.dp
        return total

    @staticmethod
    def _leaves(tree):
        out = []

        def walk(x):
            if x is None:
                return
            if isinstance(x, dict):
                for v in x.values():
                    walk(v)
            elif isinstance(x, (tuple, list)):
                for v in x:
                    walk(v)
            else:
                out.append(x)
        walk(tree)
        return out

    # ------------------------------------------------------------------
    def update(self, wshard, gshard, states):
        """One sharded optimizer step: every rank updates its (dp, L) rows.

        ONE jitted program over all params, master-shard and state buffers
        donated (XLA updates in place), lr/wd/t traced so schedules never
        recompile — `fused_update_all`, transposed onto the shard layout.
        Returns (new_wshard, new_states).
        """
        import jax
        import jax.tree_util as jtu

        names = tuple(sorted(wshard))
        base = self.base
        for name in names:
            base._update_count(name)
        takes_t = type(base)._step_takes_t()
        lrs = _np.asarray([base._get_lr(n) for n in names], _np.float32)
        wds = _np.asarray([base._get_wd(n) for n in names], _np.float32)
        ts = (_np.asarray([base._index_update_count[n] for n in names],
                          _np.float32) if takes_t else None)

        sbuf_trees = [states[n] for n in names]
        flat_s, sdef = jtu.tree_flatten(sbuf_trees)
        key = (names, tuple(tuple(wshard[n].shape) for n in names),
               tuple(str(wshard[n].dtype) for n in names), sdef,
               base.clip_gradient, base._hyper_fingerprint(), takes_t)
        fn = self._update_fn_cache.get(key)
        if fn is None:
            fn = self._build_update_fn(names, sdef, len(flat_s), takes_t)
            self._update_fn_cache[key] = fn
        args = ([wshard[n] for n in names] + [gshard[n] for n in names]
                + list(flat_s)
                + [lrs, wds, _np.float32(base.rescale_grad)]
                + ([ts] if takes_t else []))
        outs = fn(*args)
        nw = len(names)
        new_wshard = {n: outs[i] for i, n in enumerate(names)}
        new_leaves = outs[nw:]
        new_trees = jtu.tree_unflatten(sdef, list(new_leaves))
        new_states = {n: self._tuplify(t)
                      for n, t in zip(names, new_trees)}
        # the donated update produced FRESH buffers — re-attribute them
        # (the old entries die with the donated arrays)
        _mem_register(new_wshard)
        _mem_register(new_states)
        return new_wshard, new_states

    @staticmethod
    def _tuplify(t):
        if isinstance(t, list):
            return tuple(ShardedOptimizer._tuplify(x) for x in t)
        if isinstance(t, tuple):
            return tuple(ShardedOptimizer._tuplify(x) for x in t)
        return t

    def _build_update_fn(self, names, sdef, ns, takes_t):
        import jax
        import jax.tree_util as jtu
        from ..ndarray import _wrap

        base = self.base
        nw = len(names)

        def f(*flat):
            wb = flat[:nw]
            gb = flat[nw:2 * nw]
            sb = jtu.tree_unflatten(sdef, list(flat[2 * nw:2 * nw + ns]))
            lr_args = flat[2 * nw + ns]
            wd_args = flat[2 * nw + ns + 1]
            rescale = flat[2 * nw + ns + 2]
            t_args = flat[2 * nw + ns + 3] if takes_t else None
            prev = base.rescale_grad
            # deliberate trace-time swap (exposes the traced rescale to
            # step_one's _preprocess), restored in finally — the same
            # pattern as Optimizer.fused_update_all
            base.rescale_grad = rescale  # mxlint: disable=trace-closure-mutation
            try:
                new_w, new_s = [], []
                for k, name in enumerate(names):
                    w = _wrap(wb[k])
                    g = _wrap(gb[k])
                    st = _wrap_state(self._tuplify(sb[k]))
                    if takes_t:
                        base.step_one(name, w, g, st, lr_args[k],
                                      wd_args[k], t=t_args[k])
                    else:
                        base.step_one(name, w, g, st, lr_args[k],
                                      wd_args[k])
                    new_w.append(w._arr)
                    new_s.append(_state_bufs(st))
            finally:
                base.rescale_grad = prev  # mxlint: disable=trace-closure-mutation -- restore of the trace-time swap
            return tuple(new_w) + tuple(jtu.tree_leaves(new_s))

        donate = tuple(range(nw)) + tuple(range(2 * nw, 2 * nw + ns))
        from .. import sanitize as _sanitize
        return _sanitize.maybe_wrap_donated(
            jax.jit(f, donate_argnums=donate), donate,
            "optimizer.sharded_step")
