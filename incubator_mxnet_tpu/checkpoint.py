"""mx.checkpoint — checkpoint/resume, including sharded distributed saves.

Reference (SURVEY §5.4): NDArray::Save/Load dmlc::Stream format
(src/ndarray/ndarray.cc:1861,1994), Block.save_parameters,
Trainer.save_states — and explicitly NO sharded/distributed format ("each
worker saves identical full copies"). This module keeps those APIs (they
live on ndarray/Block/Trainer) and ADDS the capability the reference lacks:
mesh-sharded checkpoints where each host writes only its shards, restored
with any (possibly different) sharding — backed by orbax (the TPU-ecosystem
checkpoint library), with a plain-npz fallback for host-local state.

All saves are crash-consistent: data is written to a temp path, fsync'd,
then committed with an atomic os.replace; sharded directories additionally
record committed steps in a MANIFEST.json, and `latest_step` only trusts
committed entries — a SIGKILL (or injected IOError, see mx.fault) at any
point during a save can never lose the previous checkpoint.
"""
from __future__ import annotations

import json
import os
import shutil

import numpy as _np

from .base import MXNetError
from . import fault as _fault

__all__ = ["save_checkpoint", "load_checkpoint", "save_sharded",
           "load_sharded", "rescale_sharded", "latest_step", "latest_entry",
           "commit_step", "MANIFEST_NAME", "Repartition"]


class Repartition:
    """`rescale_sharded` spec leaf for ZeRO-style ``(dp, L)`` shard views
    (optimizer/master-copy shards from `optimizer.sharded`): instead of
    resharding the saved shape onto the new mesh — impossible when the dp
    size changed, because the LEADING AXIS of the saved array is the old
    dp — the leaf is restored replicated, its `numel` true elements are
    re-padded and re-sliced onto the target mesh's `axis` size, and the
    result lands sharded ``P(axis, None)``. Dtype-preserving; uneven
    counts (dp=3 -> 2) round-trip exactly because padding is recomputed."""

    __slots__ = ("numel", "axis")

    def __init__(self, numel, axis="dp"):
        self.numel = int(numel)
        self.axis = axis

    def __repr__(self):
        return f"Repartition(numel={self.numel}, axis={self.axis!r})"


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    else:
        out[prefix.rstrip("/")] = tree
    return out


def _norm_npz_path(path):
    """np.savez appends '.npz' to extension-less paths; normalize so save's
    return value and load agree on the real filename."""
    return path if path.endswith(".npz") else path + ".npz"


def _encode_key(k):
    """Escape-safe flat-key encoding: every '_' in the original becomes
    '_u' and every '/' becomes '_s', so names containing '__' round-trip."""
    return k.replace("_", "_u").replace("/", "_s")


def _decode_key(k):
    # every '_' in the encoded form starts a 2-char token ('_u' or '_s'),
    # so these sequential replaces cannot misalign
    return k.replace("_s", "/").replace("_u", "_")


def save_checkpoint(path, params, step=None, trainer=None):
    """Host-local checkpoint: params (dict of NDArray/array, or a Block) +
    optional trainer state (≙ the reference's save pattern, one file).

    Crash-consistent: the npz (and the `.trainer` sidecar) are written to a
    temp file and committed with an atomic rename, so a partially-written
    checkpoint can never shadow a good one."""
    from .ndarray import NDArray
    if hasattr(params, "collect_params"):  # a Block
        params = {k: p.data() for k, p in params.collect_params().items()
                  if p._data is not None}
    payload = {}
    for k, v in _flatten(params).items():
        payload[_encode_key(k)] = (
            v.asnumpy() if isinstance(v, NDArray) else _np.asarray(v))
    path = _norm_npz_path(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with _fault.atomic_output(path) as f:
        _np.savez(f, __step__=_np.asarray(step if step is not None else -1),
                  __fmt__=_np.asarray(2),  # v2: escape-safe key encoding
                  **payload)
        # after the temp write, before the rename commit — the real
        # crash window the atomic protocol must survive
        _fault.inject("checkpoint.save")
    if trainer is not None:
        trainer.save_states(path + ".trainer")
    return path


def load_checkpoint(path, net=None, trainer=None, device=None,
                    as_numpy=False):
    """Load a host-local checkpoint; returns (params_dict, step).

    as_numpy=True returns raw numpy arrays instead of NDArrays — bit-exact
    for every dtype (NDArray creation would truncate float64 to the
    device-native float32), which crash-resume parity depends on."""
    from .ndarray import array
    _fault.inject("checkpoint.load")
    # try the npz-normalized name first (what save_checkpoint writes), then
    # the raw name (extension-less files from other tooling)
    candidates = [path] if path.endswith(".npz") \
        else [_norm_npz_path(path), path]
    found = next((c for c in candidates if os.path.exists(c)), None)
    if found is None:
        raise MXNetError(f"no checkpoint at {path!r}; tried "
                         + ", ".join(repr(c) for c in candidates))
    with _np.load(found, allow_pickle=False) as f:
        step = int(f["__step__"])
        # v1 files (no __fmt__) used a lossy '/'->'__' mapping; decode them
        # with the legacy rule so their keys aren't silently corrupted
        fmt = int(f["__fmt__"]) if "__fmt__" in f.files else 1
        decode = _decode_key if fmt >= 2 else (lambda k: k.replace("__", "/"))
        meta = ("__step__", "__fmt__")
        params = {decode(k): (f[k].copy() if as_numpy
                              else array(f[k], device=device))
                  for k in f.files if k not in meta}
    if net is not None:
        flat = {k.replace("/", "."): v for k, v in params.items()}
        own = net.collect_params()
        for name, p in own.items():
            if name in flat:
                p.shape = flat[name].shape
                p.set_data(flat[name])
    if trainer is not None:
        # v1 saves wrote trainer state next to the un-normalized path
        for tp in (found + ".trainer", path + ".trainer"):
            if os.path.exists(tp):
                trainer.load_states(tp)
                break
    return params, (step if step >= 0 else None)


# ---------------------------------------------------------------------------
# sharded (multi-host) checkpoints — capability beyond the reference
# ---------------------------------------------------------------------------
def _ocp():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except ImportError:
        return None


MANIFEST_NAME = "MANIFEST.json"


def _read_manifest(directory):
    """The committed-step manifest, or None when the directory predates the
    commit protocol (legacy layout: bare step-numbered subdirs)."""
    mpath = os.path.join(directory, MANIFEST_NAME)
    try:
        with open(mpath) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _write_manifest(directory, manifest):
    with _fault.atomic_output(os.path.join(directory, MANIFEST_NAME),
                              mode="w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)


def _remove_entry_payload(directory, entry):
    target = os.path.join(directory, entry.get("path") or str(entry["step"]))
    try:
        if os.path.isdir(target):
            shutil.rmtree(target)
        elif os.path.exists(target):
            os.remove(target)
        sidecar = target + ".trainer"
        if os.path.exists(sidecar):
            os.remove(sidecar)
    except OSError:
        pass  # retention GC is best-effort; the manifest entry is gone


def commit_step(directory, step, kind="sharded", path=None, keep_last=None,
                extra=None):
    """Record `step` as COMMITTED in the directory manifest (atomically),
    then apply the `keep_last` retention policy: entries beyond the newest
    N are dropped from the manifest first and their payloads deleted after,
    so a crash mid-GC can only leave orphans, never a manifest pointing at
    deleted data. `extra` (JSON-safe dict) rides on the entry — run
    counters, RNG state, elastic shard metadata — and commits atomically
    WITH the step, so a resume sees counters exactly as of the restored
    checkpoint, never newer. Returns the manifest."""
    directory = os.path.abspath(directory)
    _gc_partials(directory)  # orphans from saves that died pre-commit
    manifest = _read_manifest(directory) or {"version": 1, "committed": []}
    entries = [e for e in manifest["committed"] if e["step"] != step]
    entry = {"step": int(step), "kind": kind, "path": path or str(step)}
    if extra is not None:
        entry["extra"] = extra
    entries.append(entry)
    entries.sort(key=lambda e: e["step"])
    evicted = []
    if keep_last is not None and keep_last > 0 and len(entries) > keep_last:
        evicted = entries[:-keep_last]
        entries = entries[-keep_last:]
    manifest["committed"] = entries
    _write_manifest(directory, manifest)
    for e in evicted:
        _remove_entry_payload(directory, e)
    return manifest


def _gc_partials(directory):
    """Remove orphaned partial saves a crashed writer left: `.tmp-*` scratch
    trees (sharded saves) and `.<name>*.tmp` files (atomic_output temps)."""
    try:
        names = os.listdir(directory)
    except OSError:
        return
    for name in names:
        if name.startswith(".tmp-") or (name.startswith(".")
                                        and name.endswith(".tmp")):
            target = os.path.join(directory, name)
            try:
                if os.path.isdir(target):
                    shutil.rmtree(target)
                else:
                    os.remove(target)
            except OSError:
                pass


def _is_proc0():
    try:
        import jax
        return jax.process_index() == 0
    except Exception:
        return True


def save_sharded(directory, tree, step=0, keep_last=None, extra=None):
    """Save a pytree of (possibly mesh-sharded) jax arrays; each host writes
    its own shards (orbax). Use for pjit/SPMD training state.

    Crash-consistent commit protocol: shards stream into a `.tmp-` scratch
    dir, which is atomically renamed to the step dir and only then recorded
    in MANIFEST.json — `latest_step` never sees a partial save. `keep_last=N`
    retains only the newest N committed steps."""
    ocp = _ocp()
    if ocp is None:
        raise MXNetError("orbax is unavailable; use save_checkpoint for "
                         "host-local state")
    from .ndarray import NDArray
    import jax.tree_util as jtu
    tree = jtu.tree_map(
        lambda v: v._arr if isinstance(v, NDArray) else v, tree,
        is_leaf=lambda v: isinstance(v, NDArray))
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    proc0 = _is_proc0()
    if proc0:
        _gc_partials(directory)
    path = os.path.join(directory, str(step))
    tmp = os.path.join(directory, f".tmp-{step}")
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(tmp, tree, force=True)
    _fault.inject("checkpoint.save_sharded")
    if proc0:
        # commit: rename the finished scratch dir over the step dir, fsync
        # the parent, then record the step in the manifest — in that order,
        # so every manifest entry always points at complete data
        if os.path.isdir(path):
            shutil.rmtree(path)
        os.replace(tmp, path)
        _fault.fsync_dir(directory)
        commit_step(directory, step, kind="sharded", keep_last=keep_last,
                    extra=extra)
    return path


def _resolve_step(directory, step):
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise MXNetError(f"no checkpoints under {directory}")
    return step, os.path.join(os.path.abspath(directory), str(step))


def load_sharded(directory, step=None, target=None):
    """Restore a sharded checkpoint (optionally resharded onto `target`'s
    shardings when a target pytree of ShapeDtypeStruct/arrays is given)."""
    ocp = _ocp()
    if ocp is None:
        raise MXNetError("orbax is unavailable")
    _fault.inject("checkpoint.load")
    step, path = _resolve_step(directory, step)
    ckptr = ocp.PyTreeCheckpointer()
    if target is not None:
        # modern orbax args API: reshard each leaf onto the target's
        # sharding/dtype as it is read back
        restore_args = ocp.checkpoint_utils.construct_restore_args(target)
        return ckptr.restore(
            path, args=ocp.args.PyTreeRestore(
                item=target, restore_args=restore_args)), step
    return ckptr.restore(path), step


def latest_entry(directory):
    """The newest COMMITTED manifest entry ({step, kind, path}) whose
    payload still exists, or None. Directories without a manifest (legacy
    layout) fall back to scanning step-numbered subdirs."""
    if not os.path.isdir(directory):
        return None
    manifest = _read_manifest(directory)
    if manifest is not None:
        for e in sorted(manifest.get("committed", []),
                        key=lambda e: e["step"], reverse=True):
            if os.path.exists(os.path.join(
                    directory, e.get("path") or str(e["step"]))):
                return e
        return None
    steps = [int(d) for d in os.listdir(directory) if d.isdigit()]
    if not steps:
        return None
    s = max(steps)
    return {"step": s, "kind": "sharded", "path": str(s)}


def latest_step(directory):
    """Newest committed step in a checkpoint directory, or None. Only
    trusts manifest-committed entries — a save that crashed before its
    commit is invisible here."""
    entry = latest_entry(directory)
    return None if entry is None else entry["step"]


def rescale_sharded(directory, mesh, specs, step=None):
    """Elastic restart onto a DIFFERENT mesh (the rescale recipe the
    reference leaves to outside tooling): restore a sharded checkpoint
    saved under one device mesh onto `mesh` — any device count whose axes
    satisfy `specs` — resharding every leaf as it streams in.

    specs: a pytree of jax.sharding.PartitionSpec congruent with the
    saved tree (None leaves mean replicated). Shapes/dtypes come from the
    checkpoint's own metadata, so no model construction is needed before
    restore. A `Repartition(numel, axis)` spec leaf marks a ZeRO-style
    ``(dp, L)`` optimizer/master shard view: it is restored replicated and
    RE-PARTITIONED onto the target mesh's `axis` size (the saved leading
    axis is the OLD dp — a plain reshard cannot change it), landing
    sharded ``P(axis, None)`` with padding recomputed and dtype preserved.
    Returns (tree_of_resharded_arrays, step).
    """
    import jax
    import jax.tree_util as jtu
    from jax.sharding import NamedSharding, PartitionSpec

    ocp = _ocp()
    if ocp is None:
        raise MXNetError("orbax is unavailable")
    step, path = _resolve_step(directory, step)
    meta = ocp.PyTreeCheckpointer().metadata(path)
    # orbax returns StepMetadata wrapping the leaf tree
    meta = getattr(meta, "item_metadata", meta)
    meta = getattr(meta, "tree", meta)

    def build_target(m, spec):
        if isinstance(spec, Repartition):
            # load the old (dp_old, L_old) view replicated; the real
            # re-slice onto the new dp happens after restore
            if spec.axis not in mesh.shape:
                raise MXNetError(
                    f"Repartition axis {spec.axis!r} not in mesh "
                    f"{dict(mesh.shape)}")
            if spec.numel > int(_np.prod(m.shape or (1,))):
                raise MXNetError(
                    f"Repartition numel {spec.numel} exceeds the saved "
                    f"leaf's size {tuple(m.shape)}")
            spec = PartitionSpec()
        elif spec is None:
            spec = PartitionSpec()
        return jax.ShapeDtypeStruct(
            tuple(m.shape), m.dtype,
            sharding=NamedSharding(mesh, spec))

    def apply_repartition(arr, spec):
        if not isinstance(spec, Repartition):
            return arr
        from .optimizer.sharded import repartition
        dp_new = int(mesh.shape[spec.axis])
        view = repartition(_np.asarray(arr), spec.numel, dp_new)
        return jax.device_put(
            view, NamedSharding(mesh, PartitionSpec(spec.axis, None)))

    def fill_missing(m, spec):
        """Dict specs may omit entries (treated as replicated); other
        containers must be congruent with the checkpoint."""
        if isinstance(m, dict):
            if spec is None:
                spec = {}
            if not isinstance(spec, dict):
                raise MXNetError(
                    f"spec {type(spec).__name__} does not match the "
                    "checkpoint's dict at this position")
            unknown = set(spec) - set(m)
            if unknown:
                # a typo'd key would silently leave its real parameter
                # REPLICATED — a memory blowup at restart, not a no-op
                raise MXNetError(
                    f"spec keys {sorted(unknown)} not in the checkpoint "
                    f"(has {sorted(m)})")
            return {k: fill_missing(m[k], spec.get(k)) for k in m}
        if isinstance(m, (list, tuple)):
            if spec is None:
                spec = [None] * len(m)
            if len(spec) != len(m):
                raise MXNetError("spec sequence length does not match "
                                 "the checkpoint")
            out = [fill_missing(mm, ss) for mm, ss in zip(m, spec)]
            # tree_map below needs IDENTICAL treedefs: a tuple node in the
            # checkpoint metadata must stay a tuple in the filled spec
            return tuple(out) if isinstance(m, tuple) else out
        return spec   # leaf: PartitionSpec or None

    specs = fill_missing(meta, specs)
    target = jtu.tree_map(build_target, meta, specs)
    tree, _ = load_sharded(directory, step=step, target=target)
    tree = jtu.tree_map(apply_repartition, tree, specs)
    return tree, step
