"""mx.checkpoint — checkpoint/resume, including sharded distributed saves.

Reference (SURVEY §5.4): NDArray::Save/Load dmlc::Stream format
(src/ndarray/ndarray.cc:1861,1994), Block.save_parameters,
Trainer.save_states — and explicitly NO sharded/distributed format ("each
worker saves identical full copies"). This module keeps those APIs (they
live on ndarray/Block/Trainer) and ADDS the capability the reference lacks:
mesh-sharded checkpoints where each host writes only its shards, restored
with any (possibly different) sharding — backed by orbax (the TPU-ecosystem
checkpoint library), with a plain-npz fallback for host-local state.
"""
from __future__ import annotations

import os

import numpy as _np

from .base import MXNetError

__all__ = ["save_checkpoint", "load_checkpoint", "save_sharded",
           "load_sharded", "latest_step"]


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    else:
        out[prefix.rstrip("/")] = tree
    return out


def save_checkpoint(path, params, step=None, trainer=None):
    """Host-local checkpoint: params (dict of NDArray/array, or a Block) +
    optional trainer state (≙ the reference's save pattern, one file)."""
    from .ndarray import NDArray
    if hasattr(params, "collect_params"):  # a Block
        params = {k: p.data() for k, p in params.collect_params().items()
                  if p._data is not None}
    payload = {}
    for k, v in _flatten(params).items():
        payload[k.replace("/", "__")] = (
            v.asnumpy() if isinstance(v, NDArray) else _np.asarray(v))
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    _np.savez(path, __step__=_np.asarray(step if step is not None else -1),
              **payload)
    if trainer is not None:
        trainer.save_states(path + ".trainer")
    return path


def load_checkpoint(path, net=None, trainer=None, device=None):
    """Load a host-local checkpoint; returns (params_dict, step)."""
    from .ndarray import array
    with _np.load(path, allow_pickle=False) as f:
        step = int(f["__step__"])
        params = {k.replace("__", "/"): array(f[k], device=device)
                  for k in f.files if k != "__step__"}
    if net is not None:
        flat = {k.replace("/", "."): v for k, v in params.items()}
        own = net.collect_params()
        for name, p in own.items():
            if name in flat:
                p.shape = flat[name].shape
                p.set_data(flat[name])
    if trainer is not None and os.path.exists(path + ".trainer"):
        trainer.load_states(path + ".trainer")
    return params, (step if step >= 0 else None)


# ---------------------------------------------------------------------------
# sharded (multi-host) checkpoints — capability beyond the reference
# ---------------------------------------------------------------------------
def _ocp():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except ImportError:
        return None


def save_sharded(directory, tree, step=0):
    """Save a pytree of (possibly mesh-sharded) jax arrays; each host writes
    its own shards (orbax). Use for pjit/SPMD training state."""
    ocp = _ocp()
    if ocp is None:
        raise MXNetError("orbax is unavailable; use save_checkpoint for "
                         "host-local state")
    from .ndarray import NDArray
    import jax.tree_util as jtu
    tree = jtu.tree_map(
        lambda v: v._arr if isinstance(v, NDArray) else v, tree,
        is_leaf=lambda v: isinstance(v, NDArray))
    path = os.path.join(os.path.abspath(directory), str(step))
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, tree, force=True)
    return path


def load_sharded(directory, step=None, target=None):
    """Restore a sharded checkpoint (optionally resharded onto `target`'s
    shardings when a target pytree of ShapeDtypeStruct/arrays is given)."""
    ocp = _ocp()
    if ocp is None:
        raise MXNetError("orbax is unavailable")
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise MXNetError(f"no checkpoints under {directory}")
    path = os.path.join(os.path.abspath(directory), str(step))
    ckptr = ocp.PyTreeCheckpointer()
    if target is not None:
        from orbax.checkpoint import args as ocp_args
        try:
            return ckptr.restore(path, item=target), step
        except TypeError:
            pass
    return ckptr.restore(path), step


def latest_step(directory):
    if not os.path.isdir(directory):
        return None
    steps = [int(d) for d in os.listdir(directory) if d.isdigit()]
    return max(steps) if steps else None
