"""mx.library — extension loading (≙ python/mxnet/library.py MXLoadLib +
include/mxnet/lib_api.h custom-op ABI).

The reference loads a compiled .so implementing the 1.3k-LoC C ABI. The
TPU-native extension unit is a PYTHON module (jax kernels are Python-level;
there is no stable C kernel ABI to target): `load(path)` imports the file
and calls its `register_ops(mx)` hook, which registers custom ops
(mx.operator.register), kvstores (KVStoreBase.register), optimizers
(mx.optimizer.register) or metrics — the same extension points the
reference exposes through lib_api.h.
"""
from __future__ import annotations

import importlib.util
import os

from .base import MXNetError

__all__ = ["load", "load_native"]

_loaded = {}


def load(path, verbose=True):
    """Load an extension module and run its register hook."""
    path = os.path.abspath(path)
    if path in _loaded:
        return _loaded[path]
    if not os.path.exists(path):
        raise MXNetError(f"extension not found: {path}")
    if path.endswith(".so"):
        # native extension: the C-level ABI (≙ MXLoadLib of a lib_api.h
        # library); see load_native for the contract
        return load_native(path, verbose=verbose)
    spec = importlib.util.spec_from_file_location(
        f"mx_ext_{os.path.basename(path).removesuffix('.py')}", path)
    if spec is None or spec.loader is None:
        raise MXNetError(f"not a loadable python extension: {path}")
    mod = importlib.util.module_from_spec(spec)
    import sys
    sys.modules[spec.name] = mod  # required before exec (enables pickling)
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(spec.name, None)
        raise
    if hasattr(mod, "register_ops"):
        import incubator_mxnet_tpu as mx
        mod.register_ops(mx)
    _loaded[path] = mod
    if verbose:
        print(f"loaded extension {path}")
    return mod


# ---------------------------------------------------------------------------
# Native (.so) extension ABI — the C-level counterpart (≙ MXLoadLib +
# include/mxnet/lib_api.h:649-771 CustomOp registration from an external
# shared library). TPU-native contract (original, small, and honest about
# where the code runs): extension ops are HOST kernels over f32 buffers,
# bridged into the compute graph with jax.pure_callback — so a loaded op
# works eagerly, under jit, and inside hybridized blocks alike.
#
# The library must export (C linkage):
#   int  mxtpu_ext_abi_version(void);             // must return 1
#   int  mxtpu_ext_num_ops(void);
#   const char* mxtpu_ext_op_name(int i);
#   // fill out_shape/out_ndim from the input shapes; rc 0 on success
#   // out_shape buffer holds up to 16 dims (MXTPU_MAX_NDIM); rc 0 ok
#   int  mxtpu_ext_infer_shape(const char* op, int n_in,
#                              const int64_t* shapes_flat, const int* ndims,
#                              int64_t* out_shape, int* out_ndim);
#   // compute out (f32, caller-allocated per the inferred shape); rc 0
#   int  mxtpu_ext_compute(const char* op, int n_in, const float** ins,
#                          const int64_t* shapes_flat, const int* ndims,
#                          float* out, const int64_t* out_shape,
#                          int out_ndim);
# ---------------------------------------------------------------------------

_native_loaded = {}


def load_native(path, verbose=True):
    """Load a native extension .so and register its ops (callable through
    mx.npx.<name>, the op registry, and MXImperativeInvoke)."""
    import ctypes

    import numpy as _np

    path = os.path.abspath(path)
    if path in _native_loaded:
        return _native_loaded[path]
    if not os.path.exists(path):
        raise MXNetError(f"extension not found: {path}")
    lib = ctypes.CDLL(path)
    for sym in ("mxtpu_ext_abi_version", "mxtpu_ext_num_ops",
                "mxtpu_ext_op_name", "mxtpu_ext_infer_shape",
                "mxtpu_ext_compute"):
        if not hasattr(lib, sym):
            raise MXNetError(
                f"{path}: missing symbol {sym} (not an mxtpu extension; "
                "see mx.library.load_native docs for the ABI)")
    lib.mxtpu_ext_abi_version.restype = ctypes.c_int
    ver = lib.mxtpu_ext_abi_version()
    if ver != 1:
        raise MXNetError(f"{path}: extension ABI version {ver} != 1")
    lib.mxtpu_ext_num_ops.restype = ctypes.c_int
    lib.mxtpu_ext_op_name.restype = ctypes.c_char_p
    lib.mxtpu_ext_op_name.argtypes = [ctypes.c_int]
    I64P = ctypes.POINTER(ctypes.c_int64)
    I32P = ctypes.POINTER(ctypes.c_int)
    F32P = ctypes.POINTER(ctypes.c_float)
    lib.mxtpu_ext_infer_shape.restype = ctypes.c_int
    lib.mxtpu_ext_infer_shape.argtypes = [
        ctypes.c_char_p, ctypes.c_int, I64P, I32P, I64P, I32P]
    lib.mxtpu_ext_compute.restype = ctypes.c_int
    lib.mxtpu_ext_compute.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(F32P), I64P, I32P,
        F32P, I64P, ctypes.c_int]

    def _flat_shapes(shapes):
        flat = []
        ndims = []
        for s in shapes:
            ndims.append(len(s))
            flat.extend(int(d) for d in s)
        return ((ctypes.c_int64 * max(len(flat), 1))(*flat),
                (ctypes.c_int * max(len(ndims), 1))(*ndims))

    def _infer(op_b, shapes):
        flat, ndims = _flat_shapes(shapes)
        out_shape = (ctypes.c_int64 * 16)()
        out_ndim = ctypes.c_int()
        rc = lib.mxtpu_ext_infer_shape(op_b, len(shapes), flat, ndims,
                                       out_shape, ctypes.byref(out_ndim))
        if rc != 0:
            raise MXNetError(f"extension infer_shape failed (rc={rc})")
        if not 0 <= out_ndim.value <= 16:
            raise MXNetError(
                f"extension returned out_ndim={out_ndim.value}; the ABI "
                "bounds output rank at 16 (MXTPU_MAX_NDIM)")
        return tuple(out_shape[i] for i in range(out_ndim.value))

    def _make_op(op_name):
        op_b = op_name.encode()

        def host_kernel(out_shape, *host_arrays):
            arrays = [_np.ascontiguousarray(a, _np.float32)
                      for a in host_arrays]
            shapes = [a.shape for a in arrays]
            out = _np.zeros(out_shape, _np.float32)
            flat, ndims = _flat_shapes(shapes)
            ptrs = (F32P * max(len(arrays), 1))(*[
                a.ctypes.data_as(F32P) for a in arrays])
            oshape = (ctypes.c_int64 * max(len(out_shape), 1))(*out_shape)
            rc = lib.mxtpu_ext_compute(op_b, len(arrays), ptrs, flat, ndims,
                                       out.ctypes.data_as(F32P), oshape,
                                       len(out_shape))
            if rc != 0:
                raise MXNetError(f"extension op {op_name} failed (rc={rc})")
            return out

        def op(*inputs):
            import functools

            import jax
            import jax.numpy as jnp

            from .ndarray import NDArray, _wrap
            raws = [x._arr if isinstance(x, NDArray) else jnp.asarray(x)
                    for x in inputs]
            out_shape = _infer(op_b, [tuple(r.shape) for r in raws])
            if not any(isinstance(r, jax.core.Tracer) for r in raws):
                # eager: run the host kernel directly (no pure_callback —
                # some transports, e.g. the tunneled TPU plugin, don't
                # support host send/recv callbacks at execution time)
                out = host_kernel(out_shape,
                                  *[_np.asarray(r) for r in raws])
                result = jnp.asarray(out)
            else:
                # traced (jit/hybridize): bridge via pure_callback; on
                # platforms without host-callback support XLA raises at
                # run time — extension ops are host kernels by contract
                result = jax.pure_callback(
                    functools.partial(host_kernel, out_shape),
                    jax.ShapeDtypeStruct(out_shape, jnp.float32),
                    *[r.astype(jnp.float32) for r in raws])
            return _wrap(result) if any(isinstance(x, NDArray)
                                        for x in inputs) else result

        op.__name__ = op_name
        op.__doc__ = (f"native extension op {op_name!r} from {path} "
                      "(host kernel via jax.pure_callback)")
        return op

    from . import numpy_extension as npx
    ops = {}
    for i in range(lib.mxtpu_ext_num_ops()):
        nm = lib.mxtpu_ext_op_name(i).decode()
        if getattr(npx, nm, None) is not None:
            raise MXNetError(
                f"extension op {nm!r} collides with an existing npx op "
                "(duplicate registration is an error, reference semantics)")
        fn = _make_op(nm)
        ops[nm] = fn
        setattr(npx, nm, fn)
    _native_loaded[path] = {"lib": lib, "ops": ops}
    if verbose:
        print(f"loaded native extension {path}: ops {sorted(ops)}")
    return _native_loaded[path]
