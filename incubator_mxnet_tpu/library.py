"""mx.library — extension loading (≙ python/mxnet/library.py MXLoadLib +
include/mxnet/lib_api.h custom-op ABI).

The reference loads a compiled .so implementing the 1.3k-LoC C ABI. The
TPU-native extension unit is a PYTHON module (jax kernels are Python-level;
there is no stable C kernel ABI to target): `load(path)` imports the file
and calls its `register_ops(mx)` hook, which registers custom ops
(mx.operator.register), kvstores (KVStoreBase.register), optimizers
(mx.optimizer.register) or metrics — the same extension points the
reference exposes through lib_api.h.
"""
from __future__ import annotations

import importlib.util
import os

from .base import MXNetError

__all__ = ["load"]

_loaded = {}


def load(path, verbose=True):
    """Load an extension module and run its register hook."""
    path = os.path.abspath(path)
    if path in _loaded:
        return _loaded[path]
    if not os.path.exists(path):
        raise MXNetError(f"extension not found: {path}")
    if path.endswith(".so"):
        raise MXNetError(
            "binary lib_api.so extensions target the CUDA runtime ABI and "
            "cannot run on this stack; port the extension to a python module "
            "with a register_ops(mx) hook (see mx.library docs)")
    spec = importlib.util.spec_from_file_location(
        f"mx_ext_{os.path.basename(path).removesuffix('.py')}", path)
    if spec is None or spec.loader is None:
        raise MXNetError(f"not a loadable python extension: {path}")
    mod = importlib.util.module_from_spec(spec)
    import sys
    sys.modules[spec.name] = mod  # required before exec (enables pickling)
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(spec.name, None)
        raise
    if hasattr(mod, "register_ops"):
        import incubator_mxnet_tpu as mx
        mod.register_ops(mx)
    _loaded[path] = mod
    if verbose:
        print(f"loaded extension {path}")
    return mod
