"""mx.operator — user-defined operators.

Reference: python/mxnet/operator.py (CustomOp/CustomOpProp executed on
dedicated C++ worker threads with GIL re-entry, src/operator/custom/
custom-inl.h:52-198). TPU-native: a custom op is just a Python callable on
NDArrays taped through autograd.Function — no worker-thread machinery is
needed because eager dispatch is already async under PJRT. The CustomOpProp
registration surface is preserved so reference-style code runs unchanged:

    @mx.operator.register("sigmoid2")
    class Sigmoid2Prop(mx.operator.CustomOpProp):
        def create_operator(self, ctx, shapes, dtypes):
            return Sigmoid2()

    out = mx.operator.invoke("sigmoid2", x)
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .autograd import Function
from .ndarray import NDArray, _as_nd, zeros_like

__all__ = ["CustomOp", "CustomOpProp", "register", "invoke", "get_all_registered"]

_REGISTRY = {}


class CustomOp:
    """≙ mx.operator.CustomOp: forward/backward with assign()."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """≙ CustomOp.assign honoring grad_req."""
        src = _as_nd(src)
        if req in ("write", "inplace", None):
            dst._set_arr(src._arr)
        elif req == "add":
            dst._set_arr((dst + src)._arr)
        elif req == "null":
            pass
        else:
            raise MXNetError(f"invalid req {req!r}")


class CustomOpProp:
    """≙ mx.operator.CustomOpProp: shape/type inference + operator factory."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name):
    """≙ mx.operator.register decorator."""
    def _reg(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register() expects a CustomOpProp subclass")
        _REGISTRY[reg_name] = prop_cls
        return prop_cls
    return _reg


def get_all_registered():
    return dict(_REGISTRY)


class _CustomFunction(Function):
    """Bridges the CustomOp protocol onto the autograd tape."""

    def __init__(self, op, n_out, is_train):
        super().__init__()
        self._op = op
        self._n_out = n_out
        # captured BEFORE Function.__call__'s pause() scope flips training
        # off (≙ reference: is_train reflects the recording context)
        self._is_train = is_train

    def forward(self, *inputs):
        holder = _OutHolder(self._n_out)
        self._op.forward(self._is_train, ["write"] * self._n_out,
                         list(inputs), holder.slots, [])
        self._inputs = inputs
        self._outputs = tuple(holder.get())
        return self._outputs if self._n_out > 1 else self._outputs[0]

    def backward(self, *output_grads):
        n_in = len(self._inputs)
        grads = [zeros_like(x) for x in self._inputs]
        self._op.backward(["write"] * n_in, list(output_grads),
                          list(self._inputs), list(self._outputs), grads, [])
        return grads if n_in > 1 else grads[0]


class _OutHolder:
    """Output slots for CustomOp.forward: op calls assign(out_data[i],...)"""

    def __init__(self, n):
        from .ndarray import array
        self.slots = [array(_np.zeros(1, _np.float32)) for _ in range(n)]

    def get(self):
        return self.slots


def invoke(op_name, *inputs, ctx=None, **kwargs):
    """Run a registered custom op eagerly (≙ the Custom op node)."""
    if op_name not in _REGISTRY:
        raise MXNetError(f"custom op {op_name!r} is not registered")
    prop = _REGISTRY[op_name](**kwargs)
    inputs = [_as_nd(x) for x in inputs]
    in_shapes = [list(x.shape) for x in inputs]
    in_types = [x.dtype for x in inputs]
    prop.infer_shape(in_shapes)
    from . import autograd
    op = prop.create_operator(ctx, in_shapes, in_types)
    fn = _CustomFunction(op, len(prop.list_outputs()),
                         autograd.is_training() or autograd.is_recording())
    return fn(*inputs)
