"""mx.npx — NumPy extensions: NN operators + control flow.

Reference: python/mxnet/numpy_extension (the `_npx_*` op namespace: activations,
softmax, pick, topk, control flow `_npx_foreach/_npx_while_loop/_npx_cond`
(src/operator/npx_control_flow.cc:513-918), sequence ops, set_np scope).
TPU-native: wrappers over ops/nn.py jax compositions; control flow lowers to
lax.scan / lax.while_loop / lax.cond — autograd through foreach/cond is native
jax vjp; while_loop is forward-only exactly like XLA requires.
"""
from __future__ import annotations

import functools

import numpy as _onp

from ..base import MXNetError, name_to_dtype
from ..ndarray import NDArray, _as_nd, _wrap
from ..ops.registry import (invoke, register_op, get_op, record_key,
                            note_layout)
from ..ops import nn as _nn
from ..ops import fused as _fused_ops
from ..ops import segment as _segment
from .. import random as _grandom
from .. import autograd as _autograd

__all__ = [
    "relu", "sigmoid", "tanh", "softmax", "log_softmax", "masked_softmax",
    "gelu", "leaky_relu", "elu", "selu", "silu", "swish", "activation",
    "one_hot", "pick", "topk", "sequence_mask", "embedding", "dropout",
    "batch_norm", "layer_norm", "group_norm", "instance_norm", "rms_norm",
    "l2_normalization", "fully_connected", "convolution", "deconvolution",
    "pooling", "foreach", "while_loop", "cond", "scan",
    "set_np", "reset_np", "is_np_array", "is_np_shape", "use_np", "erf",
    "erfinv", "gamma", "gammaln", "digamma", "multi_sum_sq", "clip_by_global_norm",
    "arange_like", "broadcast_like", "shape_array", "stop_gradient",
    "smooth_l1", "scaled_dot_product_attention",
]


def _unary(jfn, name, amp="neutral"):
    base_key = _segment.derive_key_cached(jfn)

    def fn(x, **kwargs):
        return invoke(functools.partial(jfn, **kwargs) if kwargs else jfn,
                      (_as_nd(x),), name=name, op=info,
                      key=record_key(base_key, kwargs))
    fn.__name__ = name
    register_op("npx." + name, fn, amp=amp)
    info = get_op("npx." + name)
    return fn


def _make_nn(fname, name=None):
    f = getattr(_nn, fname)
    base_key = _segment.derive_key_cached(f)

    def fn(*arrays, **kwargs):
        arrs = tuple(_as_nd(a) if not isinstance(a, NDArray) else a
                     for a in arrays)
        # array-valued kwargs (masks, lengths) close over as raw buffers —
        # they are op attributes, not differentiated inputs
        kwargs = {k: (v._arr if isinstance(v, NDArray) else v)
                  for k, v in kwargs.items()}
        return invoke(functools.partial(f, **kwargs) if kwargs else f,
                      arrs, name=name or fname, op=info,
                      key=record_key(base_key, kwargs))
    fn.__name__ = name or fname
    register_op("npx." + (name or fname), fn,
                amp=getattr(f, "_amp_class", "neutral"))
    info = get_op("npx." + (name or fname))
    return fn


import jax  # noqa: E402
import jax.numpy as _jnp  # noqa: E402

relu = _unary(jax.nn.relu, "relu")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
tanh = _unary(_jnp.tanh, "tanh")
erf = _unary(jax.scipy.special.erf, "erf")
erfinv = _unary(jax.scipy.special.erfinv, "erfinv")
gamma = _unary(lambda x: _jnp.exp(jax.scipy.special.gammaln(x)), "gamma")
gammaln = _unary(jax.scipy.special.gammaln, "gammaln")
digamma = _unary(jax.scipy.special.digamma, "digamma")
softplus = _unary(jax.nn.softplus, "softplus")
log_sigmoid = _unary(jax.nn.log_sigmoid, "log_sigmoid")
silu = _unary(jax.nn.silu, "silu")
swish = silu
stop_gradient = _unary(jax.lax.stop_gradient, "stop_gradient")

softmax = _make_nn("softmax")
log_softmax = _make_nn("log_softmax")
masked_softmax = _make_nn("masked_softmax")
activation = _make_nn("activation")
layer_norm = _make_nn("layer_norm")
group_norm = _make_nn("group_norm")
instance_norm = _make_nn("instance_norm")
rms_norm = _make_nn("rms_norm")
l2_normalization = _make_nn("l2_normalize", "l2_normalization")
one_hot = _make_nn("one_hot")
pick = _make_nn("pick")
topk = _make_nn("topk")
sequence_mask = _make_nn("sequence_mask")
embedding = _make_nn("embedding")


def rnn(data, parameters, state, state_cell=None, mode="lstm",
        state_size=None, num_layers=1, bidirectional=False, p=0.0,
        state_outputs=False):
    """Fused multi-layer (bi)RNN on a FLAT parameter vector (≙ the
    reference's `_npx.rnn` fused op, src/operator/rnn.cc:1 /
    python/mxnet/numpy_extension/_op.py:847 — VERDICT-r4 Next #10).

    data (T, N, C) time-major; `parameters` is the reference layout:
    all W_i2h/W_h2h gate blocks layer-major with direction inner, then
    all b_i2h/b_h2h pairs in the same order. Gate order LSTM [i,f,g,o],
    GRU [r,z,n] (reference/cuDNN convention). `state` (L*D, N, H) and,
    for LSTM, `state_cell` likewise. Returns `out`, or
    (out, h_n[, c_n]) when state_outputs=True."""
    if state_size is None:
        raise MXNetError("state_size is required")
    gates = {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}
    if mode not in gates:
        raise MXNetError(f"unknown rnn mode {mode!r}")
    G = gates[mode]
    # static layer-config ints, never traced values
    H, L = int(state_size), int(num_layers)  # mxlint: disable=trace-host-capture
    D = 2 if bidirectional else 1
    C = int(data.shape[-1])
    training = _autograd.is_training()
    key = _grandom.next_key() if (p > 0 and training) else None

    arrs = [_as_nd(data), _as_nd(parameters), _as_nd(state)]
    if mode == "lstm":
        if state_cell is None:
            raise MXNetError("lstm needs state_cell")
        arrs.append(_as_nd(state_cell))

    def run(x, flat, h0, *maybe_c):
        off = 0

        def take(n, shape):
            # static unpack offset over the flat param vector: advances
            # during tracing only, reset per run() call — by design
            nonlocal off
            w = flat[off:off + n].reshape(shape)
            off += n  # mxlint: disable=trace-closure-mutation
            return w

        params = {}
        for layer in range(L):
            insz = C if layer == 0 else H * D
            for d in range(D):
                params[(layer, d)] = {
                    "wx": take(G * H * insz, (G * H, insz)),
                    "wh": take(G * H * H, (G * H, H))}
        for layer in range(L):
            for d in range(D):
                params[(layer, d)]["bx"] = take(G * H, (G * H,))
                params[(layer, d)]["bh"] = take(G * H, (G * H,))
        if off != flat.shape[0]:
            # ≙ the reference op's parameter-size CHECK (rnn.cc): a
            # mismatched layout must not silently misalign every block
            raise MXNetError(
                f"parameters has {flat.shape[0]} elements; the "
                f"{mode} L={L} D={D} H={H} C={C} layout needs {off}")
        st = (h0,) + tuple(maybe_c)
        out, new_state = _nn.rnn(x, params, st, mode=mode, num_layers=L,
                                 bidirectional=(D == 2), dropout_rate=p,
                                 key=key, training=training)
        return (out,) + tuple(new_state)

    res = invoke(run, tuple(arrs), name="rnn_fused", multi_out=True)
    return tuple(res) if state_outputs else res[0]


register_op("npx.rnn", rnn)
__all__.append("rnn")
scaled_dot_product_attention = _make_nn("scaled_dot_product_attention")


def _make_fused(fname, name=None):
    """npx wrapper over an ops.fused kernel — same contract as _make_nn
    (arrays positional, static config via kwargs, dispatch-record key from
    the registration-precomputed base key)."""
    f = getattr(_fused_ops, fname)
    base_key = _segment.derive_key_cached(f)

    def fn(*arrays, **kwargs):
        arrs = tuple(_as_nd(a) if not isinstance(a, NDArray) else a
                     for a in arrays)
        # array-valued kwargs (e.g. bn_inference's residual=) close over
        # as raw buffers, same contract as _make_nn
        kwargs = {k: (v._arr if isinstance(v, NDArray) else v)
                  for k, v in kwargs.items()}
        # resolve the kernel-vs-fallback mode NOW so it enters the
        # dispatch key: a set_interpret() toggle must not replay programs
        # compiled for the other path
        kwargs.setdefault("interpret", _fused_ops._interpret())
        return invoke(functools.partial(f, **kwargs),
                      arrs, name=name or fname, op=info,
                      key=record_key(base_key, kwargs))
    fn.__name__ = name or fname
    register_op("npx." + (name or fname), fn,
                amp=getattr(f, "_amp_class", "neutral"))
    info = get_op("npx." + (name or fname))
    return fn


# fused kernel tier (ops/fused.py — Pallas on TPU, jnp composition
# elsewhere). Gluon blocks route here when fused.fusion_enabled().
fused_bias_act = _make_fused("bias_act", "fused_bias_act")
fused_norm_act_residual = _make_fused("norm_act_residual",
                                      "fused_norm_act_residual")
fused_bn_inference = _make_fused("bn_inference", "fused_bn_inference")
# device half of the uint8 input-pipeline handoff (crop/flip/normalize/
# cast as ONE batched kernel; ImageRecordIter device_augment mode and the
# DeviceFeed staging path call this) — jnp-only, no Pallas variant
fused_image_augment = _make_fused("image_augment", "fused_image_augment")


def fused_avg_pool2d(data, pool_size, layout="NHWC"):
    """Fused non-overlapping NHWC average pool (kernel == stride, no
    padding; GlobalAvgPool shapes included) with the VMEM-tiled Pallas
    backward — see ops.fused.avg_pool2d."""
    info = get_op("npx.fused_avg_pool2d")
    note_layout(info, layout)
    ps = (pool_size, pool_size) if isinstance(pool_size, int) \
        else tuple(pool_size)
    kw = {"pool_size": ps, "layout": layout,
          "interpret": _fused_ops._interpret()}
    return invoke(functools.partial(_fused_ops.avg_pool2d, **kw),
                  (_as_nd(data),), name="fused_avg_pool2d", op=info,
                  key=record_key(_avg_pool_key, kw))


register_op("npx.fused_avg_pool2d", _fused_ops.avg_pool2d,
            amp=_fused_ops.avg_pool2d._amp_class)
_avg_pool_key = _segment.derive_key_cached(_fused_ops.avg_pool2d)


def fused_batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-5,
                     momentum=0.9, axis=1, use_global_stats=False,
                     training=None, sync_axis_name=None, act_type=None,
                     residual=None):
    """Batch norm with the apply stage routed through the fused kernel
    tier, plus optional fused activation and pre-activation residual add
    (ops.fused.batch_norm). Same running-stat write-back protocol as
    npx.batch_norm."""
    if training is None:
        training = _autograd.is_training()
    kw = dict(momentum=momentum, eps=eps, training=training, axis=axis,
              use_global_stats=use_global_stats,
              sync_axis_name=sync_axis_name, act_type=act_type,
              interpret=_fused_ops._interpret())
    info = get_op("npx.fused_batch_norm")
    arrs = (_as_nd(x), _as_nd(gamma), _as_nd(beta), _as_nd(running_mean),
            _as_nd(running_var))
    if residual is not None:
        out, nm, nv = invoke(
            functools.partial(_bn_residual, **kw),
            arrs + (_as_nd(residual),),
            name="fused_batch_norm", op=info,
            key=record_key(_fused_bn_res_key, kw), multi_out=True)
    else:
        out, nm, nv = invoke(
            functools.partial(_fused_ops.batch_norm, **kw), arrs,
            name="fused_batch_norm", op=info,
            key=record_key(_fused_bn_key, kw), multi_out=True)
    if training and isinstance(running_mean, NDArray):
        with _autograd.pause():
            # adopt the (possibly pending) stat buffers like npx.batch_norm
            running_mean._set_arr(nm._data)
            running_var._set_arr(nv._data)
    return out


def _bn_residual(a, g, b, rm, rv, r, **kw):
    """Module-level residual variant: arrays positional so the dispatch
    derives a stable key (a per-call closure would key as None — no
    bulking, and a full vjp retrace per call under recording)."""
    return _fused_ops.batch_norm(a, g, b, rm, rv, residual=r, **kw)


register_op("npx.fused_batch_norm", _fused_ops.batch_norm,
            amp=_fused_ops.batch_norm._amp_class)
_fused_bn_key = _segment.derive_key_cached(_fused_ops.batch_norm)
_fused_bn_res_key = _segment.derive_key_cached(_bn_residual)


def flash_attention(query, key, value, causal=False, scale=None,
                    block_q=None, block_k=None):
    """Blockwise (flash) attention over (batch*heads, T, head_dim) —
    the ops.pallas_attention kernel registered as a first-class op:
    dispatch record + AMP class, so opperf, AMP lists and inspect
    reports see it like any other op."""
    from ..ops.pallas_attention import flash_attention as _fa
    kw = dict(causal=causal, scale=scale, block_q=block_q,
              block_k=block_k)
    info = get_op("npx.flash_attention")
    return invoke(functools.partial(_fa, **kw),
                  (_as_nd(query), _as_nd(key), _as_nd(value)),
                  name="flash_attention", op=info,
                  key=record_key(_flash_key, kw))


def _register_flash_attention():
    from ..ops.pallas_attention import flash_attention as _fa
    _fa._amp_class = "safe"   # MXU-bound flops: run in the autocast dtype
    register_op("npx.flash_attention", _fa, amp="safe")
    return _segment.derive_key_cached(_fa)


_flash_key = _register_flash_attention()


def paged_attention(query, k_slab, v_slab, lengths, layer,
                    k_scale=None, v_scale=None, interpret=None):
    """Paged decode attention over a serve.kv_pool KV slab — the
    ops.fused block-sparse decode kernel registered as a first-class op
    (dispatch record + AMP class). `query` is (S, C, H, D) chunk queries;
    lane s reads slab row s of `layer`, positions `[0, lengths[s] + j]`;
    `k_scale`/`v_scale` dequantize int8 slabs per position."""
    from ..ops import fused as _fused
    kw = dict(layer=int(layer), interpret=interpret)
    info = get_op("npx.paged_attention")
    arrs = [_as_nd(query), _as_nd(k_slab), _as_nd(v_slab),
            _as_nd(lengths)]
    fn = _fused.paged_attention
    if k_scale is not None:
        arrs.extend([_as_nd(k_scale), _as_nd(v_scale)])
        call = lambda q, k, v, ln, ks, vs: fn(q, k, v, ln, k_scale=ks,
                                              v_scale=vs, **kw)
    else:
        call = lambda q, k, v, ln: fn(q, k, v, ln, **kw)
    return invoke(call, tuple(arrs), name="paged_attention", op=info,
                  key=record_key(_paged_key, kw))


def _register_paged_attention():
    from ..ops import fused as _fused
    register_op("npx.paged_attention", _fused.paged_attention,
                amp=_fused.paged_attention._amp_class)
    return _segment.derive_key_cached(_fused.paged_attention)


_paged_key = _register_paged_attention()

# layout-sensitive kernels get dispatch records too (PR 8): the npx
# wrappers below stamp each call's layout onto the record (note_layout),
# making the NHWC/NCHW choice introspectable next to the AMP class.
for _kn in ("conv", "conv_transpose", "pooling"):
    _k = getattr(_nn, _kn)
    register_op("npx." + {"conv": "convolution",
                          "conv_transpose": "deconvolution",
                          "pooling": "pooling"}[_kn], _k,
                amp=getattr(_k, "_amp_class", "neutral"))
del _kn, _k

__all__ += ["fused_bias_act", "fused_norm_act_residual",
            "fused_bn_inference", "fused_avg_pool2d", "fused_batch_norm",
            "fused_image_augment", "flash_attention", "paged_attention"]


def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, **kwargs):
    arrs = (_as_nd(data),)
    kw = dict(act_type=act_type, slope=slope, **kwargs)
    if act_type == "prelu":
        return invoke(lambda x, g: _nn.leaky_relu(x, "prelu", gamma=g),
                      (_as_nd(data), _as_nd(gamma)), name="leaky_relu")
    if act_type == "rrelu" and _autograd.is_training():
        kw["key"] = _grandom.next_key()
        kw["training"] = True
    return invoke(functools.partial(_nn.leaky_relu, **kw), arrs,
                  name="leaky_relu")


def gelu(x, approximate=False):
    return invoke(functools.partial(jax.nn.gelu, approximate=approximate),
                  (_as_nd(x),), name="gelu")


def elu(x, alpha=1.0):
    return invoke(functools.partial(jax.nn.elu, alpha=alpha), (_as_nd(x),),
                  name="elu")


def selu(x):
    return invoke(jax.nn.selu, (_as_nd(x),), name="selu")


def dropout(data, p=0.5, axes=None, training=None):
    if training is None:
        training = _autograd.is_training()
    if not training or p <= 0:
        return _as_nd(data)
    key = _grandom.next_key()
    return invoke(lambda x: _nn.dropout(x, p, key, True, axes), (_as_nd(data),),
                  name="dropout")


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-5,
               momentum=0.9, axis=1, use_global_stats=False, training=None,
               sync_axis_name=None):
    """Functional batch norm; returns output only and writes running stats
    in-place on the NDArrays (eager path). Inside traces use ops.nn.batch_norm
    directly through the state-update protocol (gluon/block.py)."""
    if training is None:
        training = _autograd.is_training()
    out, nm, nv = invoke(
        functools.partial(_nn.batch_norm, momentum=momentum, eps=eps,
                          training=training, axis=axis,
                          use_global_stats=use_global_stats,
                          sync_axis_name=sync_axis_name),
        (_as_nd(x), _as_nd(gamma), _as_nd(beta), _as_nd(running_mean),
         _as_nd(running_var)),
        name="batch_norm", multi_out=True)
    if training and isinstance(running_mean, NDArray):
        with _autograd.pause():
            # adopt the (possibly still pending) buffers — no materialization,
            # so a bulked eager step keeps BN stat updates in the segment
            running_mean._set_arr(nm._data)
            running_var._set_arr(nv._data)
    return out


def fully_connected(x, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    arrs = (_as_nd(x), _as_nd(weight)) + (() if no_bias or bias is None
                                          else (_as_nd(bias),))
    return invoke(functools.partial(_nn.dense, flatten=flatten), arrs,
                  name="fully_connected")


def convolution(data, weight, bias=None, kernel=None, stride=1, dilate=1,
                pad=0, num_filter=None, num_group=1, no_bias=False,
                layout="NCHW"):
    arrs = (_as_nd(data), _as_nd(weight)) + (() if no_bias or bias is None
                                             else (_as_nd(bias),))
    note_layout(get_op("npx.convolution"), layout)
    return invoke(functools.partial(_nn.conv, stride=stride, padding=pad,
                                    dilation=dilate, groups=num_group,
                                    layout=layout),
                  arrs, name="convolution")


def deconvolution(data, weight, bias=None, stride=1, dilate=1, pad=0, adj=0,
                  num_group=1, no_bias=False, layout="NCHW"):
    arrs = (_as_nd(data), _as_nd(weight)) + (() if no_bias or bias is None
                                             else (_as_nd(bias),))
    note_layout(get_op("npx.deconvolution"), layout)
    return invoke(functools.partial(_nn.conv_transpose, stride=stride,
                                    padding=pad, dilation=dilate,
                                    output_padding=adj, groups=num_group,
                                    layout=layout),
                  arrs, name="deconvolution")


def pooling(data, kernel=1, pool_type="max", stride=None, pad=0,
            global_pool=False, count_include_pad=True, layout="NCHW",
            ceil_mode=False, pooling_convention=None):
    if pooling_convention is not None:  # reference name: 'valid' | 'full'
        ceil_mode = pooling_convention == "full"
    note_layout(get_op("npx.pooling"), layout)
    return invoke(functools.partial(_nn.pooling, kernel=kernel,
                                    pool_type=pool_type, stride=stride,
                                    padding=pad, global_pool=global_pool,
                                    count_include_pad=count_include_pad,
                                    layout=layout, ceil_mode=ceil_mode),
                  (_as_nd(data),), name="pooling")


def box_iou(lhs, rhs, format="corner"):
    """≙ _contrib_box_iou (src/operator/contrib/bounding_box.cc)."""
    from ..ops import contrib as _contrib
    return invoke(functools.partial(_contrib.box_iou, fmt=format),
                  (_as_nd(lhs), _as_nd(rhs)), name="box_iou")


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, force_suppress=False):
    """≙ _contrib_box_nms."""
    from ..ops import contrib as _contrib
    return invoke(functools.partial(
        _contrib.box_nms, overlap_thresh=overlap_thresh,
        valid_thresh=valid_thresh, topk=topk, coord_start=coord_start,
        score_index=score_index, id_index=id_index,
        force_suppress=force_suppress), (_as_nd(data),), name="box_nms")


def roi_align(data, rois, pooled_size, spatial_scale=1.0, sample_ratio=2):
    """≙ _contrib_ROIAlign."""
    from ..ops import contrib as _contrib
    return invoke(functools.partial(
        _contrib.roi_align, pooled_size=pooled_size,
        spatial_scale=spatial_scale, sample_ratio=sample_ratio),
        (_as_nd(data), _as_nd(rois)), name="roi_align")


def bilinear_resize2d(data, height, width, layout="NCHW"):
    """≙ _contrib_BilinearResize2D."""
    from ..ops import contrib as _contrib
    return invoke(functools.partial(_contrib.bilinear_resize2d,
                                    height=height, width=width,
                                    layout=layout),
                  (_as_nd(data),), name="bilinear_resize2d")


def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5), layout="NCHW"):
    """≙ _npx_multibox_prior (src/operator/contrib/multibox_prior.cc).

    Anchors depend only on the feature map's SHAPE, and the reference op
    has no backward — so `data` is detached before dispatch. Taping it
    (pre-r5 behavior) left anchors holding a tape node that a later
    backward severed: the usual compute-anchors-once-reuse-every-step
    pattern then crashed on the second iteration."""
    from ..ops import contrib as _contrib
    return invoke(functools.partial(
        _contrib.multibox_prior, sizes=tuple(sizes), ratios=tuple(ratios),
        clip=clip, steps=tuple(steps), offsets=tuple(offsets),
        layout=layout), (_as_nd(data).detach(),), name="multibox_prior")


def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """≙ _npx_multibox_target (src/operator/contrib/multibox_target.cc)."""
    from ..ops import contrib as _contrib
    return invoke(functools.partial(
        _contrib.multibox_target, overlap_threshold=overlap_threshold,
        ignore_label=ignore_label,
        negative_mining_ratio=negative_mining_ratio,
        negative_mining_thresh=negative_mining_thresh,
        minimum_negative_samples=minimum_negative_samples,
        variances=tuple(variances)),
        (_as_nd(anchor), _as_nd(label), _as_nd(cls_pred)),
        name="multibox_target", multi_out=True)


def multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                       threshold=0.01, background_id=0, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """≙ _npx_multibox_detection (multibox_detection.cc)."""
    from ..ops import contrib as _contrib
    return invoke(functools.partial(
        _contrib.multibox_detection, clip=clip, threshold=threshold,
        background_id=background_id, nms_threshold=nms_threshold,
        force_suppress=force_suppress, variances=tuple(variances),
        nms_topk=nms_topk),
        (_as_nd(cls_prob), _as_nd(loc_pred), _as_nd(anchor)),
        name="multibox_detection")


def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16,
             output_score=False, iou_loss=False):
    """≙ _contrib_Proposal (src/operator/contrib/proposal.cc)."""
    from ..ops import contrib as _contrib
    return invoke(functools.partial(
        _contrib.proposal, rpn_pre_nms_top_n=rpn_pre_nms_top_n,
        rpn_post_nms_top_n=rpn_post_nms_top_n, threshold=threshold,
        rpn_min_size=rpn_min_size, scales=tuple(scales),
        ratios=tuple(ratios), feature_stride=feature_stride,
        output_score=output_score, iou_loss=iou_loss),
        (_as_nd(cls_prob), _as_nd(bbox_pred), _as_nd(im_info)),
        name="proposal", multi_out=output_score)


def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                           num_deformable_group=1):
    """≙ _npx_deformable_convolution (deformable_convolution.cc)."""
    from ..ops import contrib as _contrib
    fn = functools.partial(
        _contrib.deformable_convolution, kernel=tuple(kernel),
        stride=tuple(stride), pad=tuple(pad), dilate=tuple(dilate),
        num_deformable_group=num_deformable_group)
    args = (_as_nd(data), _as_nd(offset), _as_nd(weight))
    if bias is not None:
        args = args + (_as_nd(bias),)
        return invoke(lambda d, o, w, b: fn(d, o, w, bias=b), args,
                      name="deformable_convolution")
    return invoke(lambda d, o, w: fn(d, o, w), args,
                  name="deformable_convolution")


def psroi_pooling(data, rois, spatial_scale, output_dim, pooled_size,
                  group_size=0):
    """≙ _contrib_PSROIPooling (psroi_pooling.cc, R-FCN)."""
    from ..ops import contrib as _contrib
    return invoke(functools.partial(
        _contrib.psroi_pooling, spatial_scale=spatial_scale,
        output_dim=output_dim, pooled_size=pooled_size,
        group_size=group_size), (_as_nd(data), _as_nd(rois)),
        name="psroi_pooling")


def smooth_l1(x, scalar=1.0):
    """reference: smooth_l1 op (src/operator/tensor/elemwise_unary_op)"""
    def f(v):
        s2 = scalar * scalar
        return _jnp.where(_jnp.abs(v) < 1.0 / s2,
                          0.5 * s2 * v * v, _jnp.abs(v) - 0.5 / s2)
    return invoke(f, (_as_nd(x),), name="smooth_l1")


def multi_sum_sq(*arrays):
    """Sum of squares per array, fused (reference: multi_sum_sq op used by
    clip_global_norm / LARS)."""
    arrs = tuple(_as_nd(a) for a in arrays)
    return invoke(lambda *xs: tuple(_jnp.sum(_jnp.square(x)) for x in xs),
                  arrs, name="multi_sum_sq", multi_out=True)


def clip_by_global_norm(arrays, max_norm):
    """In-place global-norm clipping over a list of NDArrays; returns the norm
    (≙ gluon.utils.clip_global_norm)."""
    sqs = multi_sum_sq(*arrays)
    total = sqs[0]
    for s in sqs[1:]:
        total = total + s
    norm = total.sqrt()
    scale = float(max_norm) / max(float(norm.asscalar()), float(max_norm))
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return norm


def arange_like(data, start=0.0, step=1.0, axis=None):
    def f(x):
        if axis is None:
            n = int(_onp.prod(x.shape))
            return _jnp.arange(start, start + step * n, step,
                               dtype=x.dtype).reshape(x.shape)
        n = x.shape[axis]
        return _jnp.arange(start, start + step * n, step, dtype=x.dtype)
    return invoke(f, (_as_nd(data),), name="arange_like")


def broadcast_like(lhs, rhs):
    return invoke(lambda a, b: _jnp.broadcast_to(a, b.shape),
                  (_as_nd(lhs), _as_nd(rhs)), name="broadcast_like")


def shape_array(data):
    return _wrap(_jnp.asarray(_as_nd(data).shape, dtype="int64"))


# ---------------------------------------------------------------------------
# control flow (reference: src/operator/npx_control_flow.cc:513-918 — stateful
# subgraph ops with LoopState; here: direct lax lowering, differentiable where
# XLA supports it)
# ---------------------------------------------------------------------------
def foreach(body, data, init_states):
    """Run `body(x_t, states) -> (out_t, new_states)` over axis 0 of data
    (≙ _npx_foreach). Differentiable (lax.scan)."""
    from jax import lax
    import jax.tree_util as jtu
    single_data = isinstance(data, NDArray)
    datas = (data,) if single_data else tuple(data)
    single_state = isinstance(init_states, NDArray)
    states = (init_states,) if single_state else tuple(init_states)
    n_data = len(datas)

    def call(*raws):
        xs = raws[:n_data]
        ss = raws[n_data:]

        def step(carry, x):
            xs_nd = [_wrap(xi) for xi in (x if n_data > 1 else (x,))]
            ss_nd = [_wrap(c) for c in carry]
            out, new_s = body(xs_nd[0] if single_data else xs_nd,
                              ss_nd[0] if single_state else ss_nd)
            outs = (out,) if isinstance(out, NDArray) else tuple(out)
            new_ss = (new_s,) if isinstance(new_s, NDArray) else tuple(new_s)
            return (tuple(s._arr for s in new_ss),
                    tuple(o._arr for o in outs))

        carry, ys = lax.scan(step, tuple(ss), xs if n_data > 1 else xs[0])
        return tuple(ys) + tuple(carry)

    res = invoke(call, datas + states, name="foreach", multi_out=True)
    n_out = len(res) - len(states)
    outs = res[:n_out]
    fin = res[n_out:]
    outs = outs[0] if n_out == 1 else list(outs)
    fin = fin[0] if single_state else list(fin)
    return outs, fin


def while_loop(cond_fn, func, loop_vars, max_iterations=None):
    """≙ _npx_while_loop. Lowers to lax.while_loop — forward-only (XLA cannot
    reverse-differentiate an unbounded loop; use `foreach` with
    max_iterations for a differentiable variant)."""
    from jax import lax
    single = isinstance(loop_vars, NDArray)
    lvs = (loop_vars,) if single else tuple(loop_vars)

    def call(*raws):
        def c(state):
            return cond_fn(*[_wrap(s) for s in state])._arr \
                if single is False else cond_fn(_wrap(state[0]))._arr

        def b(state):
            out = func(*[_wrap(s) for s in state]) if not single \
                else func(_wrap(state[0]))
            outs = (out,) if isinstance(out, NDArray) else tuple(out)
            return tuple(o._arr for o in outs)

        return lax.while_loop(c, b, tuple(raws))

    res = invoke(call, lvs, name="while_loop", multi_out=True)
    # reference returns (outputs, final_loop_vars); outputs unsupported here
    return [], (res[0] if single else list(res))


def cond(pred, then_func, else_func, inputs=None):
    """≙ _npx_cond. Differentiable (lax.cond)."""
    from jax import lax
    if inputs is None:
        inputs = []
    single = isinstance(inputs, NDArray)
    ins = (inputs,) if single else tuple(inputs)
    p = pred(*ins) if callable(pred) else pred

    def call(praw, *raws):
        def t(xs):
            out = then_func(*[_wrap(x) for x in xs]) if xs else then_func()
            outs = (out,) if isinstance(out, NDArray) else tuple(out)
            return tuple(o._arr for o in outs)

        def f(xs):
            out = else_func(*[_wrap(x) for x in xs]) if xs else else_func()
            outs = (out,) if isinstance(out, NDArray) else tuple(out)
            return tuple(o._arr for o in outs)

        return lax.cond(praw.astype(bool).reshape(()), t, f, raws)

    res = invoke(call, (_as_nd(p),) + ins, name="cond", multi_out=True)
    return res[0] if len(res) == 1 else list(res)


scan = foreach


# ---------------------------------------------------------------------------
# np-mode scopes (reference: mx.npx.set_np / is_np_array; the numpy frontend
# is always-on here, kept for script compatibility)
# ---------------------------------------------------------------------------
_np_mode = {"array": True, "shape": True}


def set_np(shape=True, array=True, dtype=None):
    _np_mode["array"] = array
    _np_mode["shape"] = shape


def reset_np():
    set_np()


def is_np_array():
    return _np_mode["array"]


def is_np_shape():
    return _np_mode["shape"]


def use_np(func):
    return func


def load(fname):
    from ..ndarray import load as _load
    return _load(fname)


def save(fname, data):
    from ..ndarray import save as _save
    return _save(fname, data)


def sequence_last(data, sequence_length=None, use_sequence_length=False,
                  axis=0):
    """≙ SequenceLast (src/operator/sequence_last.cc)."""
    data = _as_nd(data)
    if not use_sequence_length or sequence_length is None:
        idx = data.shape[axis] - 1
        return invoke(lambda x: _jnp.take(x, idx, axis=axis), (data,),
                      name="sequence_last")

    def f(x, lens):
        jnp = _jnp
        t = jnp.clip(lens.astype(jnp.int32) - 1, 0, x.shape[axis] - 1)
        moved = jnp.moveaxis(x, axis, 0)        # (T, N, ...)
        return jnp.take_along_axis(
            moved, t.reshape((1, -1) + (1,) * (moved.ndim - 2)), axis=0)[0]
    return invoke(f, (data, _as_nd(sequence_length)), name="sequence_last")


def sequence_reverse(data, sequence_length=None, use_sequence_length=False,
                     axis=0):
    """≙ SequenceReverse (src/operator/sequence_reverse.cc)."""
    data = _as_nd(data)
    if not use_sequence_length or sequence_length is None:
        return invoke(lambda x: _jnp.flip(x, axis=axis), (data,),
                      name="sequence_reverse")

    def f(x, lens):
        jnp = _jnp
        moved = jnp.moveaxis(x, axis, 0)        # (T, N, ...)
        T = moved.shape[0]
        t_idx = jnp.arange(T)[:, None]          # (T, 1)
        lens_i = lens.astype(jnp.int32)[None, :]
        rev = jnp.where(t_idx < lens_i, lens_i - 1 - t_idx, t_idx)
        out = jnp.take_along_axis(
            moved, rev.reshape(rev.shape + (1,) * (moved.ndim - 2)), axis=0)
        return jnp.moveaxis(out, 0, axis)
    return invoke(f, (data, _as_nd(sequence_length)), name="sequence_reverse")


__all__ += ["sequence_last", "sequence_reverse", "box_iou", "box_nms",
            "roi_align", "bilinear_resize2d", "multibox_prior",
            "multibox_target", "multibox_detection", "proposal",
            "deformable_convolution", "psroi_pooling"]


# Register the contrib/detection surface so the records exist for
# introspection + apply_op dispatch, carrying the AMP classes tagged in
# ops/contrib.py (PR2 dispatch-record metadata). The RAW kernels register —
# they are pure jax functions, so apply_op dispatch tapes/bulks/jits
# correctly; the python wrappers above (reference argument names,
# detach/multi_out handling) stay the mx.npx call surface. Registering a
# wrapper instead would re-enter invoke with tracer args at backward time
# (`_as_nd(tracer)` device_put → TracerArrayConversionError).
def _register_contrib_records():
    from ..ops import contrib as _contrib
    for _n in ("box_iou", "box_nms", "roi_align", "bilinear_resize2d",
               "multibox_prior", "multibox_target", "multibox_detection",
               "proposal", "deformable_convolution", "psroi_pooling"):
        kern = getattr(_contrib, _n)
        register_op("npx." + _n, kern,
                    amp=getattr(kern, "_amp_class", "neutral"))


_register_contrib_records()
