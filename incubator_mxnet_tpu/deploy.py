"""Deployment runtime: load and execute `HybridBlock.export` artifacts.

Capability equivalent of the reference's predict/C-API stack
(`/root/reference/include/mxnet/c_predict_api.h`,
`/root/reference/src/c_api/c_predict_api.cc:120-310`): a self-contained
loader that serves inference from the exported artifact triple
(`<prefix>.jaxport` + `<prefix>.params.npz` + `<prefix>.deploy.json`)
without the model's Python class on the import path. It backs three
consumers:

  * Python — `ExportedModel` directly, or `gluon.SymbolBlock.imports`
  * the C ABI — `native/c_api.cc` (libmxtpu.so), the stable non-Python
    boundary playing the role of the reference's 240-function c_api.h
  * the C++ frontend — `cpp_package/include/mxtpu/*.hpp` (≙ cpp-package)

TPU-native design: the executable artifact is a versioned `jax.export`
serialization (StableHLO inside, lowered for both cpu and tpu) run through
one `jax.jit` on the ambient PJRT client; there is no NNVM graph, executor,
or ps-lite layer to re-create. The `_capi_*` functions at the bottom are
the C ABI's internal entry points — plain functions over plain types so the
embedded-interpreter side (c_api.cc) stays a thin marshalling layer.
"""
from __future__ import annotations

import json
import os

import numpy as _np

from .base import MXNetError, _register_env

_register_env("MXNET_COMPILE_CACHE_DIR", str, None,
              "Directory for jax's persistent compilation cache: every "
              "jit compile serializes its executable there, and a later "
              "process (replica, restart) DESERIALIZES instead of "
              "recompiling — replica warmup becomes O(load), not "
              "O(compile). Armed at the first ExportedModel load or "
              "serve.CachedDecoder build; share the dir across replicas")

# armed-once latch: jax.config.update is process-global, and re-applying
# it per model load would spam config churn
_COMPILE_CACHE_ARMED = [False]


def maybe_enable_compile_cache():
    """Wire `MXNET_COMPILE_CACHE_DIR` onto jax's persistent compilation
    cache (idempotent; no-op when the env is unset). Must run BEFORE the
    first compile of the programs it should cover — ExportedModel and
    serve.CachedDecoder call it in their constructors. The min-time /
    min-size thresholds are zeroed so even small serving programs (bucket
    MLPs, decode steps) persist: replica warmup is the target, and a
    second replica should skip EVERY compile, not just the slow ones.
    Returns True when the cache is armed."""
    if _COMPILE_CACHE_ARMED[0]:
        return True
    d = os.environ.get("MXNET_COMPILE_CACHE_DIR")
    if not d:
        return False
    import jax
    jax.config.update("jax_compilation_cache_dir", d)
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except AttributeError:        # older jax: threshold knob absent
            pass
    # jax initializes its cache backend lazily at the FIRST compile and
    # then never re-reads the dir config: a process that compiled
    # anything before arming would silently keep running cache-less.
    # Reset forces re-initialization against the new dir.
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except Exception:
        pass
    _COMPILE_CACHE_ARMED[0] = True
    return True

# Reference dtype codes (mshadow/base.h kFloat32..; c_api callers use these
# integers on the wire). bfloat16 appended at its reference index (12).
DTYPE_CODES = {
    0: "float32", 1: "float64", 2: "float16", 3: "uint8",
    4: "int32", 5: "int8", 6: "int64", 7: "bool",
    8: "int16", 9: "uint16", 10: "uint32", 11: "uint64",
    12: "bfloat16",
}
DTYPE_TO_CODE = {v: k for k, v in DTYPE_CODES.items()}


def _np_dtype(code_or_name):
    if isinstance(code_or_name, (int, _np.integer)):
        name = DTYPE_CODES.get(int(code_or_name))
        if name is None:
            raise MXNetError(f"unknown dtype code {code_or_name}")
    else:
        name = str(code_or_name)
    if name == "bfloat16":
        import ml_dtypes
        return _np.dtype(ml_dtypes.bfloat16)
    return _np.dtype(name)


class ExportedModel:
    """A loaded, runnable export artifact (≙ the reference PredictorHandle).

    Usage::

        model = ExportedModel("model-0000")      # or explicit paths
        out = model.run(x)                       # np.ndarray in, out

    Concurrency contract (the reference predictor requires one handle per
    thread; this one does not): `run`/`call_arrays` are safe to call from
    any number of threads on a SHARED instance. The jitted call is a
    compiled-program invocation on the PJRT client, which is thread-safe,
    and `run` touches no mutable instance state after construction. The
    only races are benign: concurrent FIRST calls may both enter tracing —
    jax serializes compilation internally — so latency-sensitive servers
    should `warmup()` once before going multi-threaded (serve.Server does).
    `compile_cache_size()` exposes the jit cache entry count so callers can
    assert the zero-retrace steady state (tests/test_serve.py holds this
    contract under an 8-thread hammer).
    """

    def __init__(self, prefix=None, *, jaxport=None, params=None,
                 manifest=None):
        if prefix is not None:
            jaxport = jaxport or f"{prefix}.jaxport"
            params = params or f"{prefix}.params.npz"
            manifest = manifest or f"{prefix}.deploy.json"
        if not (jaxport and params and manifest):
            raise MXNetError(
                "ExportedModel needs a prefix or explicit jaxport=, "
                "params=, manifest= paths")
        for p in (jaxport, params, manifest):
            if not os.path.exists(p):
                raise MXNetError(f"export artifact missing: {p}")

        with open(manifest) as f:
            self.manifest = json.load(f)
        if self.manifest.get("format_version") != 1:
            raise MXNetError(
                f"unsupported deploy manifest version "
                f"{self.manifest.get('format_version')!r}")

        import jax
        import jax.export as jexp
        # persistent-compilation-cache wiring: with MXNET_COMPILE_CACHE_DIR
        # set, this artifact's bucket program compiles once per FLEET, not
        # once per replica (armed before the jit below ever compiles)
        maybe_enable_compile_cache()
        with open(jaxport, "rb") as f:
            self._exported = jexp.deserialize(f.read())
        loaded = _np.load(params, allow_pickle=False)
        try:
            self._pbufs = tuple(
                jax.numpy.asarray(loaded[name])
                for name in self.manifest["params"])
        except KeyError as e:
            raise MXNetError(
                f"parameter {e} listed in manifest but absent from "
                f"{params}") from e
        self._key = jax.random.PRNGKey(0)
        self._call = jax.jit(self._exported.call)
        self.n_out = int(self.manifest["n_out"])
        self.single_output = bool(self.manifest["single_output"])
        self.input_specs = [
            (tuple(d["shape"]), d["dtype"]) for d in self.manifest["inputs"]]

    @property
    def num_inputs(self):
        return len(self.input_specs)

    @property
    def output_arity(self):
        return self.n_out

    @property
    def batch_size(self):
        """Leading dim of the first exported input (the batch bucket this
        artifact serves; exports are static-shape programs)."""
        return int(self.input_specs[0][0][0])

    def warmup(self):
        """Compile (and run once on zeros) ahead of traffic, so no serving
        thread ever hits tracing. Returns self."""
        self.run(*[_np.zeros(s, dtype=_np_dtype(d))
                   for s, d in self.input_specs])
        return self

    def lowered(self, *inputs):
        """The bucket program lowered at its exported shapes WITHOUT
        executing it: the inspection surface for
        `mx.inspect.inspect_step(model)` — fusion-level offender
        attribution of exactly the program `run()` dispatches. The
        lowering lands in the jit cache, so a later `run()`/`warmup()`
        does not recompile. `inputs` are optional (the exported shapes
        are fixed); when given they must match `input_specs` — lowering
        at any other shape would inspect a program `run()` never uses."""
        if inputs:
            if len(inputs) != len(self.input_specs):
                raise MXNetError(
                    f"ExportedModel.lowered got {len(inputs)} inputs, "
                    f"artifact expects {len(self.input_specs)}")
            arrs = []
            for a, (s, d) in zip(inputs, self.input_specs):
                a = _np.asarray(a)
                if tuple(a.shape) != tuple(s):
                    raise MXNetError(
                        f"ExportedModel.lowered input shape {a.shape} "
                        f"does not match the exported spec {tuple(s)} — "
                        f"the artifact's program is fixed-shape")
                arrs.append(a.astype(_np_dtype(d), copy=False))
        else:
            arrs = [_np.zeros(s, dtype=_np_dtype(d))
                    for s, d in self.input_specs]
        return self._call.lower(self._pbufs, self._key, *arrs)

    def compile_cache_size(self):
        """Entries in the jitted call's compile cache (1 after warmup; any
        growth in steady state is a retrace). -1 when the running jax
        version does not expose the counter."""
        return int(getattr(self._call, "_cache_size", lambda: -1)())

    def _check_inputs(self, inputs):
        if len(inputs) != len(self.input_specs):
            raise MXNetError(
                f"model takes {len(self.input_specs)} inputs, "
                f"got {len(inputs)}")
        arrs = []
        for i, (x, (shape, dtype)) in enumerate(
                zip(inputs, self.input_specs)):
            a = _np.asarray(getattr(x, "asnumpy", lambda: x)())
            if tuple(a.shape) != shape:
                raise MXNetError(
                    f"input {i}: shape {tuple(a.shape)} != exported "
                    f"{shape} (exports are static-shape programs)")
            if str(a.dtype) != dtype:
                a = a.astype(_np_dtype(dtype))
            arrs.append(a)
        return arrs

    def run(self, *inputs):
        """Execute the exported forward; returns np.ndarray or a tuple."""
        arrs = self._check_inputs(inputs)
        out_raw, _aux, _ = self._call(self._pbufs, self._key, *arrs)
        outs = tuple(_np.asarray(o) for o in out_raw)
        return outs[0] if self.single_output else outs

    def call_arrays(self, *arrs):
        """Traceable forward over jax arrays: safe to call inside another
        jit trace (SymbolBlock embedded in a hybridized parent) — no host
        transfer, no shape-check materialization. Returns the raw output
        tuple."""
        import jax.numpy as jnp
        cast = tuple(
            jnp.asarray(a, _np_dtype(dtype))
            for a, (_, dtype) in zip(arrs, self.input_specs))
        out_raw, _aux, _ = self._call(self._pbufs, self._key, *cast)
        return tuple(out_raw)


# --------------------------------------------------------------------------
# C ABI support functions (called from native/c_api.cc via the embedded
# interpreter). Handles crossing the boundary are ordinary Python objects
# whose refcounts the C side owns.
# --------------------------------------------------------------------------

def _capi_version():
    from . import __version__
    return __version__


def _capi_dtype_size(dtype_code):
    """Element width in bytes for a C-ABI dtype code (single source of
    truth for the boundary; c_api.cc queries this rather than keeping its
    own table)."""
    return int(_np_dtype(dtype_code).itemsize)


def _capi_ndarray_create(buf, shape, dtype_code):
    """bytes-like + shape list + reference dtype code -> NDArray."""
    from . import np as mxnp
    a = _np.frombuffer(bytes(buf), dtype=_np_dtype(dtype_code))
    a = a.reshape(tuple(shape))
    return mxnp.array(a)


def _capi_ndarray_zeros(shape, dtype_code):
    from . import np as mxnp
    return mxnp.zeros(tuple(shape), dtype=str(_np_dtype(dtype_code)))


def _capi_ndarray_shape(nd):
    return list(nd.shape)


def _capi_ndarray_dtype(nd):
    name = str(nd.dtype)
    if name not in DTYPE_TO_CODE:
        raise MXNetError(f"dtype {name} has no C ABI code")
    return DTYPE_TO_CODE[name]


def _capi_ndarray_tobytes(nd):
    return nd.asnumpy().tobytes()


def _capi_invoke(op_name, inputs, kwargs_json):
    """Generic imperative op dispatch (≙ MXImperativeInvokeEx,
    /root/reference/src/c_api/c_api_ndarray.cc:91): look the op up in the
    np/npx/nd namespaces, call with positional NDArray inputs + JSON
    kwargs, normalize to a list of NDArrays."""
    from . import np as mxnp, npx, nd
    from .ndarray import NDArray
    fn = None
    for ns in (mxnp, npx, nd):
        fn = getattr(ns, op_name, None)
        if fn is not None:
            break
    if fn is None:
        raise MXNetError(f"unknown operator {op_name!r}")
    kwargs = json.loads(kwargs_json) if kwargs_json else {}
    out = fn(*inputs, **kwargs)
    if isinstance(out, (list, tuple)):
        return [o if isinstance(o, NDArray) else mxnp.array(o) for o in out]
    return [out if isinstance(out, NDArray) else mxnp.array(out)]


def _capi_waitall():
    from .ndarray import waitall
    waitall()


# -- autograd group (≙ MXAutograd*, reference c_api.h:1308) ----------------
_GRAD_REQ_OF_CODE = {0: "null", 1: "write", 2: "write", 3: "add"}


def _capi_autograd_set_recording(flag):
    from . import autograd
    return int(autograd.set_recording(bool(flag)))


def _capi_autograd_set_training(flag):
    from . import autograd
    return int(autograd.set_training(bool(flag)))


def _capi_autograd_is_recording():
    from . import autograd
    return autograd.is_recording()


def _capi_autograd_is_training():
    from . import autograd
    return autograd.is_training()


def _capi_autograd_mark_variables(variables, req_codes):
    from . import autograd
    reqs = [_GRAD_REQ_OF_CODE[int(c)] for c in req_codes]
    for v, r in zip(variables, reqs):
        v.attach_grad(grad_req=r)
    return True


def _capi_autograd_backward(heads, head_grads, retain_graph):
    from . import autograd
    autograd.backward(list(heads),
                      list(head_grads) if head_grads is not None else None,
                      retain_graph=bool(retain_graph))
    return True


def _capi_autograd_backward_ex(heads, head_grads, variables, retain_graph,
                               create_graph, is_train):
    """≙ MXAutogradBackwardEx (c_api.h:1308): with `variables`, the
    autograd.grad path — returns new grad arrays; without, plain
    backward (grads land on marked variables)."""
    from . import autograd
    # per-element None entries mean default ones-like seeds; the backward
    # impl handles them directly
    hg = list(head_grads) if head_grads is not None else None
    if not variables:
        autograd.backward(list(heads), hg, retain_graph=bool(retain_graph),
                          create_graph=bool(create_graph),
                          train_mode=bool(is_train))
        return []
    return list(autograd.grad(list(heads), list(variables), head_grads=hg,
                              retain_graph=bool(retain_graph),
                              create_graph=bool(create_graph),
                              train_mode=bool(is_train)))


def _capi_ndarray_get_grad(nd):
    g = nd.grad
    if g is None:
        raise MXNetError("array has no gradient buffer "
                         "(not marked, or backward not run)")
    return g


# -- kvstore group (≙ MXKVStore*, reference c_api.h:2347) ------------------
def _capi_kv_create(type_str):
    from .kvstore import create
    return create(type_str)


def _capi_kv_init(kv, keys, vals, _priority):
    for k, v in zip(keys, vals):
        kv.init(int(k), v)
    return True


def _capi_kv_push(kv, keys, vals, priority):
    for k, v in zip(keys, vals):
        kv.push(int(k), v, priority=priority)
    return True


def _capi_kv_pull(kv, keys, outs, priority):
    for k, o in zip(keys, outs):
        kv.pull(int(k), out=o, priority=priority)
    return True


def _capi_kv_rank(kv):
    return int(kv.rank)


def _capi_kv_size(kv):
    return int(kv.num_workers)


def _capi_pred_create(jaxport_path, params_path, manifest_path):
    return ExportedModel(jaxport=jaxport_path, params=params_path,
                         manifest=manifest_path)


def _capi_pred_create_prefix(prefix):
    return ExportedModel(prefix)


def _capi_pred_num_inputs(model):
    return model.num_inputs


def _capi_pred_input_spec(model, i):
    shape, dtype = model.input_specs[i]
    return list(shape), DTYPE_TO_CODE[dtype]


def _capi_pred_forward(model, inputs):
    """NDArray inputs -> list of NDArray outputs (always a list)."""
    from . import np as mxnp
    out = model.run(*inputs)
    if not isinstance(out, tuple):
        out = (out,)
    return [mxnp.array(o) for o in out]


# ==========================================================================
# round-4 C ABI breadth (VERDICT-r3 Next #3): MXSymbol*, MXDataIter*/
# Dataset/Batchify, MXProfile*, MXEngine*, MXRecordIO*, and the NDArray /
# KVStore / misc tail. Same contract as above: plain functions over plain
# types; handles are the Python objects themselves.
# ==========================================================================

# -- NDArray tail ----------------------------------------------------------
def _capi_ndarray_create_none():
    from . import np as mxnp
    return mxnp.zeros((0,))


def _capi_ndarray_copy_from_bytes(nd, buf):
    a = _np.frombuffer(bytes(buf), dtype=str(nd.dtype)).reshape(nd.shape)
    nd[...] = a
    return True


def _capi_ndarray_at(nd, idx):
    return nd[int(idx)]


def _capi_ndarray_slice(nd, start, stop):
    return nd[int(start):int(stop)]


def _capi_ndarray_reshape(nd, shape, reverse=0):
    spec = [int(s) for s in shape]
    if int(reverse):
        # reference reverse inference: special values (0 = copy-dim,
        # -1 = infer) match from the RIGHT; flipping both views reduces
        # it to the forward rule
        cur = list(nd.shape)[::-1]
        spec = spec[::-1]
        out = []
        for i, d in enumerate(spec):
            out.append(cur[i] if d == 0 and i < len(cur) else d)
        return nd.reshape(tuple(out[::-1]))
    return nd.reshape(tuple(spec))


def _capi_ndarray_detach(nd):
    return nd.detach()


def _capi_ndarray_context(nd):
    dev = nd.device
    # reference dev_type codes: 1=cpu, 2=gpu; TPU reports as 6 (extension)
    code = {"cpu": 1, "gpu": 2, "tpu": 6}.get(dev.device_type, 1)
    return code, int(dev.device_id)


def _capi_ndarray_wait_to_read(nd):
    nd.wait_to_read()
    return True


def _capi_ndarray_storage_type(nd):
    """≙ NDArrayStorageType codes (include/mxnet/ndarray.h:62):
    default=0, row_sparse=1, csr=2."""
    from .ndarray.sparse import BaseSparseNDArray
    if isinstance(nd, BaseSparseNDArray):
        return {"row_sparse": 1, "csr": 2}[nd.stype]
    return 0


# ---- sparse storage group (≙ c_api.h:653-1077, sparse aux access) --------

def _capi_ndarray_create_sparse(storage_type, shape, dtype_code):
    from .ndarray import sparse as _sp
    stype = {1: "row_sparse", 2: "csr"}.get(int(storage_type))
    if stype is None:
        raise MXNetError(f"invalid sparse storage_type {storage_type}")
    return _sp.zeros(stype, tuple(shape), dtype=str(_np_dtype(dtype_code)))


def _sparse_aux_np(nd, i):
    """Aux array i of a sparse handle. CSR order ≙ csr::kIndPtr=0,
    csr::kIdx=1; RSP ≙ rowsparse::kIdx=0."""
    from .ndarray.sparse import CSRNDArray, RowSparseNDArray
    if isinstance(nd, CSRNDArray):
        if i == 0:
            return nd._indptr_np
        if i == 1:
            return nd._indices_np
    elif isinstance(nd, RowSparseNDArray) and i == 0:
        return nd._indices_np
    raise MXNetError(f"no aux array {i} on {type(nd).__name__}")


def _capi_ndarray_num_aux(nd):
    from .ndarray.sparse import CSRNDArray, RowSparseNDArray
    if isinstance(nd, CSRNDArray):
        return 2
    if isinstance(nd, RowSparseNDArray):
        return 1
    return 0


def _capi_ndarray_aux_type(nd, i):
    _sparse_aux_np(nd, i)      # validates the slot
    return DTYPE_TO_CODE["int64"]


class _HostNDArray:
    """Host-side array handle for the C boundary: sparse aux arrays are
    int64 by ABI contract, but the device stack (x64 disabled) would
    silently narrow them to int32 — so aux reads stay on the host."""

    def __init__(self, a):
        self._a = _np.ascontiguousarray(a)

    @property
    def shape(self):
        return self._a.shape

    @property
    def dtype(self):
        return self._a.dtype

    @property
    def ndim(self):
        return self._a.ndim

    @property
    def size(self):
        return self._a.size

    def asnumpy(self):
        return self._a

    def wait_to_read(self):
        return self


def _capi_ndarray_get_aux(nd, i):
    return _HostNDArray(_sparse_aux_np(nd, i))


def _capi_ndarray_get_data(nd):
    from .ndarray.sparse import BaseSparseNDArray
    if not isinstance(nd, BaseSparseNDArray):
        raise MXNetError("GetDataNDArray expects a sparse handle")
    return nd.data


def _capi_ndarray_sync_copy_from_ndarray(dst, src, i):
    """≙ MXNDArraySyncCopyFromNDArray: fill slot i of a sparse dst from a
    dense src (i == -1 -> data, else aux i). Aux writes may resize nnz;
    the paired data/indices slot is grown with it so the container stays
    structurally valid between the two copies."""
    import numpy as _onp

    from .ndarray.sparse import CSRNDArray, RowSparseNDArray
    a = src.asnumpy() if hasattr(src, "asnumpy") else _onp.asarray(src)
    if isinstance(dst, CSRNDArray):
        if i == -1:
            dst._data_np = a.astype(dst.dtype).ravel()
            if dst._indices_np.size != dst._data_np.size:
                dst._indices_np = _onp.resize(
                    dst._indices_np, dst._data_np.size)
        elif i == 0:
            dst._indptr_np = a.astype(_onp.int64).ravel()
        elif i == 1:
            dst._indices_np = a.astype(_onp.int64).ravel()
            if dst._data_np.size != dst._indices_np.size:
                dst._data_np = _onp.resize(dst._data_np,
                                           dst._indices_np.size)
        else:
            raise MXNetError(f"invalid slot {i} for csr")
        return True
    if isinstance(dst, RowSparseNDArray):
        if i == -1:
            dst._data_np = a.astype(dst.dtype).reshape(
                (-1,) + dst.shape[1:])
            if dst._indices_np.size != dst._data_np.shape[0]:
                dst._indices_np = _onp.resize(
                    dst._indices_np, dst._data_np.shape[0])
        elif i == 0:
            dst._indices_np = a.astype(_onp.int64).ravel()
            if dst._data_np.shape[0] != dst._indices_np.size:
                dst._data_np = _onp.resize(
                    dst._data_np,
                    (dst._indices_np.size,) + dst.shape[1:])
        else:
            raise MXNetError(f"invalid slot {i} for row_sparse")
        return True
    if i == -1:
        from . import np as mxnp
        dst[:] = mxnp.array(a)
        return True
    raise MXNetError("aux copy needs a sparse destination")


def _capi_kv_pull_row_sparse(kv, keys, outs, row_ids, priority):
    """≙ MXKVStorePullRowSparse (c_api.h:2569); keys may be int or str."""
    for k, out, rid in zip(keys, outs, row_ids):
        kv.row_sparse_pull(k, out=out, row_ids=rid, priority=priority)
    return True


def _capi_ndarray_check_format(nd, full_check):
    """≙ MXNDArraySyncCheckFormat: sparse handles validate their aux
    invariants; dense handles are trivially valid."""
    if hasattr(nd, "check_format"):
        nd.check_format(full_check=bool(full_check))
    return True


def _capi_ndarray_save(fname, arrays, names):
    from .ndarray import save
    if names:
        save(fname, dict(zip(names, arrays)))
    else:
        save(fname, list(arrays))
    return True


def _capi_ndarray_load(fname):
    from .ndarray import load
    from . import np as mxnp
    data = load(fname)
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        names, arrays = [], list(data)
    return names, arrays


def _capi_ndarray_legacy_save(fname, arrays, names):
    """Write the reference binary .params container."""
    from .gluon.model_zoo.model_store import save_params_file
    save_params_file(fname, {n: a.asnumpy()
                             for n, a in zip(names, arrays)})
    return True


def _capi_random_seed(seed):
    from . import random as _random
    _random.seed(int(seed))
    return True


def _capi_list_all_op_names():
    out = set()
    from . import np as mxnp, npx
    for ns in (mxnp, npx):
        for nm in dir(ns):
            if not nm.startswith("_") and callable(getattr(ns, nm, None)):
                out.add(nm)
    return sorted(out)


def _capi_lib_features():
    from .runtime import Features
    return [(f.name, bool(f.enabled)) for f in Features().values()]


def _capi_device_count(kind):
    import jax
    try:
        if kind == "gpu":
            return 0       # TPU build: no CUDA devices, by design
        if kind == "tpu":
            return sum(1 for d in jax.devices() if d.platform == "tpu")
        return len(jax.devices())
    except RuntimeError:
        return 0


def _capi_memory_info(_dev_id):
    # (used, limit) per the C API contract. device_memory_info returns a
    # MemoryInfo namedtuple — the old code treated it as a dict (a latent
    # AttributeError) and a backend without bytes_limit now reads as an
    # explicit (0, 0) don't-know instead of fake zero headroom.
    from .device import device_memory_info
    info = device_memory_info()
    if not info.known:
        return 0, 0
    return int(info.total - info.free), int(info.total)


def _capi_is_numpy_shape():
    return 1   # np-shape semantics are the only mode in this framework


def _capi_is_numpy_default_dtype():
    return 1


# -- symbol group (≙ MXSymbol*, c_api.h:1448-2100) -------------------------
def _capi_symbol_create_variable(name):
    from . import symbol as sym
    return sym.Variable(name)


class _AtomicSymbol:
    """Uncomposed op template (CreateAtomicSymbol -> Compose two-step)."""

    def __init__(self, op, attrs):
        self.op = op
        self.attrs = attrs


def _capi_symbol_create_atomic(op_name, keys, vals):
    from . import symbol as sym
    if op_name not in sym.list_legacy_ops():
        raise MXNetError(f"unknown legacy op {op_name!r}")
    return _AtomicSymbol(op_name, dict(zip(keys, vals)))


def _capi_symbol_compose(holder, name, keys, args):
    """In-place compose (reference MXSymbolCompose mutates the handle):
    an _AtomicSymbol holder BECOMES the composed Symbol; a Symbol holder
    gets its free variables substituted."""
    from . import symbol as sym
    if isinstance(holder, _AtomicSymbol):
        maker = getattr(sym, holder.op)
        kwargs = dict(holder.attrs)
        if keys:
            composed = maker(name=name or None,
                             **dict(zip(keys, args)), **kwargs)
        else:
            composed = maker(*args, name=name or None, **kwargs)
        holder.__class__ = sym.Symbol
        holder.__dict__.clear()
        holder._outputs = list(composed._outputs)
        return True
    if keys:
        kwargs = dict(zip(keys, args))
    else:
        # positional composition: bind free variables in graph input order
        kwargs = dict(zip(holder.list_inputs(), args))
    composed = holder.compose(**kwargs)
    holder._outputs = list(composed._outputs)
    return True


def _capi_symbol_from_json(json_str):
    from . import symbol as sym
    return sym.load_json(json_str)


def _capi_symbol_to_json(s):
    return s.tojson()


def _capi_symbol_from_file(fname):
    from . import symbol as sym
    return sym.load(fname)


def _capi_symbol_save_file(s, fname):
    s.save(fname)
    return True


def _capi_symbol_copy(s):
    from . import symbol as sym
    return sym.load_json(s.tojson())


def _capi_symbol_print(s):
    return s.debug_str()


def _capi_symbol_get_name(s):
    return s.name or ""


def _capi_symbol_get_attr(s, key):
    return s.attr(key)   # None = absent; "" is a present empty value


def _capi_symbol_set_attr(s, key, value):
    s._set_attr(**{key: value})
    return True


def _capi_symbol_list_attr(s):
    flat = []
    for nm, attrs in s.attr_dict().items():
        for k, v in attrs.items():
            flat.extend([f"{nm}${k}", str(v)])
    return flat


def _capi_symbol_list_attr_shallow(s):
    flat = []
    for k, v in s.list_attr().items():
        flat.extend([k, str(v)])
    return flat


def _capi_symbol_list_arguments(s):
    return s.list_arguments()


def _capi_symbol_list_outputs(s):
    return s.list_outputs()


def _capi_symbol_list_aux(s):
    return s.list_auxiliary_states()


def _capi_symbol_get_internals(s):
    return s.get_internals()


def _capi_symbol_get_children(s):
    c = s.get_children()
    if c is None:
        raise MXNetError("symbol has no children")
    return c


def _capi_symbol_get_output(s, idx):
    return s[int(idx)]


def _capi_symbol_num_outputs(s):
    return s.num_outputs


def _capi_symbol_get_inputs(s):
    from . import symbol as sym
    return sym.Group([sym.Variable(n) for n in s.list_inputs()])


def _capi_symbol_create_group(symbols):
    from . import symbol as sym
    return sym.Group(list(symbols))


def _capi_symbol_infer_shape(s, names, shapes, partial):
    """Returns (arg_shapes, out_shapes, aux_shapes, complete) with -1 rows
    for still-unknown entries when partial."""
    kwargs = {n: tuple(sh) for n, sh in zip(names, shapes)}
    try:
        arg, out, aux = s.infer_shape(**kwargs)
    except MXNetError:
        if not partial:
            raise
        n_args = len(s.list_arguments())
        n_aux = len(s.list_auxiliary_states())
        return ([None] * n_args, [None] * s.num_outputs, [None] * n_aux, 0)
    complete = int(all(x is not None for x in list(arg) + list(aux)))
    return list(arg), list(out), list(aux), complete


def _capi_symbol_infer_type(s, names, type_codes=None):
    if type_codes:
        kwargs = {n: str(_np_dtype(c)) for n, c in zip(names, type_codes)}
    else:
        kwargs = {n: "float32" for n in names}
    arg, out, aux = s.infer_type(**kwargs)
    to_code = lambda ds: [DTYPE_TO_CODE[str(_np.dtype(d))] for d in ds]
    return to_code(arg), to_code(out), to_code(aux)


def _capi_symbol_list_atomic_creators():
    from . import symbol as sym
    return sym.list_legacy_ops()


def _capi_symbol_atomic_info(op_name):
    from . import symbol as sym
    if op_name not in sym.list_legacy_ops():
        raise MXNetError(f"unknown legacy op {op_name!r}")
    doc = f"legacy graph op {op_name} (executor: symbol/__init__.py)"
    return op_name, doc


# -- data iterator / dataset / batchify groups -----------------------------
_DATAITER_CREATORS = ("NDArrayIter", "ImageRecordIter", "CSVIter",
                      "LibSVMIter")


def _capi_list_data_iters():
    return list(_DATAITER_CREATORS)


def _capi_data_iter_info(name):
    if name not in _DATAITER_CREATORS:
        raise MXNetError(f"unknown iterator {name!r}")
    return name, f"{name} (io/__init__.py, ≙ reference src/io/iter_*.cc)"


def _capi_data_iter_create(name, keys, vals):
    from . import io as io_mod
    import ast
    if name not in _DATAITER_CREATORS:
        raise MXNetError(f"unknown iterator {name!r}")
    kwargs = {}
    for k, v in zip(keys, vals):
        try:
            kwargs[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            kwargs[k] = v
    return _IterHandle(getattr(io_mod, name)(**kwargs))


class _IterHandle:
    """Current-batch cursor over a DataIter (the C iteration contract:
    Next() then GetData()/GetLabel()/GetPadNum())."""

    def __init__(self, it):
        self.it = it
        self.batch = None

    def next(self):
        try:
            self.batch = next(self.it)
            return 1
        except StopIteration:
            self.batch = None
            return 0

    def reset(self):
        self.it.reset()
        self.batch = None


def _capi_data_iter_next(h):
    return h.next()


def _capi_data_iter_before_first(h):
    h.reset()
    return True


def _capi_data_iter_data(h):
    if h.batch is None:
        raise MXNetError("no current batch: call MXDataIterNext first")
    return h.batch.data[0]


def _capi_data_iter_label(h):
    if h.batch is None:
        raise MXNetError("no current batch: call MXDataIterNext first")
    if not h.batch.label:
        raise MXNetError("iterator has no labels")
    return h.batch.label[0]


def _capi_data_iter_items(h):
    if h.batch is None:
        raise MXNetError("no current batch: call MXDataIterNext first")
    return list(h.batch.data) + list(h.batch.label or [])


def _capi_data_iter_pad_num(h):
    if h.batch is None:
        return 0
    return int(getattr(h.batch, "pad", 0) or 0)


def _capi_data_iter_index(h):
    if h.batch is None or getattr(h.batch, "index", None) is None:
        return []
    return [int(i) for i in h.batch.index]


def _capi_data_iter_len_hint(h):
    try:
        return len(h.it)
    except TypeError:
        return -1


_DATASET_CREATORS = ("ArrayDataset", "RecordFileDataset", "ImageRecordDataset")


def _capi_list_datasets():
    return list(_DATASET_CREATORS)


def _capi_dataset_info(name):
    if name not in _DATASET_CREATORS:
        raise MXNetError(f"unknown dataset {name!r}")
    return name, f"{name} (gluon/data, ≙ reference gluon.data datasets)"


def _capi_dataset_create(name, keys, vals):
    import ast
    from .gluon import data as gdata
    kwargs = {}
    for k, v in zip(keys, vals):
        try:
            kwargs[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            kwargs[k] = v
    if name == "ArrayDataset":
        import numpy as np
        arrs = [np.asarray(v) for k, v in sorted(kwargs.items())]
        return gdata.ArrayDataset(*arrs)
    if name == "RecordFileDataset":
        return gdata.RecordFileDataset(**kwargs)
    if name == "ImageRecordDataset":
        return gdata.vision.ImageRecordDataset(**kwargs)
    raise MXNetError(f"unknown dataset {name!r}")


def _capi_dataset_len(ds):
    return len(ds)


def _capi_dataset_get_items(ds, idx):
    from . import np as mxnp
    from .ndarray import NDArray
    item = ds[int(idx)]
    if not isinstance(item, tuple):
        item = (item,)
    out = []
    for x in item:
        if isinstance(x, NDArray):
            out.append(x)
        elif isinstance(x, bytes):
            out.append(mxnp.array(_np.frombuffer(x, _np.uint8)))
        else:
            out.append(mxnp.array(_np.asarray(x)))
    return out


_BATCHIFY_FUNCS = ("Stack", "Pad", "Group")


def _capi_list_batchify():
    return list(_BATCHIFY_FUNCS)


def _capi_batchify_info(name):
    if name not in _BATCHIFY_FUNCS:
        raise MXNetError(f"unknown batchify {name!r}")
    return name, f"batchify.{name} (gluon/data/batchify.py)"


def _capi_batchify_create(name, keys, vals):
    from .gluon.data import batchify
    kw = dict(zip(keys, vals))
    if name == "Stack":
        return batchify.Stack()
    if name == "Pad":
        unknown = set(kw) - {"val", "pad_val", "axis", "dtype"}
        if unknown:
            raise MXNetError(f"Pad batchify: unknown params {sorted(unknown)}")
        return batchify.Pad(
            axis=int(kw.get("axis", 0)),
            val=float(kw.get("val", kw.get("pad_val", 0))),
            dtype=kw.get("dtype") or None)
    if name == "Group":
        # components default to Stack x N (N from 'size'); richer nesting
        # composes Python-side
        n = int(kw.get("size", 2))
        return batchify.Group(*[batchify.Stack() for _ in range(n)])
    raise MXNetError(f"unknown batchify {name!r}")


def _capi_batchify_invoke(fn, samples):
    from .gluon.data import batchify as B
    samples = list(samples)
    if isinstance(fn, B.Group):
        # the C wire is a FLAT handle array of num_samples*k entries
        # (sample-major, ≙ MXBatchifyFunctionInvoke's inputs layout);
        # regroup into per-sample component tuples
        k = len(fn._fns)
        if k and len(samples) % k:
            raise MXNetError(
                f"Group batchify got {len(samples)} arrays, not a "
                f"multiple of its {k} components")
        samples = [tuple(samples[i:i + k])
                   for i in range(0, len(samples), k)]
    out = fn(samples)
    if not isinstance(out, (list, tuple)):
        out = (out,)
    return list(out)


# -- profiler group (≙ MXProfile*, c_api.h:246-600) ------------------------
def _capi_profiler_set_config(keys, vals):
    from . import profiler
    profiler.set_config(**dict(zip(keys, vals)))
    return True


def _capi_profiler_set_state(state):
    from . import profiler
    if int(state):
        profiler.start()
    else:
        profiler.stop()
    return True


def _capi_profiler_pause(paused):
    from . import profiler
    if int(paused):
        profiler.pause()
    else:
        profiler.resume()
    return True


def _capi_profiler_dump(finished, filename):
    from . import profiler
    profiler.dump(finished=bool(finished),
                  filename=filename if filename else None)
    return True


def _capi_profiler_dumps(reset):
    from . import profiler
    return profiler.dumps(reset=bool(reset))


def _capi_profile_create_domain(name):
    from . import profiler
    return profiler.Domain(name)


def _capi_profile_create_task(domain, name):
    from . import profiler
    return profiler.Task(name, domain)


def _capi_profile_create_frame(domain, name):
    from . import profiler
    return profiler.Frame(name, domain)


def _capi_profile_create_event(name):
    from . import profiler
    return profiler.Event(name)


def _capi_profile_create_counter(domain, name, value):
    from . import profiler
    c = profiler.Counter(domain, name)
    if value is not None:
        c.set_value(int(value))
    return c


def _capi_profile_duration_start(obj):
    obj.start()
    return True


def _capi_profile_duration_stop(obj):
    obj.stop()
    return True


def _capi_profile_set_counter(c, value):
    c.set_value(int(value))
    return True


def _capi_profile_adjust_counter(c, delta):
    c.increment(int(delta)) if delta >= 0 else c.decrement(-int(delta))
    return True


def _capi_profile_set_marker(domain, name, scope):
    from . import profiler
    profiler.Marker(domain, name).mark(scope or "process")
    return True


# -- engine group (≙ MXEngine*, c_api.h:3028-3119) -------------------------
def _capi_engine_set_bulk_size(size):
    from . import engine
    # set_bulk_size returns the previously CONFIGURED value — not
    # effective_bulk_size(), which NaiveEngine forces to 0 and would make
    # the save/restore pattern permanently disable bulking
    return int(engine.set_bulk_size(int(size)))


import ctypes as _ctypes

# stable no-op completion callback for async engine pushes (kept as a
# module global so the function pointer outlives every call)
_ENGINE_NOOP_COMPLETE = _ctypes.CFUNCTYPE(None, _ctypes.c_void_p)(
    lambda _param: None)


def _capi_engine_push(fn_addr, param_addr, deleter_addr, is_async):
    """Execute a C callback through the engine (≙ MXEnginePushSync/Async).

    The TPU runtime has no user-visible dependency engine: callbacks run
    inline after the current bulking segment flushes — the NaiveEngine
    contract, which the reference also honors for sync pushes. The
    caller's param deleter runs after the function completes (reference
    EngineFuncParamDeleter contract)."""
    import ctypes
    from .ndarray import waitall
    waitall()
    try:
        if int(is_async):
            # async signature: void (*)(void* engine, void* param, void* cb).
            # cb must be a CALLABLE completion callback (the reference
            # contract requires the func to invoke it) — never NULL.
            CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_void_p,
                                  ctypes.c_void_p)
            CB(fn_addr)(None, ctypes.c_void_p(param_addr or 0),
                        ctypes.cast(_ENGINE_NOOP_COMPLETE,
                                    ctypes.c_void_p))
        else:
            CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
            CB(fn_addr)(ctypes.c_void_p(param_addr or 0))
    finally:
        if deleter_addr:
            DEL = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
            DEL(deleter_addr)(ctypes.c_void_p(param_addr or 0))
    return True


# -- recordio group (≙ MXRecordIO*, c_api.h:2810-2900) ---------------------
def _capi_recordio_writer_create(path):
    from .recordio import MXRecordIO
    return MXRecordIO(path, "w")


def _capi_recordio_reader_create(path):
    from .recordio import MXRecordIO
    return MXRecordIO(path, "r")


def _capi_recordio_close(rec):
    rec.close()
    return True


def _capi_recordio_write(rec, buf):
    rec.write(bytes(buf))
    return True


def _capi_recordio_read(rec):
    # None = EOF; b"" is a legitimate zero-length record — the C side
    # distinguishes them (EOF -> *buf NULL, empty record -> non-NULL)
    return rec.read()


def _capi_recordio_tell(rec):
    return int(rec.tell())


def _capi_recordio_seek(rec, pos):
    rec.seek(int(pos))
    return True


# -- kvstore tail ----------------------------------------------------------
def _capi_kv_type(kv):
    return kv.type


def _capi_kv_barrier(kv):
    kv.barrier()
    return True


def _capi_kv_pushpull(kv, keys, invals, outvals, priority):
    # keys arrive as ints from the int-keyed entry points and as strs
    # from the Ex variants; the store keeps each key space verbatim
    for k, vin, vout in zip(keys, invals, outvals):
        kv.pushpull(k, vin, out=vout, priority=priority)
    return True


def _capi_kv_broadcast(kv, keys, invals, outvals, priority):
    for k, vin, vout in zip(keys, invals, outvals):
        kv.broadcast(k, vin, out=vout, priority=priority)
    return True


def _capi_kv_set_compression(kv, keys, vals):
    params = {}
    for k, v in zip(keys, vals):
        params[k] = float(v) if k == "threshold" else v
    kv.set_gradient_compression(params)
    return True


def _capi_kv_init_str(kv, keys, vals):
    for k, v in zip(keys, vals):
        kv.init(k, v)
    return True


def _capi_kv_push_str(kv, keys, vals, priority):
    for k, v in zip(keys, vals):
        kv.push(k, v, priority=priority)
    return True


def _capi_kv_pull_str(kv, keys, outs, priority):
    for k, o in zip(keys, outs):
        kv.pull(k, out=o, priority=priority)
    return True


def _capi_kv_set_updater(kv, fn_addr, handle_addr):
    """C-callback updater (≙ MXKVStoreSetUpdater): the callback receives
    (key, recv NDArrayHandle, local NDArrayHandle, user handle). Handles
    are borrowed PyObject* valid for the duration of the call."""
    import ctypes
    CB = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_void_p,
                          ctypes.c_void_p, ctypes.c_void_p)
    cb = CB(fn_addr)

    def updater(key, recv, local):
        cb(int(key), id(recv), id(local),
           ctypes.c_void_p(handle_addr or 0))

    kv.set_updater(updater)
    return True


def _capi_kv_set_updater_ex(kv, int_addr, str_addr, handle_addr):
    """≙ MXKVStoreSetUpdaterEx: int keys dispatch to the int callback,
    string keys to the string callback (const char* first arg)."""
    import ctypes
    ICB = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_void_p,
                           ctypes.c_void_p, ctypes.c_void_p)
    SCB = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_void_p,
                           ctypes.c_void_p, ctypes.c_void_p)
    icb = ICB(int_addr) if int_addr else None
    scb = SCB(str_addr) if str_addr else None

    def updater(key, recv, local):
        h = ctypes.c_void_p(handle_addr or 0)
        if isinstance(key, str):
            if scb is None:
                raise MXNetError(
                    "string-keyed update but no string updater registered")
            scb(key.encode(), id(recv), id(local), h)
        else:
            if icb is None:
                raise MXNetError(
                    "int-keyed update but no int updater registered")
            icb(int(key), id(recv), id(local), h)

    kv.set_updater(updater)
    return True


def _capi_kv_is_worker(_kv):
    return 1   # SPMD runtime: every process is a worker (no server nodes)


def _capi_kv_is_server(_kv):
    return 0


def _capi_kv_is_scheduler(_kv):
    return 0


def _capi_kv_num_dead(_kv, _node_id):
    return 0   # PJRT surfaces failures as errors, not dead-node counts


def _capi_load_lib(path, verbose=0):
    """≙ MXLoadLib: load a Python or native (.so) extension."""
    from . import library
    library.load(path, verbose=bool(verbose))
    return True
