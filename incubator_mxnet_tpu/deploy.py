"""Deployment runtime: load and execute `HybridBlock.export` artifacts.

Capability equivalent of the reference's predict/C-API stack
(`/root/reference/include/mxnet/c_predict_api.h`,
`/root/reference/src/c_api/c_predict_api.cc:120-310`): a self-contained
loader that serves inference from the exported artifact triple
(`<prefix>.jaxport` + `<prefix>.params.npz` + `<prefix>.deploy.json`)
without the model's Python class on the import path. It backs three
consumers:

  * Python — `ExportedModel` directly, or `gluon.SymbolBlock.imports`
  * the C ABI — `native/c_api.cc` (libmxtpu.so), the stable non-Python
    boundary playing the role of the reference's 240-function c_api.h
  * the C++ frontend — `cpp_package/include/mxtpu/*.hpp` (≙ cpp-package)

TPU-native design: the executable artifact is a versioned `jax.export`
serialization (StableHLO inside, lowered for both cpu and tpu) run through
one `jax.jit` on the ambient PJRT client; there is no NNVM graph, executor,
or ps-lite layer to re-create. The `_capi_*` functions at the bottom are
the C ABI's internal entry points — plain functions over plain types so the
embedded-interpreter side (c_api.cc) stays a thin marshalling layer.
"""
from __future__ import annotations

import json
import os

import numpy as _np

from .base import MXNetError

# Reference dtype codes (mshadow/base.h kFloat32..; c_api callers use these
# integers on the wire). bfloat16 appended at its reference index (12).
DTYPE_CODES = {
    0: "float32", 1: "float64", 2: "float16", 3: "uint8",
    4: "int32", 5: "int8", 6: "int64", 7: "bool",
    8: "int16", 9: "uint16", 10: "uint32", 11: "uint64",
    12: "bfloat16",
}
DTYPE_TO_CODE = {v: k for k, v in DTYPE_CODES.items()}


def _np_dtype(code_or_name):
    if isinstance(code_or_name, (int, _np.integer)):
        name = DTYPE_CODES.get(int(code_or_name))
        if name is None:
            raise MXNetError(f"unknown dtype code {code_or_name}")
    else:
        name = str(code_or_name)
    if name == "bfloat16":
        import ml_dtypes
        return _np.dtype(ml_dtypes.bfloat16)
    return _np.dtype(name)


class ExportedModel:
    """A loaded, runnable export artifact (≙ the reference PredictorHandle).

    Usage::

        model = ExportedModel("model-0000")      # or explicit paths
        out = model.run(x)                       # np.ndarray in, out
    """

    def __init__(self, prefix=None, *, jaxport=None, params=None,
                 manifest=None):
        if prefix is not None:
            jaxport = jaxport or f"{prefix}.jaxport"
            params = params or f"{prefix}.params.npz"
            manifest = manifest or f"{prefix}.deploy.json"
        if not (jaxport and params and manifest):
            raise MXNetError(
                "ExportedModel needs a prefix or explicit jaxport=, "
                "params=, manifest= paths")
        for p in (jaxport, params, manifest):
            if not os.path.exists(p):
                raise MXNetError(f"export artifact missing: {p}")

        with open(manifest) as f:
            self.manifest = json.load(f)
        if self.manifest.get("format_version") != 1:
            raise MXNetError(
                f"unsupported deploy manifest version "
                f"{self.manifest.get('format_version')!r}")

        import jax
        import jax.export as jexp
        with open(jaxport, "rb") as f:
            self._exported = jexp.deserialize(f.read())
        loaded = _np.load(params, allow_pickle=False)
        try:
            self._pbufs = tuple(
                jax.numpy.asarray(loaded[name])
                for name in self.manifest["params"])
        except KeyError as e:
            raise MXNetError(
                f"parameter {e} listed in manifest but absent from "
                f"{params}") from e
        self._key = jax.random.PRNGKey(0)
        self._call = jax.jit(self._exported.call)
        self.n_out = int(self.manifest["n_out"])
        self.single_output = bool(self.manifest["single_output"])
        self.input_specs = [
            (tuple(d["shape"]), d["dtype"]) for d in self.manifest["inputs"]]

    @property
    def num_inputs(self):
        return len(self.input_specs)

    @property
    def output_arity(self):
        return self.n_out

    def _check_inputs(self, inputs):
        if len(inputs) != len(self.input_specs):
            raise MXNetError(
                f"model takes {len(self.input_specs)} inputs, "
                f"got {len(inputs)}")
        arrs = []
        for i, (x, (shape, dtype)) in enumerate(
                zip(inputs, self.input_specs)):
            a = _np.asarray(getattr(x, "asnumpy", lambda: x)())
            if tuple(a.shape) != shape:
                raise MXNetError(
                    f"input {i}: shape {tuple(a.shape)} != exported "
                    f"{shape} (exports are static-shape programs)")
            if str(a.dtype) != dtype:
                a = a.astype(_np_dtype(dtype))
            arrs.append(a)
        return arrs

    def run(self, *inputs):
        """Execute the exported forward; returns np.ndarray or a tuple."""
        arrs = self._check_inputs(inputs)
        out_raw, _aux, _ = self._call(self._pbufs, self._key, *arrs)
        outs = tuple(_np.asarray(o) for o in out_raw)
        return outs[0] if self.single_output else outs

    def call_arrays(self, *arrs):
        """Traceable forward over jax arrays: safe to call inside another
        jit trace (SymbolBlock embedded in a hybridized parent) — no host
        transfer, no shape-check materialization. Returns the raw output
        tuple."""
        import jax.numpy as jnp
        cast = tuple(
            jnp.asarray(a, _np_dtype(dtype))
            for a, (_, dtype) in zip(arrs, self.input_specs))
        out_raw, _aux, _ = self._call(self._pbufs, self._key, *cast)
        return tuple(out_raw)


# --------------------------------------------------------------------------
# C ABI support functions (called from native/c_api.cc via the embedded
# interpreter). Handles crossing the boundary are ordinary Python objects
# whose refcounts the C side owns.
# --------------------------------------------------------------------------

def _capi_version():
    from . import __version__
    return __version__


def _capi_dtype_size(dtype_code):
    """Element width in bytes for a C-ABI dtype code (single source of
    truth for the boundary; c_api.cc queries this rather than keeping its
    own table)."""
    return int(_np_dtype(dtype_code).itemsize)


def _capi_ndarray_create(buf, shape, dtype_code):
    """bytes-like + shape list + reference dtype code -> NDArray."""
    from . import np as mxnp
    a = _np.frombuffer(bytes(buf), dtype=_np_dtype(dtype_code))
    a = a.reshape(tuple(shape))
    return mxnp.array(a)


def _capi_ndarray_zeros(shape, dtype_code):
    from . import np as mxnp
    return mxnp.zeros(tuple(shape), dtype=str(_np_dtype(dtype_code)))


def _capi_ndarray_shape(nd):
    return list(nd.shape)


def _capi_ndarray_dtype(nd):
    name = str(nd.dtype)
    if name not in DTYPE_TO_CODE:
        raise MXNetError(f"dtype {name} has no C ABI code")
    return DTYPE_TO_CODE[name]


def _capi_ndarray_tobytes(nd):
    return nd.asnumpy().tobytes()


def _capi_invoke(op_name, inputs, kwargs_json):
    """Generic imperative op dispatch (≙ MXImperativeInvokeEx,
    /root/reference/src/c_api/c_api_ndarray.cc:91): look the op up in the
    np/npx/nd namespaces, call with positional NDArray inputs + JSON
    kwargs, normalize to a list of NDArrays."""
    from . import np as mxnp, npx, nd
    from .ndarray import NDArray
    fn = None
    for ns in (mxnp, npx, nd):
        fn = getattr(ns, op_name, None)
        if fn is not None:
            break
    if fn is None:
        raise MXNetError(f"unknown operator {op_name!r}")
    kwargs = json.loads(kwargs_json) if kwargs_json else {}
    out = fn(*inputs, **kwargs)
    if isinstance(out, (list, tuple)):
        return [o if isinstance(o, NDArray) else mxnp.array(o) for o in out]
    return [out if isinstance(out, NDArray) else mxnp.array(out)]


def _capi_waitall():
    from .ndarray import waitall
    waitall()


# -- autograd group (≙ MXAutograd*, reference c_api.h:1308) ----------------
_GRAD_REQ_OF_CODE = {0: "null", 1: "write", 2: "write", 3: "add"}


def _capi_autograd_set_recording(flag):
    from . import autograd
    return int(autograd.set_recording(bool(flag)))


def _capi_autograd_set_training(flag):
    from . import autograd
    return int(autograd.set_training(bool(flag)))


def _capi_autograd_is_recording():
    from . import autograd
    return autograd.is_recording()


def _capi_autograd_is_training():
    from . import autograd
    return autograd.is_training()


def _capi_autograd_mark_variables(variables, req_codes):
    from . import autograd
    reqs = [_GRAD_REQ_OF_CODE[int(c)] for c in req_codes]
    for v, r in zip(variables, reqs):
        v.attach_grad(grad_req=r)
    return True


def _capi_autograd_backward(heads, head_grads, retain_graph):
    from . import autograd
    autograd.backward(list(heads),
                      list(head_grads) if head_grads is not None else None,
                      retain_graph=bool(retain_graph))
    return True


def _capi_ndarray_get_grad(nd):
    g = nd.grad
    if g is None:
        raise MXNetError("array has no gradient buffer "
                         "(not marked, or backward not run)")
    return g


# -- kvstore group (≙ MXKVStore*, reference c_api.h:2347) ------------------
def _capi_kv_create(type_str):
    from .kvstore import create
    return create(type_str)


def _capi_kv_init(kv, keys, vals, _priority):
    for k, v in zip(keys, vals):
        kv.init(int(k), v)
    return True


def _capi_kv_push(kv, keys, vals, priority):
    for k, v in zip(keys, vals):
        kv.push(int(k), v, priority=priority)
    return True


def _capi_kv_pull(kv, keys, outs, priority):
    for k, o in zip(keys, outs):
        kv.pull(int(k), out=o, priority=priority)
    return True


def _capi_kv_rank(kv):
    return int(kv.rank)


def _capi_kv_size(kv):
    return int(kv.num_workers)


def _capi_pred_create(jaxport_path, params_path, manifest_path):
    return ExportedModel(jaxport=jaxport_path, params=params_path,
                         manifest=manifest_path)


def _capi_pred_create_prefix(prefix):
    return ExportedModel(prefix)


def _capi_pred_num_inputs(model):
    return model.num_inputs


def _capi_pred_input_spec(model, i):
    shape, dtype = model.input_specs[i]
    return list(shape), DTYPE_TO_CODE[dtype]


def _capi_pred_forward(model, inputs):
    """NDArray inputs -> list of NDArray outputs (always a list)."""
    from . import np as mxnp
    out = model.run(*inputs)
    if not isinstance(out, tuple):
        out = (out,)
    return [mxnp.array(o) for o in out]
