"""mx.util (≙ python/mxnet/util.py): np-mode decorators, env helpers.

The numpy frontend is always-on in this framework (there is no legacy
nd/symbol split to toggle), so the np_shape/np_array machinery reduces to
compatibility no-ops that keep reference scripts running unchanged.
"""
from __future__ import annotations

import functools
import os

from .base import get_env, set_env

__all__ = ["use_np", "use_np_shape", "use_np_array", "np_shape", "np_array",
           "is_np_shape", "is_np_array", "set_np", "reset_np", "getenv",
           "setenv", "get_max_supported_compute_capability",
           "default_array", "makedirs"]


def is_np_shape():
    return True


def is_np_array():
    return True


def set_np(shape=True, array=True, dtype=None):
    pass


def reset_np():
    pass


class _NoOpScope:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __call__(self, func):
        return func


def np_shape(active=True):
    return _NoOpScope()


def np_array(active=True):
    return _NoOpScope()


def use_np(func):
    """Decorator (≙ mx.util.use_np): numpy semantics are the default."""
    return func


use_np_shape = use_np
use_np_array = use_np


def getenv(name):
    return get_env(name)


def setenv(name, value):
    set_env(name, value)


def get_max_supported_compute_capability():
    return 0  # CUDA concept; no equivalent on TPU


def default_array(source_array, ctx=None, dtype=None):
    from .ndarray import array
    return array(source_array, device=ctx, dtype=dtype)


def makedirs(d):
    os.makedirs(os.path.expanduser(d), exist_ok=True)
