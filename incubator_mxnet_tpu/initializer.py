"""Weight initializers (≙ python/mxnet/initializer.py).

Reference surface: Zero, One, Constant, Uniform, Normal, Orthogonal, Xavier,
MSRAPrelu, Bilinear, LSTMBias, Mixed + the `@register` alias registry so
string names ("xavier", "msra", ...) resolve in Parameter(init=...).

TPU-native: initializers produce numpy arrays host-side (they run once, at
init time — no reason to compile them), then the Parameter places them on
device. Stateless; seeded from mx.random's global seed.
"""
from __future__ import annotations

import math
import re

import numpy as _np

__all__ = [
    "Initializer", "register", "create", "Zero", "One", "Constant", "Uniform",
    "Normal", "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias",
    "Mixed", "InitDesc",
]

_REGISTRY = {}


def register(klass):
    """≙ mx.init.register: makes the class resolvable by lowercase name."""
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(init, **kwargs):
    """Resolve an initializer from an instance, a string name, or None."""
    if init is None:
        return Uniform()
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        name = init.lower()
        if name not in _REGISTRY:
            from .base import MXNetError
            raise MXNetError(f"unknown initializer {init!r}; "
                             f"registered: {sorted(_REGISTRY)}")
        return _REGISTRY[name](**kwargs)
    raise TypeError(f"cannot create initializer from {type(init)}")


class InitDesc(str):
    """Parameter-name descriptor passed to initializers; carries attrs
    (≙ mxnet.init.InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    """Base initializer (≙ python/mxnet/initializer.py:93)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, shape, dtype, rng):
        """Dispatch on the parameter name suffix the way the reference's
        InitDesc pattern matching does (initializer.py `__call__`)."""
        name = str(name)
        if name.endswith("gamma") or "weight_v" in name:
            return self._init_one(shape, dtype)
        if name.endswith("beta") or name.endswith("bias"):
            return self._init_zero(shape, dtype)
        if name.endswith("running_mean") or name.endswith("moving_mean"):
            return self._init_zero(shape, dtype)
        if name.endswith("running_var") or name.endswith("moving_var"):
            return self._init_one(shape, dtype)
        return self.init_array(name, shape, dtype, rng)

    def init_array(self, name, shape, dtype, rng):
        return self._init_weight(shape, dtype, rng)

    def _init_weight(self, shape, dtype, rng):
        raise NotImplementedError

    @staticmethod
    def _init_zero(shape, dtype):
        return _np.zeros(shape, dtype=_np.float32).astype(dtype, copy=False)

    @staticmethod
    def _init_one(shape, dtype):
        return _np.ones(shape, dtype=_np.float32).astype(dtype, copy=False)

    def __repr__(self):
        kw = ", ".join(f"{k}={v}" for k, v in self._kwargs.items())
        return f"{type(self).__name__}({kw})"


@register
class Zero(Initializer):
    def _init_weight(self, shape, dtype, rng):
        return self._init_zero(shape, dtype)


@register
class One(Initializer):
    def _init_weight(self, shape, dtype, rng):
        return self._init_one(shape, dtype)


# reference aliases "zeros"/"ones"
_REGISTRY["zeros"] = Zero
_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, shape, dtype, rng):
        v = _np.asarray(self.value, dtype=_np.float32)
        return _np.broadcast_to(v, shape).astype(dtype, copy=False).copy()


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, shape, dtype, rng):
        return rng.uniform(-self.scale, self.scale,
                           size=shape).astype(dtype, copy=False)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, shape, dtype, rng):
        return (rng.standard_normal(size=shape) * self.sigma).astype(
            dtype, copy=False)


@register
class Orthogonal(Initializer):
    """≙ mx.init.Orthogonal (Saxe et al., initializer.py)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, shape, dtype, rng):
        nout = shape[0]
        nin = int(_np.prod(shape[1:])) if len(shape) > 1 else 1
        if self.rand_type == "uniform":
            tmp = rng.uniform(-1.0, 1.0, size=(nout, nin))
        else:
            tmp = rng.standard_normal(size=(nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        return (self.scale * q.reshape(shape)).astype(dtype, copy=False)


@register
class Xavier(Initializer):
    """Glorot init (≙ mx.init.Xavier, initializer.py Xavier class)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, shape, dtype, rng):
        if len(shape) < 2:
            # degenerate (bias-like) shape: fall back to uniform
            fan_in = fan_out = max(int(_np.prod(shape)), 1)
        else:
            hw_scale = float(_np.prod(shape[2:])) if len(shape) > 2 else 1.0
            fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            from .base import MXNetError
            raise MXNetError(f"invalid factor_type {self.factor_type!r}")
        scale = math.sqrt(self.magnitude / max(factor, 1e-12))
        if self.rnd_type == "uniform":
            out = rng.uniform(-scale, scale, size=shape)
        elif self.rnd_type == "gaussian":
            out = rng.standard_normal(size=shape) * scale
        else:
            from .base import MXNetError
            raise MXNetError(f"invalid rnd_type {self.rnd_type!r}")
        return out.astype(dtype, copy=False)


@register
class MSRAPrelu(Xavier):
    """He init with PReLU slope correction (≙ mx.init.MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


_REGISTRY["msra"] = MSRAPrelu


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (≙ mx.init.Bilinear, for deconv upsampling)."""

    def _init_weight(self, shape, dtype, rng):
        weight = _np.zeros(int(_np.prod(shape)), dtype=_np.float32)
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return weight.reshape(shape).astype(dtype, copy=False)


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (≙ mx.init.LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, shape, dtype, rng):
        b = _np.zeros(shape, dtype=_np.float32)
        num_hidden = shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias  # i,f,g,o gate order
        return b.astype(dtype, copy=False)


@register
class Mixed(Initializer):
    """Pattern → initializer routing (≙ mx.init.Mixed)."""

    def __init__(self, patterns, initializers):
        super().__init__()
        if len(patterns) != len(initializers):
            from .base import MXNetError
            raise MXNetError("patterns and initializers must pair up")
        self.map = [(re.compile(p), create(i)) for p, i in
                    zip(patterns, initializers)]

    def __call__(self, name, shape, dtype, rng):
        for pat, init in self.map:
            if pat.search(str(name)):
                return init(name, shape, dtype, rng)
        from .base import MXNetError
        raise MXNetError(f"parameter {name!r} matched no pattern; add '.*'")
