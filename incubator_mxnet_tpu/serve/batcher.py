"""Request queue + dynamic batcher over exported artifacts.

Architecture (docs/SERVING.md):

    submit() ──► bounded queue ──► batcher thread ──► bucketed program ──► futures
                 (admission          (assembles          (ExportedModel
                  control,            shape-bucket        per power-of-two
                  backpressure)       batches)            batch bucket)

One request is ONE sample (the exported input shape minus its leading batch
dim). The batcher coalesces concurrent requests into a batch, rounds the
batch up to the smallest available bucket (pad rows are zeros, sliced off
before the reply), and executes it through the bucket's compiled program.
Buckets are a small fixed set (powers of two by convention), so steady-state
traffic re-executes a handful of compiled programs — zero retraces after
warmup, observable via `compile_cache_size()` and the `programs_compiled`
counter (the serving analog of the PR 2 dispatch/compile counters).

Failure semantics are typed and fail-fast (MXNetError subclasses):
`QueueFullError` for admission-control rejects and shed requests,
`RequestTimeout` for missed deadlines, `ServerClosed` after shutdown.
`mx.fault` injection points `serve.enqueue` / `serve.execute` /
`serve.reply` wire the `MXNET_FAULT_SPEC` machinery through the three
stages, so overload and fault behavior is testable deterministically.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as _np

from ..base import MXNetError, get_env
from ..deploy import _np_dtype
from .. import fault as _fault
from ..telemetry import (record_span, trace as _trace, mem_on_oom,
                         mem_install_oom_hook)
from .metrics import ServeMetrics, SERVE_STATS


def _profiler_on():
    return _trace._profiler_running()

__all__ = [
    "ServeError", "QueueFullError", "RequestTimeout", "ServerClosed",
    "ReplicaDraining",
    "BucketedModel", "CallableModel", "Server", "pick_bucket",
]


class ServeError(MXNetError):
    """Base class for serving failures."""


class QueueFullError(ServeError):
    """Admission control failed the request: the queue was at capacity and
    the overload policy rejected this request (`policy='reject'`) or shed it
    after it was queued (`policy='shed'`)."""

    def __init__(self, msg, policy="reject"):
        super().__init__(msg)
        self.policy = policy


class RequestTimeout(ServeError):
    """The request missed its deadline while waiting in the queue."""


class ServerClosed(ServeError):
    """submit() after close(), or the request was pending at a non-draining
    shutdown."""


class ReplicaDraining(ServerClosed):
    """submit() while the engine is DRAINING: it has stopped admitting but
    is still finishing its resident requests before a restart (the
    drain-and-swap protocol). The fleet router catches this and re-routes
    to another replica — clients never see it. Subclasses ServerClosed so
    single-process callers that already handle close() races keep
    working."""


def pick_bucket(n, buckets):
    """Smallest bucket >= n, or None when n exceeds the largest bucket."""
    for b in buckets:
        if b >= n:
            return b
    return None


def _as_row(x):
    a = _np.asarray(getattr(x, "asnumpy", lambda: x)())
    return a


# ---------------------------------------------------------------------------
# model backends
# ---------------------------------------------------------------------------
class BucketedModel:
    """A fixed set of batch-size buckets, each served by one ExportedModel.

    The per-bucket artifacts come from exporting the SAME block at several
    batch sizes (`export_block`), the deployment recipe for static-shape
    programs: traffic is padded onto a small closed set of compiled
    programs instead of compiling one program per observed batch size.
    """

    def __init__(self, models):
        if not models:
            raise ServeError("BucketedModel needs at least one model")
        if not isinstance(models, dict):
            models = {int(m.input_specs[0][0][0]): m for m in models}
        self._models = dict(sorted(models.items()))
        self.batch_sizes = list(self._models)
        if any(b < 1 for b in self.batch_sizes):
            raise ServeError(f"invalid batch buckets {self.batch_sizes}")
        # row specs (per-sample shape/dtype) must agree across buckets
        ref = self.row_specs
        for b, m in self._models.items():
            specs = [(tuple(s[1:]), d) for s, d in m.input_specs]
            if specs != ref:
                raise ServeError(
                    f"bucket {b} row specs {specs} != bucket "
                    f"{self.batch_sizes[0]} row specs {ref}")
        m0 = self._models[self.batch_sizes[0]]
        self.single_output = m0.single_output
        self.n_out = m0.n_out

    @classmethod
    def from_prefixes(cls, prefixes):
        """Load one exported artifact triple per bucket."""
        from ..deploy import ExportedModel
        return cls([ExportedModel(p) for p in prefixes])

    @classmethod
    def export_block(cls, block, sample_shape, buckets, directory,
                     name="model", dtype="float32"):
        """Export `block` once per bucket size and load the artifacts back.

        `sample_shape` is the per-sample input shape (no batch dim).
        Returns the BucketedModel over `<directory>/<name>-b<bucket>-0000`.
        """
        from .. import np as mxnp
        from ..deploy import ExportedModel
        models = {}
        for b in sorted(set(int(x) for x in buckets)):
            x = mxnp.zeros((b,) + tuple(sample_shape), dtype=dtype)
            prefix = os.path.join(directory, f"{name}-b{b}")
            block(x)   # shape inference at this batch size
            block.export(prefix, example_inputs=x)
            models[b] = ExportedModel(f"{prefix}-0000")
        return cls(models)

    @property
    def num_inputs(self):
        return self._models[self.batch_sizes[0]].num_inputs

    @property
    def row_specs(self):
        """[(per-sample shape, dtype), ...] — input specs minus batch dim."""
        m = self._models[self.batch_sizes[0]]
        return [(tuple(s[1:]), d) for s, d in m.input_specs]

    def run_batch(self, bucket, arrs):
        """Execute the bucket's program; returns a tuple of stacked
        outputs (leading dim == bucket)."""
        out = self._models[bucket].run(*arrs)
        return out if isinstance(out, tuple) else (out,)

    def warmup(self):
        for b, m in self._models.items():
            m.warmup()

    def compile_cache_size(self):
        """Total compiled programs across buckets (-1 when unknown)."""
        sizes = [m.compile_cache_size() for m in self._models.values()]
        if any(s < 0 for s in sizes):
            return -1
        return sum(sizes)


class CallableModel:
    """Serve a plain callable (jax-traceable, params closed over) at a fixed
    bucket set: `fn(*batched_arrays) -> array | tuple`. One `jax.jit`
    wrapper; each bucket's shape compiles once into its cache (the same
    small-closed-set contract as BucketedModel, without the export
    round-trip — the in-process deployment path)."""

    def __init__(self, fn, batch_sizes=None, row_specs=(),
                 single_output=True):
        import jax
        if batch_sizes is None:
            # knob precedence: explicit arg > deployment profile >
            # default ladder (each bucket is one compiled program, so
            # the set is a measured cost/padding trade — swept by
            # mx.tune's serve_batch phase)
            from ..tune.profile import resolve as _tune_resolve
            batch_sizes = _tune_resolve("serve.batch_buckets",
                                        [1, 2, 4, 8, 16, 32])
        if not row_specs:
            raise ServeError("CallableModel needs row_specs")
        self._jit = jax.jit(fn)
        self.batch_sizes = sorted(int(b) for b in batch_sizes)
        self.row_specs = [(tuple(s), str(d)) for s, d in row_specs]
        self.single_output = bool(single_output)

    @property
    def num_inputs(self):
        return len(self.row_specs)

    def run_batch(self, bucket, arrs):
        out = self._jit(*arrs)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return tuple(_np.asarray(o) for o in out)

    def warmup(self):
        for b in self.batch_sizes:
            arrs = [_np.zeros((b,) + s, dtype=_np_dtype(d))
                    for s, d in self.row_specs]
            self.run_batch(b, arrs)

    def compile_cache_size(self):
        return int(getattr(self._jit, "_cache_size", lambda: -1)())


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------
class _Request:
    __slots__ = ("inputs", "future", "deadline", "t_submit", "ctx")

    def __init__(self, inputs, deadline, ctx=None):
        self.inputs = inputs
        self.future = Future()
        self.deadline = deadline
        self.t_submit = time.perf_counter()
        # the request's root TraceContext ("serve.request"): minted on the
        # caller thread, carried across the queue so batcher-side spans
        # (queue-wait / execute / reply) land in the SAME trace
        self.ctx = ctx


class Server:
    """Thread-safe dynamic-batching server over a bucketed model.

    ::

        model = serve.BucketedModel.from_prefixes(["m-b1-0000", "m-b8-0000"])
        with serve.Server(model, batch_timeout_ms=2.0) as srv:
            fut = srv.submit(x_row)          # returns a Future
            y = fut.result()
            y = srv.predict(x_row)           # submit + wait

    Knobs (constructor arg > MXNET_SERVE_* env > default):

      max_queue          bound on queued requests (admission control)
      batch_timeout_ms   max wait to fill a batch after its first request
      default_deadline_ms  per-request queue deadline (None = no deadline)
      overload_policy    'reject' (reject-newest, fail the submitter) or
                         'shed' (shed-oldest, fail the oldest queued
                         request and admit the new one)

    Exactly one batcher thread executes batches, so the underlying jit call
    never runs concurrently with itself; submit() is safe from any number
    of threads.
    """

    def __init__(self, model, *, max_queue=None, batch_timeout_ms=None,
                 default_deadline_ms=None, overload_policy=None,
                 name="serve"):
        from ..deploy import ExportedModel
        if isinstance(model, ExportedModel):
            model = BucketedModel([model])
        elif isinstance(model, (dict, list)) :
            model = BucketedModel(model)
        self.model = model
        self.name = name
        self.max_queue = int(
            max_queue if max_queue is not None
            else get_env("MXNET_SERVE_MAX_QUEUE", 256, typ=int))
        self.batch_timeout_s = float(
            batch_timeout_ms if batch_timeout_ms is not None
            else get_env("MXNET_SERVE_BATCH_TIMEOUT_MS", 2.0,
                         typ=float)) / 1e3
        dl = (default_deadline_ms if default_deadline_ms is not None
              else get_env("MXNET_SERVE_DEADLINE_MS", typ=float))
        self.default_deadline_s = None if dl is None else float(dl) / 1e3
        self.overload_policy = (
            overload_policy
            or get_env("MXNET_SERVE_OVERLOAD_POLICY", "reject"))
        if self.overload_policy not in ("reject", "shed"):
            raise ServeError(
                f"overload_policy must be 'reject' or 'shed', got "
                f"{self.overload_policy!r}")
        if self.max_queue < 1:
            raise ServeError("max_queue must be >= 1")

        self.metrics = ServeMetrics()
        self._queue = deque()
        self._cv = threading.Condition()
        self._closing = False
        self._drain = True
        self._started = False
        self._warm = set()
        self._thread = threading.Thread(
            target=self._loop, name=f"{name}-batcher", daemon=True)

    # -- lifecycle ---------------------------------------------------------
    def start(self, warmup=True):
        """Compile every bucket's program (unless warmup=False), then start
        the batcher thread. Warming up front keeps compilation out of the
        serving path: steady state never retraces."""
        if self._started:
            return self
        if warmup:
            self.model.warmup()
            for b in self.model.batch_sizes:
                if self._mark_warm(b):
                    self.metrics.count("programs_compiled")
        port = get_env("MXNET_METRICS_PORT", typ=int)
        if port is not None:
            # process-wide singleton: a second Server must not rebind the
            # port, and closing one Server must not tear the endpoint down
            # under the others — it lives until process exit. A bind
            # failure (port held by ANOTHER process) must not abort
            # serving: observability is optional, inference is not.
            from .. import telemetry
            try:
                telemetry.ensure_metrics_server(port)
            except OSError as e:
                import logging
                logging.getLogger("mx.serve").warning(
                    "metrics endpoint on port %s unavailable (%s); "
                    "serving continues without /metrics", port, e)
        # flight-recorder crash hooks (no-ops unless MXNET_FLIGHTREC_DIR):
        # a served process should always leave a black box — and an
        # uncaught RESOURCE_EXHAUSTED should leave the memory one too
        _trace.install_crash_hooks()
        mem_install_oom_hook()
        self._started = True
        self._thread.start()
        return self

    def __enter__(self):
        return self.start()

    def close(self, drain=True, timeout=30.0):
        """Stop the batcher. `drain=True` serves the queued requests first;
        `drain=False` fails them with ServerClosed."""
        with self._cv:
            if self._closing:
                pending = []
            else:
                self._closing = True
                self._drain = drain
                pending = [] if drain else list(self._queue)
                if not drain:
                    self._queue.clear()
            self._cv.notify_all()
        for req in pending:
            _fail(req, ServerClosed("server closed before execution"))
        if self._started:
            self._thread.join(timeout=timeout)

    def __exit__(self, *exc):
        self.close()

    def _mark_warm(self, bucket):
        """Record `bucket`'s program as compiled; True on first sighting.
        `_warm` is touched from both start() (caller thread) and _execute
        (batcher thread), so the check-and-add runs under _cv."""
        with self._cv:
            if bucket in self._warm:
                return False
            self._warm.add(bucket)
            return True

    # -- submission --------------------------------------------------------
    def _check_row(self, inputs):
        specs = self.model.row_specs
        if len(inputs) != len(specs):
            raise ServeError(
                f"model takes {len(specs)} inputs, got {len(inputs)}")
        rows = []
        for i, (x, (shape, dtype)) in enumerate(zip(inputs, specs)):
            a = _as_row(x)
            if tuple(a.shape) != shape:
                raise ServeError(
                    f"input {i}: sample shape {tuple(a.shape)} != exported "
                    f"row shape {shape} (one request = one sample)")
            if str(a.dtype) != dtype:
                a = a.astype(_np_dtype(dtype))   # bf16-aware
            rows.append(a)
        return tuple(rows)

    def submit(self, *inputs, deadline_ms=None):
        """Enqueue one sample; returns a `concurrent.futures.Future`.

        Raises QueueFullError immediately when the queue is at capacity
        under the reject-newest policy; under shed-oldest the OLDEST queued
        request fails instead and this one is admitted. Raises ServerClosed
        after close()."""
        if not self._started:
            raise ServeError("Server.start() (or `with Server(...)`) first")
        t_enter = time.perf_counter()
        rows = self._check_row(inputs)
        _fault.inject("serve.enqueue")
        dl = (deadline_ms / 1e3 if deadline_ms is not None
              else self.default_deadline_s)
        # one request = one trace: the root "serve.request" context is
        # minted HERE on the caller thread (child of any ambient span,
        # else a new sampled root) and rides the queue — every later
        # stage's span carries the same trace_id across the thread hop.
        # Minted only while a COLLECTOR can consume the ids (profiler /
        # flightrec spool / explicit MXNET_TRACE_SAMPLE — see
        # trace.request_root): ids nobody can see are pure per-request
        # cost on a GIL-saturated server, and MXNET_TELEMETRY=0 disables
        # tracing outright — the always-on default stays within the ≤2%
        # A/B guard.
        ctx = _trace.request_root("serve.request")
        req = _Request(rows, None if dl is None
                       else time.perf_counter() + dl, ctx=ctx)
        shed_req = None
        rejected_depth = None
        with self._cv:
            if self._closing:
                raise ServerClosed("server is closed")
            if len(self._queue) >= self.max_queue:
                if self.overload_policy == "reject":
                    rejected_depth = len(self._queue)
                else:
                    shed_req = self._queue.popleft()
            if rejected_depth is None:
                self._queue.append(req)
                depth = len(self._queue)
                self._cv.notify()
        if rejected_depth is not None:
            # flight-recorder I/O (incl. a rate-limited dump) OUTSIDE the
            # server lock: an overload black box must not stall admission
            self.metrics.count("rejected")
            _trace.flightrec_record(
                "serve.reject", self.name, depth=rejected_depth,
                trace_id=ctx.trace_id if ctx else None)
            _trace.flightrec_maybe_dump("serve.overload")
            raise QueueFullError(
                f"queue full ({self.max_queue}); request rejected",
                policy="reject")
        self.metrics.count("requests")
        self.metrics.set_queue_depth(depth)
        if ctx is not None and _profiler_on():
            # caller-thread stage span: admission + enqueue cost, the
            # first node under serve.request (the thread-boundary anchor).
            # Stage-level spans only record while a trace is being
            # COLLECTED (profiler running): at thousands of requests/s
            # their histogram value is nil next to the timeline's
            # wait/exec totals, and the always-on path must stay ≤2%
            # overhead — the per-request root span below carries the
            # aggregate either way
            record_span("serve.enqueue",
                        (time.perf_counter() - t_enter) * 1e6,
                        ts_us=t_enter * 1e6, cat="serve",
                        ctx=_trace.child_context(ctx, "serve.enqueue"),
                        queue_depth=depth)
        if shed_req is not None:
            self.metrics.count("shed")
            _trace.flightrec_record(
                "serve.shed", self.name, depth=depth,
                trace_id=shed_req.ctx.trace_id if shed_req.ctx else None)
            _trace.flightrec_maybe_dump("serve.overload")
            _fail(shed_req, QueueFullError(
                f"queue full ({self.max_queue}); oldest request shed",
                policy="shed"))
        return req.future

    def predict(self, *inputs, timeout=None, deadline_ms=None):
        """submit() + wait; returns the model output for this sample."""
        return self.submit(*inputs, deadline_ms=deadline_ms).result(
            timeout=timeout)

    def stats(self):
        """Metrics snapshot + compile accounting for the zero-retrace
        assertion. `out["timeline"]` carries the request-time attribution
        (queue-wait vs execute — the serving data-stall/compute split)."""
        out = self.metrics.snapshot()
        out["buckets"] = list(self.model.batch_sizes)
        out["compile_cache_size"] = self.model.compile_cache_size()
        return out

    def timeline(self):
        """Request-timeline attribution only: where request time went."""
        return self.metrics.snapshot()["timeline"]

    def metrics_text(self):
        """Prometheus text: the process-wide telemetry registry (includes
        the `serve` counter group and the `serve.batch` span histogram)
        plus this server's per-instance gauges — what the `/metrics`
        endpoint (`telemetry.start_metrics_server` / MXNET_METRICS_PORT)
        serves, with the per-server lines appended."""
        from .. import telemetry
        return telemetry.metrics_text() + "\n".join(
            self.metrics.prometheus_lines(server=self.name)) + "\n"

    # -- batcher thread ----------------------------------------------------
    def _assemble(self):
        """Pop the next batch (up to the largest bucket), honoring the
        assembly timeout measured from the FIRST queued request."""
        max_b = self.model.batch_sizes[-1]
        with self._cv:
            while not self._queue and not self._closing:
                self._cv.wait()
            if not self._queue:
                return None          # closing and empty
            t_end = self._queue[0].t_submit + self.batch_timeout_s
            while (len(self._queue) < max_b and not self._closing):
                remaining = t_end - time.perf_counter()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=remaining)
            batch = [self._queue.popleft()
                     for _ in range(min(len(self._queue), max_b))]
            depth = len(self._queue)
        self.metrics.set_queue_depth(depth)
        return batch, depth

    def _loop(self):
        while True:
            got = self._assemble()
            if got is None:
                return
            batch, depth = got
            now = time.perf_counter()
            live = []
            for req in batch:
                if req.deadline is not None and now > req.deadline:
                    self.metrics.count("timeouts")
                    _trace.flightrec_record(
                        "serve.timeout", self.name,
                        waited_ms=round((now - req.t_submit) * 1e3, 1),
                        trace_id=req.ctx.trace_id if req.ctx else None)
                    _fail(req, RequestTimeout(
                        "deadline expired after "
                        f"{(now - req.t_submit) * 1e3:.1f}ms in queue"))
                else:
                    live.append(req)
            if not live:
                continue
            self._execute(live, depth)

    def _execute(self, batch, depth):
        n = len(batch)
        bucket = pick_bucket(n, self.model.batch_sizes)
        if bucket is None:       # can't happen: assembly caps at max bucket
            bucket = self.model.batch_sizes[-1]
        if self._mark_warm(bucket):
            self.metrics.count("programs_compiled")
        t0 = time.perf_counter()
        try:
            _fault.inject("serve.execute")
            arrs = []
            for j, (shape, dtype) in enumerate(self.model.row_specs):
                a = _np.zeros((bucket,) + shape, dtype=_np_dtype(dtype))
                for i, req in enumerate(batch):
                    a[i] = req.inputs[j]
                arrs.append(a)
            outs = self.model.run_batch(bucket, arrs)
            # pad-row mask: inputs were zero-padded from n rows up to
            # `bucket`, so output rows [n:] are PAD GARBAGE (whatever the
            # model computed on zero rows). Slicing to [:n] here — not at
            # reply indexing — makes the boundary explicit and checkable:
            # an output whose leading dim is not the bucket has no
            # row<->request correspondence at all (e.g. a model that
            # reduces over the batch), and indexing it per-request would
            # silently hand every requester data mixing in pad rows. Fail
            # the batch with a typed error instead.
            bad = [tuple(getattr(o, "shape", ())) for o in outs
                   if getattr(o, "shape", None) is None
                   or len(o.shape) == 0 or o.shape[0] != bucket]
            if bad:
                raise ServeError(
                    f"model output shapes {bad} do not carry the batch "
                    f"dim (bucket {bucket}): pad rows cannot be masked "
                    f"off, refusing to reply with pad-contaminated data")
            outs = tuple(o[:n] for o in outs)
        except BaseException as e:
            # RESOURCE_EXHAUSTED gets the OOM black box (census + plans +
            # flightrec ring) before the batch is failed (crash-path
            # helper: never raises — the import is guarded too, so a
            # teardown-time failure can never displace the real error)
            mem_on_oom(e, where="serve.batch")
            self.metrics.count("errors", n)
            err = e if isinstance(e, MXNetError) else ServeError(
                f"batch execution failed: {type(e).__name__}: {e}")
            for req in batch:
                _fail(req, err)
            return
        t_exec_end = time.perf_counter()
        exec_ms = (t_exec_end - t0) * 1e3
        # queue wait summed over the batch's requests: the request-timeline
        # split (queued vs executing) Server.stats()["timeline"] reports
        wait_ms = sum((t0 - req.t_submit) * 1e3 for req in batch)
        self.metrics.observe_batch(
            bucket, n, exec_ms, depth, queue_wait_ms=wait_ms,
            member_traces=[req.ctx.trace_id for req in batch if req.ctx])
        # batcher-thread stage spans, one pair per traced request: the
        # queue-wait (t_submit -> batch assembly done) and this batch's
        # execution window, both children of the request's root context —
        # with the caller-side serve.enqueue span they make the trace
        # cross the submit -> batcher/executor thread boundary. Only
        # while the profiler collects (see serve.enqueue above).
        if _profiler_on():
            for req in batch:
                if req.ctx is None:
                    continue
                record_span("serve.queue_wait",
                            (t0 - req.t_submit) * 1e6,
                            ts_us=req.t_submit * 1e6, cat="serve",
                            ctx=_trace.child_context(req.ctx,
                                                     "serve.queue_wait"),
                            bucket=bucket)
                record_span("serve.execute", exec_ms * 1e3,
                            ts_us=t0 * 1e6, cat="serve",
                            ctx=_trace.child_context(req.ctx,
                                                     "serve.execute"),
                            bucket=bucket, batch_n=n)
        try:
            _fault.inject("serve.reply")
        except BaseException as e:
            self.metrics.count("errors", n)
            err = e if isinstance(e, MXNetError) else ServeError(
                f"reply failed: {type(e).__name__}: {e}")
            for req in batch:
                _fail(req, err)
            return
        done = time.perf_counter()
        for i, req in enumerate(batch):
            row = tuple(o[i] for o in outs)
            if self.model.single_output:
                row = row[0]
            if req.future.set_running_or_notify_cancel():
                req.future.set_result(row)
            self.metrics.count("replies")
            # latency/margins use the SHARED pre-loop timestamp:
            # set_result() runs client done-callbacks inline, and a
            # per-iteration clock would bill earlier members' callback
            # time to later members as server latency
            total_ms = (done - req.t_submit) * 1e3
            self.metrics.observe_latency(total_ms)
            if req.ctx is not None and _profiler_on():
                record_span("serve.reply",
                            (time.perf_counter() - done) * 1e6,
                            ts_us=done * 1e6, cat="serve",
                            ctx=_trace.child_context(req.ctx,
                                                     "serve.reply"))
                # close the request's root span: enqueue -> reply, the
                # ONE trace the whole request renders as. Span records
                # only while a trace is being collected — the always-on
                # request-latency aggregate is ServeMetrics (p50/p95/p99
                # + the slowest table), so the steady-state tracing cost
                # stays at one context mint per request (the ≤2% A/B)
                record_span("serve.request", total_ms * 1e3,
                            ts_us=req.t_submit * 1e6, cat="serve",
                            ctx=req.ctx, bucket=bucket)
            self.metrics.observe_request(
                total_ms,
                trace_id=req.ctx.trace_id if req.ctx else None,
                queue_wait_ms=(t0 - req.t_submit) * 1e3,
                exec_ms=exec_ms, batch_size=n,
                deadline_margin_ms=((req.deadline - done) * 1e3
                                    if req.deadline is not None else None))


def _fail(req, exc):
    if req.future.set_running_or_notify_cancel():
        req.future.set_exception(exc)
