"""mx.serve.replica — one fleet worker process.

Spawned by `serve.Fleet` as

    python -m incubator_mxnet_tpu.serve.replica \\
        --connect <router-port> --replica <index> --spec <spec.json>

and speaks newline-delimited JSON to the router over a localhost TCP
socket. The spec file is the VERSION-PINNED model artifact manifest:
decoder config + parameter seed + version tag (+ engine knobs); a
drain-and-swap restarts the replica against a new spec file, nothing else
changes.

Protocol (replica -> router unless noted):

  hello      first message: replica index, pid, model version, the bound
             /metrics port, warmup_s, compile_cache_size — the router
             DISCOVERS the metrics port from here instead of assuming it
  request    (router ->) prompt/max_new/deadline_ms/trace-context dict;
             answered by exactly one `reply` or `error`
  reply      generated token ids (+ serving version)
  error      typed failure: `kind` is the exception class name;
             kind=ReplicaDraining is the routed-around drain signal and
             never surfaces to clients
  ping/pong  (router ->)/(replica ->) heartbeat; pong carries queue depth
             (least-loaded routing signal), draining flag, and the
             zero-retrace observables
  drain      (router ->) stop admitting, finish KV-resident requests,
             answer `drained`, exit 0 (the supervisor respawns, possibly
             on a new version)
  stop       (router ->) hard close and exit

Metrics-port derivation (the PR-16 collision fix): `ensure_metrics_server`
is a process-wide singleton, so N replica children inheriting one
`MXNET_METRICS_PORT` would race to bind the SAME port and N-1 would lose.
Each replica derives `base + replica_index`, logs the choice, and reports
the actually-bound port in its hello.

Warm start: the spawning supervisor sets `MXNET_COMPILE_CACHE_DIR`
(inherited here), so `CachedDecoder.__init__` arms the persistent
compilation cache and `ContinuousEngine.start()` deserializes both step
programs instead of recompiling (the 2.37x warm skip measured in
`serve_continuous_r14.json`).
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import socket
import sys
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as _np

from ..base import get_env
from .. import telemetry
from ..telemetry import trace as _trace
from .batcher import ReplicaDraining, ServerClosed

logger = logging.getLogger("mx.serve.fleet")

__all__ = ["main", "derive_metrics_port"]


def derive_metrics_port(base, replica_index):
    """Per-replica /metrics port: base + replica index (None when no base
    is configured). Keeping the offset an arithmetic rule (not an
    ephemeral bind) makes the port predictable for operators, while the
    hello message still carries the AUTHORITATIVE bound port."""
    if not base:
        return None
    return int(base) + int(replica_index)


def _start_metrics(replica_index):
    """Bind this replica's derived metrics port; returns the bound port
    (or None when MXNET_METRICS_PORT is unset / the port is taken)."""
    base = get_env("MXNET_METRICS_PORT", typ=int)
    port = derive_metrics_port(base, replica_index)
    if port is None:
        return None
    try:
        srv = telemetry.ensure_metrics_server(port)
    except OSError as e:
        logger.warning("replica %d: metrics port %d unavailable: %s",
                       replica_index, port, e)
        return None
    bound = srv.server_address[1]
    logger.info("replica %d: serving /metrics on port %d "
                "(MXNET_METRICS_PORT base %s + replica index %d)",
                replica_index, bound, base, replica_index)
    return bound


class _StubEngine:
    """jax-free stand-in engine for fleet protocol tests and the
    router-side fault-point suite: resolves each request after a fixed
    delay with a deterministic token pattern derived from (prompt,
    version). Mirrors the exact ContinuousEngine surface the replica loop
    touches — submit / queue_depth / begin_drain / draining / close /
    warmup_s / compile_cache_size / retraces_after_warmup /
    prefix_hit_count."""

    def __init__(self, spec):
        self.version = str(spec.get("version", "v0"))
        self._delay_s = float(spec.get("stub_delay_ms", 5.0)) / 1e3
        self.warmup_s = 0.0
        self._vtag = sum(self.version.encode()) % 997
        self._cv = threading.Condition()
        self._q = deque()
        self._closing = False
        self._drain = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="stub-engine")
        self._thread.start()

    def compile_cache_size(self):
        return 0

    def retraces_after_warmup(self):
        return 0

    def prefix_hit_count(self):
        return 0

    @property
    def draining(self):
        return self._closing and self._drain and self._thread.is_alive()

    def queue_depth(self):
        with self._cv:
            return len(self._q), 0

    def submit(self, prompt_tokens, max_new_tokens=16, deadline_ms=None,
               **sampling):
        # sampling params accepted for wire compatibility; the stub's
        # deterministic token pattern ignores them
        prompt = _np.asarray(prompt_tokens, dtype=_np.int64).ravel()
        fut = Future()
        with self._cv:
            if self._closing:
                if self._drain and self._thread.is_alive():
                    raise ReplicaDraining("stub engine is draining")
                raise ServerClosed("stub engine is closed")
            self._q.append((time.perf_counter() + self._delay_s,
                            prompt, int(max_new_tokens), fut))
            self._cv.notify()
        return fut

    def _loop(self):
        while True:
            with self._cv:
                while not self._q and not self._closing:
                    self._cv.wait()
                if not self._q and self._closing:
                    return
                due, prompt, max_new, fut = self._q.popleft()
            delay = due - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            base = int(prompt.sum()) % 997
            toks = _np.asarray(
                [(base * 31 + i + self._vtag) % 97 for i in range(max_new)],
                dtype=_np.int32)
            if fut.set_running_or_notify_cancel():
                fut.set_result(toks)

    def begin_drain(self):
        with self._cv:
            self._closing = True
            self._drain = True
            self._cv.notify_all()

    def close(self, drain=True, timeout=30.0):
        with self._cv:
            self._closing = True
            self._drain = drain
            pending = [] if drain else list(self._q)
            if not drain:
                self._q.clear()
            self._cv.notify_all()
        for _, _, _, fut in pending:
            if fut.set_running_or_notify_cancel():
                fut.set_exception(
                    ServerClosed("stub engine closed before completion"))
        self._thread.join(timeout=timeout)


def _resolve_profile(spec):
    """Activate the deployment profile for this replica's model, if one
    exists: explicit `spec["profile"]` path > ``MXNET_TUNE_PROFILE`` >
    lookup by (model, hardware) fingerprint under the profile dir.
    Returns the applied profile hash (reported in the hello so the Fleet
    can detect divergent tunings) or None — a mismatched or corrupt
    profile falls back loudly inside tune.profile and the replica boots
    on env/defaults; tuning must never keep a replica down."""
    if spec.get("stub"):
        # jax-free protocol stub: pass a declared hash through verbatim
        # (lets fleet-level divergence plumbing be tested without a model)
        return spec.get("profile_hash")
    try:
        from ..tune import profile as _tprof
        model_fp = _tprof.model_fingerprint(spec.get("config", {}))
        path = spec.get("profile") or os.environ.get("MXNET_TUNE_PROFILE")
        if path:
            prof = _tprof.DeploymentProfile.load(path)
        else:
            prof = _tprof.lookup(model_fp)
        if prof is not None and _tprof.activate(prof, model_fp=model_fp,
                                                source="replica"):
            return prof.profile_hash
    except Exception as e:  # noqa: BLE001 — boot anyway, on defaults
        logger.warning("deployment profile unavailable (%s); replica "
                       "starts on env/defaults", e)
    return None


def _build_engine(spec):
    """Engine from a version-pinned spec manifest. `stub: true` selects
    the jax-free protocol stub (tests/bench harness plumbing); otherwise a
    CachedDecoder + ContinuousEngine (warm via MXNET_COMPILE_CACHE_DIR,
    tuned via the activated deployment profile)."""
    if spec.get("stub"):
        return _StubEngine(spec)
    from .continuous import (CachedDecoder, ContinuousEngine,
                             DecoderConfig)
    cfg = DecoderConfig(**spec.get("config", {}))
    model = CachedDecoder(cfg, seed=int(spec.get("seed", 0)))
    eng = ContinuousEngine(model, **spec.get("engine", {}))
    eng.start()
    return eng


def main(argv=None):
    ap = argparse.ArgumentParser(prog="serve.replica")
    ap.add_argument("--connect", type=int, required=True,
                    help="router listen port on 127.0.0.1")
    ap.add_argument("--replica", type=int, required=True)
    ap.add_argument("--spec", required=True,
                    help="version-pinned model spec JSON path")
    args = ap.parse_args(argv)
    logging.basicConfig(
        stream=sys.stderr, level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    with open(args.spec) as f:
        spec = json.load(f)
    version = str(spec.get("version", "v0"))

    metrics_port = _start_metrics(args.replica)
    profile_hash = _resolve_profile(spec)    # before any program builds
    eng = _build_engine(spec)

    sock = socket.create_connection(("127.0.0.1", args.connect),
                                    timeout=60)
    sock.settimeout(None)
    rfile = sock.makefile("r", encoding="utf-8", newline="\n")
    wlock = threading.Lock()

    def send(msg):
        data = (json.dumps(msg) + "\n").encode("utf-8")
        try:
            with wlock:
                sock.sendall(data)
        except OSError:
            pass            # router gone; the reader loop will see EOF

    send({"type": "hello", "replica": args.replica, "pid": os.getpid(),
          "version": version, "metrics_port": metrics_port,
          "warmup_s": eng.warmup_s,
          "compile_cache_size": eng.compile_cache_size(),
          "profile_hash": profile_hash})

    drain_started = threading.Event()
    done = threading.Event()

    def _finish_drain(timeout_s):
        t0 = time.perf_counter()
        eng.close(drain=True, timeout=timeout_s)
        send({"type": "drained", "replica": args.replica,
              "version": version,
              "drain_ms": round((time.perf_counter() - t0) * 1e3, 3)})
        done.set()
        # orderly exit: close the socket so the router's reader sees EOF
        # AFTER `drained`; the supervisor respawns us (maybe on a new spec)
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def _on_done(rid, fut):
        try:
            toks = fut.result()
        except ReplicaDraining as e:
            send({"type": "error", "id": rid, "kind": "ReplicaDraining",
                  "message": str(e)})
        except Exception as e:  # typed serve errors and unexpected alike
            send({"type": "error", "id": rid,
                  "kind": type(e).__name__, "message": str(e)})
        else:
            send({"type": "reply", "id": rid, "version": version,
                  "tokens": [int(t) for t in toks]})

    for line in rfile:
        try:
            msg = json.loads(line)
        except ValueError:
            continue
        t = msg.get("type")
        if t == "request":
            rid = msg.get("id")
            # re-join the router's trace: the serve.request root minted
            # inside submit() becomes a CHILD of the router's
            # fleet.request span (TraceContext.to_dict/from_dict hop), so
            # one trace survives a failover re-dispatch
            ctx = _trace.TraceContext.from_dict(msg.get("trace") or {})
            token = _trace.attach(ctx) if ctx is not None else None
            # sampling params (temperature/top_k/top_p/seed) ride the
            # request message; absent keys keep the engine's greedy
            # defaults so old routers speak the same protocol
            sampling = {k: msg[k]
                        for k in ("temperature", "top_k", "top_p", "seed")
                        if k in msg}
            try:
                fut = eng.submit(msg.get("prompt"),
                                 msg.get("max_new", 16),
                                 deadline_ms=msg.get("deadline_ms"),
                                 **sampling)
            except ReplicaDraining as e:
                send({"type": "error", "id": rid,
                      "kind": "ReplicaDraining", "message": str(e)})
            except Exception as e:
                send({"type": "error", "id": rid,
                      "kind": type(e).__name__, "message": str(e)})
            else:
                fut.add_done_callback(
                    lambda f, rid=rid: _on_done(rid, f))
            finally:
                if token is not None:
                    _trace.detach(token)
        elif t == "ping":
            waiting, running = eng.queue_depth()
            send({"type": "pong", "seq": msg.get("seq"),
                  "replica": args.replica, "version": version,
                  "waiting": waiting, "running": running,
                  "draining": bool(getattr(eng, "draining", False)),
                  "retraces": eng.retraces_after_warmup(),
                  "compile_cache_size": eng.compile_cache_size(),
                  # prefix-cache effectiveness, surfaced so the router's
                  # affinity decisions are observable fleet-wide
                  "prefix_hits": eng.prefix_hit_count()})
        elif t == "drain":
            if not drain_started.is_set():
                drain_started.set()
                eng.begin_drain()
                timeout_s = float(msg.get("timeout_ms", 30000.0)) / 1e3
                threading.Thread(target=_finish_drain,
                                 args=(timeout_s,), daemon=True,
                                 name="replica-drain").start()
        elif t == "stop":
            break

    if drain_started.is_set():
        done.wait(timeout=5.0)
        return 0
    try:
        eng.close(drain=False, timeout=5.0)
    except Exception:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
