"""mx.serve.fleet — multi-replica serving: supervisor, SLO-aware router,
replica-death failover, and zero-downtime drain-and-swap.

The ContinuousEngine (PR 14) is one process serving one model: one SIGKILL
and every in-flight request dies with no retry path. This module is the
tracker/parameter-server tier of the reference framework (the layer that
made MXNet multi-process) rebuilt as a serving fleet:

    Fleet (this process)                      replica children
    ───────────────────                       ────────────────
    submit() ─► router ── EDF-aware ────────► serve.replica 0   engine
                │         least-loaded        serve.replica 1   engine
                │         dispatch            ...
    monitor ────┤  heartbeats / liveness
    supervisor ─┘  respawn (warm) / drain-and-swap

  * **Dispatch** (`fleet.dispatch` fault point): among SERVING replicas,
    pick the least-loaded (router in-flight count, then replica-reported
    queue depth, then lowest index). Deadlines ride along: the remaining
    budget is recomputed at every (re-)dispatch, and failover re-enqueues
    earliest-deadline-first — the EDF admission inside each engine then
    orders the merged queue.
  * **Prefix affinity**: the router remembers which replica last served
    each block-quantized prompt-prefix hash (`serve.prefix_cache`'s
    `rolling_hash`, block = MXNET_SERVE_PREFIX_BLOCK) and prefers that
    replica while it is healthy and non-draining — repeated prefixes
    land where their KV is already cached, turning cross-replica cache
    misses into `prefix.hits`. Pure preference, never a constraint:
    a dead/draining/excluded affinity target falls back to the normal
    EDF + least-loaded pick, and the map is a bounded LRU (stale
    entries age out, respawned replicas just re-learn). Counted in
    `fleet.affinity_hits`.
  * **Health** (`fleet.heartbeat`): pings every MXNET_FLEET_HEARTBEAT_MS;
    a replica missing `heartbeat_misses` consecutive beats is declared
    hung and SIGKILLed into the death path. Process exit is ALSO polled,
    so an idle replica's death (no traffic, no socket activity) is
    detected within one beat — the PR-9 idle-death detection analog.
  * **Failover**: a dead replica's in-flight requests re-enqueue onto
    survivors with a bounded retry budget (MXNET_FLEET_RETRY_BUDGET),
    emitting the same structured `fault.retry` / `fault.retry_exhausted`
    records as `fault.retrying`; when the budget is spent the ORIGINAL
    error surfaces to the client, wrapped in `ReplicaDied`.
  * **Respawn** (`fleet.respawn`): the supervisor respawns warm (the
    spawn env carries MXNET_COMPILE_CACHE_DIR, so the child deserializes
    both step programs). PR-9 worker-death protocol: bounded CONSECUTIVE
    restarts (reset on the first successful reply after a respawn), and
    `fault.retry_exhausted`-style original-error resurfacing when the
    budget is spent (replica marked `failed`, fleet serves degraded).
  * **Drain-and-swap** (`fleet.swap`): `swap(spec)` rolls the fleet one
    replica at a time onto a new version-pinned spec: mark draining
    (router routes around; the engine's typed `ReplicaDraining` rejects
    are re-dispatched, never surfaced), wait for its KV-resident requests
    to finish (MXNET_FLEET_DRAIN_TIMEOUT_MS, then hard-stop + failover),
    respawn on the new spec — zero dropped requests fleet-wide.

Observability: `fleet.*` counters (`FLEET_STATS`) + the per-replica
`serve.replica_state` gauge; one trace per request even across a failover
hop (the router's `fleet.request` root is shipped in the request message
and each replica-side `serve.request` span parents under it).
"""
from __future__ import annotations

import json
import logging
import os
import socket
from collections import OrderedDict
import subprocess
import sys
import tempfile
import threading
import time

import numpy as _np
from concurrent.futures import Future

from ..base import MXNetError, get_env
from .. import fault as _fault
from ..fault import _log_event
from ..telemetry import record_span, trace as _trace
from ..telemetry.registry import gauge, stats_group
from .batcher import (QueueFullError, RequestTimeout, ServeError,
                      ServerClosed, _profiler_on)
from .prefix_cache import rolling_hash as _rolling_hash

logger = logging.getLogger("mx.serve.fleet")

__all__ = ["Fleet", "FleetError", "ReplicaDied", "FLEET_STATS",
           "fleet_stats", "REPLICA_STATES"]

_STATS_LOCK = threading.Lock()
FLEET_STATS = stats_group("fleet", {
    "replicas_live": 0,       # level: replicas currently SERVING
    "failovers": 0,           # replica-death events with in-flight work
    "retries": 0,             # request re-dispatches after a failure
    "respawns": 0,            # failure respawns (swap restarts excluded)
    "swaps": 0,               # completed rolling drain-and-swap operations
    "drain_ms": 0.0,          # cumulative replica drain time
    "profile_divergence": 0,  # hellos that revealed replicas serving the
                              # same fleet under DIFFERENT tune profiles
    "affinity_hits": 0,       # dispatches routed by prefix affinity (the
                              # remembered replica was healthy and chosen)
}, lock=_STATS_LOCK, help="serving-fleet supervisor/router counters")


def fleet_stats(reset=False):
    """Snapshot (optionally reset) of the process-wide fleet counters."""
    return FLEET_STATS.snapshot(reset=reset)


# replica lifecycle, surfaced per-replica on the serve.replica_state gauge
REPLICA_STATES = {"dead": 0, "spawning": 1, "serving": 2, "draining": 3,
                  "failed": 4}
_REPLICA_STATE = gauge(
    "serve.replica_state",
    help="fleet replica lifecycle (0 dead, 1 spawning, 2 serving, "
         "3 draining, 4 failed)",
    labels=("replica",))


def _set_state_gauge(index, state):
    _REPLICA_STATE.labels(replica=index).set(REPLICA_STATES[state])


class FleetError(ServeError):
    """Fleet-level failure (no serving replica, aborted swap, ...)."""


class ReplicaDied(FleetError):
    """A replica died; carries the index and the original cause (the
    error that surfaces when the retry budget is spent)."""

    def __init__(self, msg, replica=None, cause=None):
        super().__init__(msg)
        self.replica = replica
        self.cause = cause


# error kinds a replica forwards over the wire -> client-visible classes
_WIRE_ERRORS = {
    "RequestTimeout": RequestTimeout,
    "QueueFullError": QueueFullError,
    "ServerClosed": ServerClosed,
    "ServeError": ServeError,
}


class _FleetRequest:
    __slots__ = ("rid", "prompt", "max_new", "deadline_at", "future",
                 "ctx", "attempts", "reroutes", "t_submit", "replica",
                 "first_error", "sampling", "prefix_hash")

    def __init__(self, rid, prompt, max_new, deadline_at, ctx,
                 sampling=None, prefix_hash=None):
        self.rid = rid
        self.prompt = prompt
        self.max_new = max_new
        self.deadline_at = deadline_at    # perf_counter deadline or None
        self.future = Future()
        self.ctx = ctx                    # fleet.request root context
        self.attempts = 0                 # failed dispatch/serve attempts
        self.reroutes = 0                 # silent ReplicaDraining re-routes
        self.t_submit = time.perf_counter()
        self.replica = None
        self.first_error = None
        # sampling params ride the wire verbatim (and survive failover
        # re-dispatch — including the router-assigned seed, so a retried
        # sampled request draws the SAME tokens on the new replica)
        self.sampling = sampling or {}
        self.prefix_hash = prefix_hash    # affinity key (None = no prefix)

    def sort_key(self):
        """EDF for failover re-dispatch: earliest deadline first,
        deadline-less after every deadline-holder, FIFO among peers."""
        return (self.deadline_at is None,
                self.deadline_at if self.deadline_at is not None
                else self.t_submit,
                self.t_submit)


class _EDFGate:
    """Deadline-ordered admission to replica claiming.

    `_pick()` breaks load ties by index, and concurrent dispatch threads
    race it arbitrarily — so under contention a deadline-less request
    could claim the least-loaded replica ahead of one about to expire.
    The gate serializes the CLAIM step in earliest-deadline-first order
    (`_FleetRequest.sort_key`: deadline holders first, FIFO among
    deadline-less peers): every dispatching request registers, and only
    the tightest-deadline waiter may proceed to `_pick()`; everyone else
    blocks on the condition until the head leaves. Claims are quick
    (pick + one socket write), so this orders the queue without
    meaningfully serializing throughput — and it unifies admission with
    the EDF failover re-dispatch order `_async_dispatch` already uses.
    """

    def __init__(self):
        self._cv = threading.Condition()
        self._waiting = []

    def enter(self, freq):
        with self._cv:
            self._waiting.append(freq)
            self._cv.notify_all()

    def leave(self, freq):
        with self._cv:
            try:
                self._waiting.remove(freq)
            except ValueError:
                pass
            self._cv.notify_all()

    def wait_turn(self, freq, timeout=0.02):
        """True when `freq` is the tightest-deadline waiter right now;
        otherwise block (bounded) for the head to advance and report
        whether it is our turn yet. Callers loop — their loop re-checks
        the request's own deadline/closing state between waits, so a
        non-head request is never parked unboundedly."""
        with self._cv:
            head = min(self._waiting, key=_FleetRequest.sort_key,
                       default=None)
            if head is freq or head is None:
                return True
            self._cv.wait(timeout)
            head = min(self._waiting, key=_FleetRequest.sort_key,
                       default=None)
            return head is freq or head is None


class _Replica:
    """Supervisor-side handle for one replica child. `generation` guards
    against stale reader threads / late death reports after a respawn."""

    def __init__(self, index):
        self.index = index
        self.state = "dead"
        self.generation = 0
        self.proc = None
        self.sock = None
        self.wlock = threading.Lock()
        self.pid = None
        self.hello = {}
        self.pong = {}
        self.version = None
        self.metrics_port = None
        self.inflight = {}            # rid -> _FleetRequest (fleet lock)
        self.last_pong = time.perf_counter()
        self.missed = 0
        self.consecutive_restarts = 0
        self.first_error = None       # original death reason (resurfaced)
        self.swap_pending = False     # orderly drain-exit in progress
        self.ready_evt = threading.Event()
        self.drained_evt = threading.Event()
        self.served_since_spawn = 0


class Fleet:
    """Replica supervisor + front-door router. ::

        spec = {"version": "v1", "config": {...DecoderConfig...},
                "seed": 0, "engine": {"max_slots": 4}}
        with serve.Fleet(spec, replicas=2) as fleet:
            toks = fleet.submit([3, 1, 4], max_new_tokens=8).result()
            fleet.swap(dict(spec, version="v2"))   # zero-downtime

    Knobs (constructor arg > MXNET_FLEET_* env > default): `replicas`,
    `heartbeat_ms`, `retry_budget`, `drain_timeout_ms`; plus
    `heartbeat_misses` (beats before hung) and `max_restarts` (bounded
    consecutive respawns per replica)."""

    def __init__(self, spec, *, replicas=None, heartbeat_ms=None,
                 retry_budget=None, drain_timeout_ms=None,
                 heartbeat_misses=3, max_restarts=3, workdir=None,
                 spawn_timeout=180.0, name="serve.fleet"):
        self.spec = dict(spec)
        self.version = str(self.spec.get("version", "v0"))
        self.name = name
        self.n = int(replicas if replicas is not None
                     else get_env("MXNET_FLEET_REPLICAS", 2, typ=int))
        if self.n < 1:
            raise FleetError("fleet needs at least one replica")
        hb = (heartbeat_ms if heartbeat_ms is not None
              else get_env("MXNET_FLEET_HEARTBEAT_MS", 500.0, typ=float))
        self.heartbeat_s = float(hb) / 1e3
        self.retry_budget = int(
            retry_budget if retry_budget is not None
            else get_env("MXNET_FLEET_RETRY_BUDGET", 2, typ=int))
        dt = (drain_timeout_ms if drain_timeout_ms is not None
              else get_env("MXNET_FLEET_DRAIN_TIMEOUT_MS", 30000.0,
                           typ=float))
        self.drain_timeout_s = float(dt) / 1e3
        self.heartbeat_misses = max(1, int(heartbeat_misses))
        self.max_restarts = max(0, int(max_restarts))
        self.spawn_timeout = float(spawn_timeout)
        self._workdir = workdir or tempfile.mkdtemp(prefix="mxfleet-")
        self._lock = threading.RLock()
        self._replicas = [_Replica(i) for i in range(self.n)]
        self._started = False
        self._closing = False
        self._listener = None
        self._port = None
        self._spec_path = None
        self._rid = [0]
        self._seed_counter = [0]          # router-level sampling seeds
        self._swap_lock = threading.Lock()
        self._monitor_thread = None
        # cap on how long a dispatch may wait for SOME replica to accept
        # (covers the respawn window when every replica died at once)
        self._dispatch_wait_s = max(30.0, self.drain_timeout_s)
        self._edf = _EDFGate()
        # prefix-affinity routing: block-quantized prefix hash -> replica
        # index that last served it. Bounded LRU under self._lock; the
        # block width mirrors the engines' prefix-cache granularity so
        # the router's key equals what a replica's cache could hit on.
        self._prefix_block = max(1, get_env("MXNET_SERVE_PREFIX_BLOCK",
                                            16, typ=int))
        self._affinity = OrderedDict()
        self._affinity_cap = 1024

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        """Bind the router socket, spawn every replica, wait for hellos."""
        if self._started:
            return self
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", 0))
        listener.listen(self.n + 2)
        with self._lock:
            self._spec_path = self._write_spec(self.spec)
            self._listener = listener
            self._port = listener.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"{self.name}-accept").start()
        for h in self._replicas:
            self._spawn(h, self._spec_path)
        deadline = time.perf_counter() + self.spawn_timeout
        for h in self._replicas:
            left = deadline - time.perf_counter()
            if left <= 0 or not h.ready_evt.wait(timeout=left):
                self.close()
                raise FleetError(
                    f"replica {h.index} failed to report hello within "
                    f"{self.spawn_timeout:.0f}s (see "
                    f"{self._replica_log(h.index)})")
        self._started = True
        self._monitor_thread = threading.Thread(
            target=self._monitor, daemon=True, name=f"{self.name}-monitor")
        self._monitor_thread.start()
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    def close(self, timeout=30.0):
        """Stop the fleet: orderly `stop` to every replica, then kill
        stragglers. In-flight futures fail with ServerClosed."""
        with self._lock:
            if self._closing:
                return
            self._closing = True
            handles = list(self._replicas)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for h in handles:
            try:
                self._send(h, {"type": "stop"})
            except OSError:
                pass
        deadline = time.perf_counter() + timeout
        for h in handles:
            if h.proc is None:
                continue
            try:
                h.proc.wait(timeout=max(0.1,
                                        deadline - time.perf_counter()))
            except subprocess.TimeoutExpired:
                h.proc.kill()
                try:
                    h.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
            with self._lock:
                doomed = list(h.inflight.values())
                h.inflight.clear()
                h.state = "dead"
                if h.sock is not None:
                    try:
                        h.sock.close()
                    except OSError:
                        pass
                    h.sock = None
            _set_state_gauge(h.index, "dead")
            for freq in doomed:
                _fail_future(freq, ServerClosed("fleet closed"))
        self._update_live()

    # -- spawning ----------------------------------------------------------
    def _write_spec(self, spec):
        os.makedirs(self._workdir, exist_ok=True)
        path = os.path.join(self._workdir,
                            f"spec_{spec.get('version', 'v0')}.json")
        with open(path, "w") as f:
            json.dump(spec, f)
        return path

    def _replica_log(self, index):
        return os.path.join(self._workdir, f"replica{index}.log")

    def _spawn(self, h, spec_path):
        """Launch one replica child (state -> spawning). The child's env
        is inherited verbatim — MXNET_COMPILE_CACHE_DIR for the warm
        start, MXNET_METRICS_PORT for the derived metrics port."""
        with self._lock:
            h.generation += 1
            h.state = "spawning"
            h.ready_evt.clear()
            h.drained_evt.clear()
            h.hello = {}
            h.pong = {}
            h.served_since_spawn = 0
            h.missed = 0
        _set_state_gauge(h.index, "spawning")
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH")
            else "")
        cmd = [sys.executable, "-m", "incubator_mxnet_tpu.serve.replica",
               "--connect", str(self._port), "--replica", str(h.index),
               "--spec", spec_path]
        log = open(self._replica_log(h.index), "ab")
        try:
            h.proc = subprocess.Popen(cmd, env=env,
                                      stdout=log, stderr=log,
                                      stdin=subprocess.DEVNULL)
        finally:
            log.close()
        logger.info("fleet: spawned replica %d pid %d (%s)", h.index,
                    h.proc.pid, os.path.basename(spec_path))

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._handshake, args=(conn,),
                             daemon=True,
                             name=f"{self.name}-reader").start()

    def _handshake(self, conn):
        try:
            conn.settimeout(self.spawn_timeout)
            rf = conn.makefile("r", encoding="utf-8", newline="\n")
            hello = json.loads(rf.readline())
            if hello.get("type") != "hello":
                conn.close()
                return
            i = int(hello["replica"])
            if not 0 <= i < self.n:
                conn.close()
                return
        except (OSError, ValueError):
            try:
                conn.close()
            except OSError:
                pass
            return
        conn.settimeout(None)
        h = self._replicas[i]
        with self._lock:
            h.sock = conn
            h.hello = hello
            h.pid = hello.get("pid")
            h.version = hello.get("version")
            h.metrics_port = hello.get("metrics_port")
            h.last_pong = time.perf_counter()
            h.missed = 0
            h.state = "serving"
            gen = h.generation
        _set_state_gauge(i, "serving")
        self._update_live()
        self._check_profile_divergence()
        logger.info("fleet: replica %d serving version %s "
                    "(pid %s, metrics port %s, warmup %.3fs, "
                    "compile cache %s, profile %s)", i, h.version, h.pid,
                    h.metrics_port, hello.get("warmup_s") or 0.0,
                    hello.get("compile_cache_size"),
                    hello.get("profile_hash") or "-")
        h.ready_evt.set()
        self._reader(h, rf, gen)

    # -- replica I/O -------------------------------------------------------
    def _send(self, h, msg):
        with self._lock:
            sock = h.sock
        if sock is None:
            raise OSError(f"replica {h.index} has no connection")
        data = (json.dumps(msg) + "\n").encode("utf-8")
        with h.wlock:
            sock.sendall(data)

    def _reader(self, h, rf, gen):
        try:
            for line in rf:
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue
                self._on_message(h, gen, msg)
        except OSError:
            pass
        with self._lock:
            stale = gen != h.generation
        if not stale:
            self._on_replica_down(h, gen, "connection lost")

    def _on_message(self, h, gen, msg):
        t = msg.get("type")
        if t == "pong":
            with self._lock:
                if gen != h.generation:
                    return
                h.pong = msg
                h.last_pong = time.perf_counter()
                h.missed = 0
            return
        if t == "drained":
            with _STATS_LOCK:
                FLEET_STATS["drain_ms"] += float(msg.get("drain_ms", 0.0))
            h.drained_evt.set()
            return
        if t in ("reply", "error"):
            with self._lock:
                if gen != h.generation:
                    return          # a failover already re-owns these rids
                freq = h.inflight.pop(msg.get("id"), None)
                if freq is not None and t == "reply":
                    # a served request proves the respawn healthy: reset
                    # the consecutive-restart budget and the stored death
                    # cause (PR-9 protocol)
                    h.served_since_spawn += 1
                    h.consecutive_restarts = 0
                    h.first_error = None
            if freq is None:
                return
            if t == "reply":
                self._resolve(h, freq, msg)
            else:
                self._on_wire_error(h, freq, msg)

    def _resolve(self, h, freq, msg):
        toks = _np.asarray(msg.get("tokens", []), dtype=_np.int32)
        if freq.future.set_running_or_notify_cancel():
            freq.future.set_result(toks)
        if freq.ctx is not None and _profiler_on():
            now = time.perf_counter()
            record_span("fleet.request", (now - freq.t_submit) * 1e6,
                        ts_us=freq.t_submit * 1e6, cat="fleet",
                        ctx=freq.ctx, replica=h.index,
                        attempts=freq.attempts + 1, tokens=int(toks.size))

    def _on_wire_error(self, h, freq, msg):
        kind = msg.get("kind", "ServeError")
        text = msg.get("message", "")
        if kind == "ReplicaDraining":
            # the routed-around drain signal: silent re-dispatch, never
            # client-visible, never billed against the retry budget
            freq.reroutes += 1
            if freq.reroutes > self.n + self.retry_budget + 2:
                _fail_future(freq, FleetError(
                    f"no admitting replica after {freq.reroutes} "
                    f"re-routes (fleet draining?)"))
                return
            self._async_dispatch([freq])
            return
        err = _WIRE_ERRORS.get(kind, ServeError)(
            f"replica {h.index}: {text}")
        _fail_future(freq, err)

    # -- submission / dispatch --------------------------------------------
    def submit(self, prompt_tokens, max_new_tokens=16, deadline_ms=None,
               temperature=0.0, top_k=0, top_p=1.0, seed=None):
        """Enqueue one generation request onto the fleet; returns a
        Future of the generated np.int32 token ids. Sampling params pass
        through to the replica engine; a sampled request without a seed
        gets a ROUTER-assigned one, so a failover re-dispatch replays
        the exact same draw sequence on the surviving replica."""
        if not self._started:
            raise FleetError("Fleet.start() (or `with fleet:`) first")
        if self._closing:
            raise ServerClosed("fleet is closed")
        prompt = _np.asarray(prompt_tokens, dtype=_np.int32).ravel()
        if prompt.size < 1:
            raise ServeError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ServeError("max_new_tokens must be >= 1")
        temperature = float(temperature)
        if temperature < 0.0:
            raise ServeError("temperature must be >= 0")
        if not 0.0 < float(top_p) <= 1.0:
            raise ServeError(f"top_p must be in (0, 1], got {top_p}")
        sampling = {}
        if temperature > 0.0:
            sampling["temperature"] = temperature
            if int(top_k):
                sampling["top_k"] = int(top_k)
            if float(top_p) != 1.0:
                sampling["top_p"] = float(top_p)
            if seed is None:
                with self._lock:
                    seed = self._seed_counter[0]
                    self._seed_counter[0] += 1
            sampling["seed"] = int(seed)
        ctx = _trace.request_root("fleet.request")
        with self._lock:
            self._rid[0] += 1
            rid = self._rid[0]
        deadline_at = (time.perf_counter() + deadline_ms / 1e3
                       if deadline_ms is not None else None)
        # affinity key: hash of the longest block-quantized prefix a
        # replica's cache could actually hit on ((plen-1)//block blocks —
        # one suffix token must remain to prefill). None = too short.
        nblocks = (int(prompt.size) - 1) // self._prefix_block
        phash = (_rolling_hash(prompt[:nblocks * self._prefix_block])
                 if nblocks >= 1 else None)
        freq = _FleetRequest(rid, prompt, int(max_new_tokens),
                             deadline_at, ctx, sampling=sampling,
                             prefix_hash=phash)
        self._dispatch(freq)
        return freq.future

    def generate(self, prompt_tokens, max_new_tokens=16, timeout=None,
                 deadline_ms=None, temperature=0.0, top_k=0, top_p=1.0,
                 seed=None):
        """submit() + wait."""
        return self.submit(prompt_tokens, max_new_tokens,
                           deadline_ms=deadline_ms,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, seed=seed).result(timeout=timeout)

    def _pick(self, exclude=(), prefer=None):
        """Least-loaded SERVING replica: router-side in-flight count,
        then replica-reported queue depth, then lowest index. `prefer`
        (the prefix-affinity target) wins outright when it is among the
        healthy candidates — affinity beats load balance because a row
        copy from a warm prefix cache is cheaper than any prefill."""
        with self._lock:
            cands = [h for h in self._replicas
                     if h.state == "serving" and h.sock is not None
                     and h.index not in exclude]
            if not cands:
                return None
            if prefer is not None:
                for h in cands:
                    if h.index == prefer:
                        return h
            return min(cands, key=lambda h: (
                len(h.inflight),
                h.pong.get("waiting", 0) + h.pong.get("running", 0),
                h.index))

    def _dispatch(self, freq, exclude=()):
        """Place one request (dispatch, or re-dispatch after failover /
        drain re-route), EDF-gated: among requests concurrently waiting
        to claim a replica, the tightest deadline claims first. Retries
        alternate replicas under the budget."""
        self._edf.enter(freq)
        try:
            self._dispatch_inner(freq, exclude)
        finally:
            self._edf.leave(freq)

    def _dispatch_inner(self, freq, exclude=()):
        exclude = set(exclude)
        wait_deadline = time.perf_counter() + self._dispatch_wait_s
        while True:
            if freq.future.done():
                return
            remaining_ms = None
            if freq.deadline_at is not None:
                left = freq.deadline_at - time.perf_counter()
                if left <= 0:
                    _fail_future(freq, RequestTimeout(
                        f"deadline expired after "
                        f"{(time.perf_counter() - freq.t_submit) * 1e3:.1f}"
                        f"ms before a replica accepted the request"))
                    return
                remaining_ms = max(1.0, left * 1e3)
            if not self._edf.wait_turn(freq):
                continue            # not the tightest deadline waiting
            prefer = None
            if freq.prefix_hash is not None:
                with self._lock:
                    prefer = self._affinity.get(freq.prefix_hash)
            h = self._pick(exclude, prefer=prefer)
            if h is None:
                if exclude:
                    exclude = set()     # wrap around before giving up
                    continue
                if self._closing or not self._respawn_possible():
                    _fail_future(freq, self._terminal_error())
                    return
                if time.perf_counter() > wait_deadline:
                    _fail_future(freq, FleetError(
                        f"no serving replica within "
                        f"{self._dispatch_wait_s:.0f}s"))
                    return
                time.sleep(0.02)        # a respawn/drain window; re-check
                continue
            try:
                _fault.inject("fleet.dispatch")
                msg = {"type": "request", "id": freq.rid,
                       "prompt": freq.prompt.tolist(),
                       "max_new": freq.max_new}
                msg.update(freq.sampling)
                if remaining_ms is not None:
                    msg["deadline_ms"] = remaining_ms
                if freq.ctx is not None:
                    msg["trace"] = freq.ctx.to_dict()
                with self._lock:
                    if h.state != "serving" or h.sock is None:
                        continue
                    h.inflight[freq.rid] = freq
                    freq.replica = h.index
                self._send(h, msg)
                if freq.prefix_hash is not None:
                    with self._lock:
                        self._affinity[freq.prefix_hash] = h.index
                        self._affinity.move_to_end(freq.prefix_hash)
                        while len(self._affinity) > self._affinity_cap:
                            self._affinity.popitem(last=False)
                    if prefer == h.index:
                        with _STATS_LOCK:
                            FLEET_STATS["affinity_hits"] += 1
                return
            except (OSError, MXNetError, TimeoutError) as e:
                with self._lock:
                    h.inflight.pop(freq.rid, None)
                if not self._count_retry(freq, e, where="fleet.dispatch"):
                    return
                exclude.add(h.index)
                if len(exclude) >= self.n:
                    exclude = set()

    def _async_dispatch(self, freqs):
        """(Re-)dispatch off the calling thread — reader and monitor
        threads must never block in the dispatch wait loop."""
        freqs = sorted(freqs, key=_FleetRequest.sort_key)   # EDF order

        def run():
            for freq in freqs:
                exclude = ({freq.replica} if freq.replica is not None
                           else set())
                self._dispatch(freq, exclude=exclude)
        threading.Thread(target=run, daemon=True,
                         name=f"{self.name}-redispatch").start()

    def _count_retry(self, freq, err, where):
        """`fault.retrying` semantics for the router: bounded attempts
        with structured fault-logger records; the ORIGINAL error surfaces
        when the budget is spent. Returns True when a retry is allowed."""
        freq.attempts += 1
        if freq.first_error is None:
            freq.first_error = err
        if freq.attempts > self.retry_budget:
            _log_event("fault.retry_exhausted", point=where,
                       attempts=freq.attempts,
                       error=repr(freq.first_error))
            _fail_future(freq, ReplicaDied(
                f"request failed after {freq.attempts} attempt(s); "
                f"original error: {freq.first_error}",
                replica=freq.replica, cause=freq.first_error))
            return False
        with _STATS_LOCK:
            FLEET_STATS["retries"] += 1
        _log_event("fault.retry", point=where, attempt=freq.attempts,
                   error=repr(err), sleep=0)
        return True

    def _respawn_possible(self):
        with self._lock:
            return any(h.state in ("spawning", "serving", "draining",
                                   "dead")
                       for h in self._replicas)

    def _terminal_error(self):
        with self._lock:
            causes = {h.index: repr(h.first_error)
                      for h in self._replicas if h.first_error is not None}
        return FleetError(
            f"no replica can serve (all failed); original errors: "
            f"{causes or 'none recorded'}")

    # -- failure detection / failover / respawn ---------------------------
    def _on_replica_down(self, h, gen, reason):
        """Single funnel for replica death: socket EOF, process exit, or
        missed heartbeats. Idempotent per generation."""
        with self._lock:
            if gen != h.generation or h.state in ("dead", "failed"):
                return
            h.generation += 1          # invalidate reader + late reports
            h.state = "dead"
            sock, h.sock = h.sock, None
            swap_exit = h.swap_pending
            doomed = list(h.inflight.values())
            h.inflight.clear()
            cause = ReplicaDied(f"replica {h.index} {reason}",
                                replica=h.index)
            if h.first_error is None:
                h.first_error = cause
        _set_state_gauge(h.index, "dead")
        self._update_live()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if self._closing:
            for freq in doomed:
                _fail_future(freq, ServerClosed("fleet closed"))
            return
        logger.warning("fleet: replica %d down (%s), %d in-flight",
                       h.index, reason, len(doomed))
        _log_event("fleet.replica_down", replica=h.index, reason=reason,
                   inflight=len(doomed), swap=swap_exit)
        if doomed:
            with _STATS_LOCK:
                FLEET_STATS["failovers"] += 1
            # every failover hop bills the retry budget; requests past it
            # surface the ORIGINAL death error (fault.retrying semantics)
            retryable = [freq for freq in doomed
                         if self._count_retry(freq, cause,
                                              where="fleet.failover")]
            if retryable:
                self._async_dispatch(retryable)
        if not swap_exit:
            threading.Thread(target=self._respawn, args=(h,),
                             daemon=True,
                             name=f"{self.name}-respawn").start()

    def _respawn(self, h):
        """Warm respawn with the PR-9 bounded-consecutive-restarts
        protocol. Runs on its own thread, outside every lock."""
        while not self._closing:
            with self._lock:
                h.consecutive_restarts += 1
                n = h.consecutive_restarts
            if n > self.max_restarts:
                with self._lock:
                    h.state = "failed"
                _set_state_gauge(h.index, "failed")
                logger.error(
                    "fleet: replica %d exceeded %d consecutive restarts; "
                    "marking failed. Original error: %r", h.index,
                    self.max_restarts, h.first_error)
                _log_event("fleet.restart_exhausted", replica=h.index,
                           restarts=n - 1, error=repr(h.first_error))
                return
            try:
                _fault.inject("fleet.respawn")
            except Exception as e:
                logger.warning("fleet: respawn of replica %d failed "
                               "(attempt %d): %s", h.index, n, e)
                continue
            with _STATS_LOCK:
                FLEET_STATS["respawns"] += 1
            try:
                self._spawn(h, self._spec_path)
            except OSError as e:
                with self._lock:
                    if h.first_error is None:
                        h.first_error = e
                continue
            if h.ready_evt.wait(timeout=self.spawn_timeout):
                return
            # never said hello: kill and bill another consecutive restart
            if h.proc is not None:
                h.proc.kill()
            with self._lock:
                h.state = "dead"
                h.generation += 1
            _set_state_gauge(h.index, "dead")

    def _monitor(self):
        """Liveness + heartbeat sweep. Process-exit polling catches idle
        deaths within one beat; missed pongs catch hangs."""
        seq = 0
        while not self._closing:
            time.sleep(self.heartbeat_s)
            if self._closing:
                return
            seq += 1
            for h in list(self._replicas):
                with self._lock:
                    state, gen = h.state, h.generation
                if state in ("failed", "dead", "spawning"):
                    continue
                proc = h.proc
                if proc is not None and proc.poll() is not None:
                    self._on_replica_down(
                        h, gen, f"process exited rc={proc.returncode}")
                    continue
                if state != "serving":
                    continue            # draining: drain path owns it
                try:
                    _fault.inject("fleet.heartbeat")
                    self._send(h, {"type": "ping", "seq": seq})
                    age = time.perf_counter() - h.last_pong
                    if age > self.heartbeat_s * 1.5:
                        h.missed += 1
                    else:
                        h.missed = 0
                except (OSError, MXNetError, TimeoutError) as e:
                    h.missed += 1
                    logger.warning(
                        "fleet: heartbeat to replica %d failed "
                        "(miss %d/%d): %s", h.index, h.missed,
                        self.heartbeat_misses, e)
                if h.missed >= self.heartbeat_misses:
                    logger.warning(
                        "fleet: replica %d missed %d heartbeats; "
                        "declaring hung", h.index, h.missed)
                    if h.proc is not None:
                        h.proc.kill()
                    self._on_replica_down(
                        h, gen,
                        f"missed {h.missed} heartbeats (hang/SIGSTOP)")

    # -- drain-and-swap ----------------------------------------------------
    def swap(self, spec=None, version=None):
        """Rolling zero-downtime upgrade: drain-and-swap every replica
        onto a new version-pinned spec, one at a time, routing traffic
        around the draining replica. Zero dropped requests: resident
        requests finish before the restart; a drain-timeout hard-stop
        hands its leftovers to the failover path."""
        if spec is None:
            if version is None:
                raise FleetError("swap() needs a spec or a version")
            spec = dict(self.spec, version=version)
        spec = dict(spec)
        new_version = str(spec.get("version", "v0"))
        if not self._started:
            raise FleetError("Fleet.start() first")
        with self._swap_lock:
            path = self._write_spec(spec)
            # new spec becomes the respawn target immediately: a replica
            # that dies mid-swap already comes back on the new version
            with self._lock:
                self._spec_path = path
            t_swap = time.perf_counter()
            for h in list(self._replicas):
                with self._lock:
                    state = h.state
                if state == "failed":
                    continue
                try:
                    _fault.inject("fleet.swap")
                except Exception as e:
                    raise FleetError(
                        f"swap to {new_version!r} aborted at replica "
                        f"{h.index}: {e}") from e
                self._swap_one(h, path, new_version)
            self.spec = spec
            self.version = new_version
            with _STATS_LOCK:
                FLEET_STATS["swaps"] += 1
            logger.info("fleet: rolling swap to version %s complete "
                        "(%.1fms)", new_version,
                        (time.perf_counter() - t_swap) * 1e3)

    def _swap_one(self, h, spec_path, new_version):
        with self._lock:
            if h.state != "serving":
                return                  # death path already respawns new
            h.state = "draining"
            h.swap_pending = True
            h.drained_evt.clear()
            gen = h.generation
        _set_state_gauge(h.index, "draining")
        self._update_live()
        try:
            self._send(h, {"type": "drain",
                           "timeout_ms": self.drain_timeout_s * 1e3})
        except OSError:
            pass                        # died mid-send; down path runs
        if not h.drained_evt.wait(timeout=self.drain_timeout_s + 5.0):
            logger.warning("fleet: replica %d drain timed out; "
                           "hard-stopping (failover absorbs leftovers)",
                           h.index)
            if h.proc is not None:
                h.proc.kill()
        proc = h.proc
        if proc is not None:
            try:
                proc.wait(timeout=30.0)
            except subprocess.TimeoutExpired:
                proc.kill()
        # the child's exit lands in _on_replica_down (reader EOF or the
        # monitor's poll) which, seeing swap_pending, fails over any
        # leftovers but leaves the respawn to us
        t0 = time.perf_counter()
        while True:
            with self._lock:
                if h.state in ("dead", "failed"):
                    break
            if time.perf_counter() - t0 > 30.0:
                self._on_replica_down(h, gen, "drain exit not observed")
                break
            time.sleep(0.01)
        with self._lock:
            h.swap_pending = False
            h.consecutive_restarts = 0
            h.first_error = None
        self._spawn(h, spec_path)
        if not h.ready_evt.wait(timeout=self.spawn_timeout):
            raise FleetError(
                f"replica {h.index} failed to come back on version "
                f"{new_version!r} within {self.spawn_timeout:.0f}s")

    # -- introspection -----------------------------------------------------
    def _update_live(self):
        with self._lock:
            live = sum(1 for h in self._replicas if h.state == "serving")
        with _STATS_LOCK:
            FLEET_STATS["replicas_live"] = live

    def stats(self):
        """Plain-data snapshot: per-replica state + the fleet counters."""
        with self._lock:
            reps = [{
                "replica": h.index, "state": h.state, "pid": h.pid,
                "version": h.version, "metrics_port": h.metrics_port,
                "inflight": len(h.inflight),
                "consecutive_restarts": h.consecutive_restarts,
                "warmup_s": h.hello.get("warmup_s"),
                "compile_cache_size": (h.pong or h.hello).get(
                    "compile_cache_size"),
                "retraces": h.pong.get("retraces"),
                "profile_hash": h.hello.get("profile_hash"),
                "prefix_hits": h.pong.get("prefix_hits"),
            } for h in self._replicas]
        out = {"version": self.version, "replicas": reps}
        out.update(FLEET_STATS.snapshot())
        return out

    def _check_profile_divergence(self):
        """A fleet must be homogeneously tuned: every serving replica's
        hello-reported deployment-profile hash should agree (None =
        untuned, which is homogeneous too). More than one distinct hash
        means some replica found a different — or stale — profile on
        disk; bill `fleet.profile_divergence` and log the structured
        event so operators see it the moment the odd replica hellos."""
        with self._lock:
            hashes = sorted({h.hello.get("profile_hash")
                             for h in self._replicas
                             if h.state == "serving"
                             and h.hello.get("profile_hash")})
        if len(hashes) > 1:
            with _STATS_LOCK:
                FLEET_STATS["profile_divergence"] += 1
            _log_event("fleet.profile_divergence", hashes=hashes)
            return True
        return False

    def retraces_after_warmup(self):
        """Max replica-reported compiled-program growth since warmup
        (from the last pong of each live replica; -1 = unknown)."""
        with self._lock:
            vals = [h.pong.get("retraces") for h in self._replicas
                    if h.pong.get("retraces") is not None]
        if not vals:
            return -1
        return max(vals)

    def assert_no_retraces(self):
        """Fleet-wide zero-retrace contract: every replica's engine must
        report 0 compiled-program growth since its warmup."""
        with self._lock:
            bad = {h.index: h.pong.get("retraces")
                   for h in self._replicas
                   if (h.pong.get("retraces") or 0) > 0}
        if bad:
            raise MXNetError(
                f"fleet replicas retraced after warmup: {bad}")
        return 0


def _fail_future(freq, exc):
    if freq.future.set_running_or_notify_cancel():
        freq.future.set_exception(exc)
