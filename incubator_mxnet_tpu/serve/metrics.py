"""Serving metrics: profiler-style counters, latency percentiles, batch
occupancy, and a Chrome-trace lane.

Two layers, mirroring the PR 2 dispatch-stats design (ops/segment.py
DISPATCH_STATS):

  * SERVE_STATS — one flat module-level counter dict aggregated across every
    Server in the process, readable via `profiler.serve_stats()` (the
    profiler-counter surface the reference exposes through MXProfile*
    counters). Increments take the module _STATS_LOCK: `dict[k] += n` is a
    read-modify-write, and submit() runs on every client thread at once —
    under contention the GIL does NOT make it atomic, so the lock-free
    version dropped counts (mxlint: lock-shared-mutation). The lock also
    makes `serve_stats(reset=True)`'s snapshot+zero atomic: a reset can no
    longer eat increments that land between the copy and the zeroing.
  * ServeMetrics — per-Server instance metrics with the derived views the
    counters cannot carry: latency p50/p95/p99 over a bounded reservoir,
    a batch-occupancy histogram keyed by bucket, live queue depth, and
    requests/s over the server's lifetime.

Chrome-trace lane: each executed batch lands in the profiler event buffer
(name "serve.batch", cat "serve") when the profiler is running, so
`profiler.dump()` renders serving alongside op dispatch and the storage
lane — the serving analog of the reference's per-worker device lanes.
"""
from __future__ import annotations

import heapq
import threading
import time
from collections import deque

from ..telemetry.registry import stats_group as _stats_group

__all__ = ["SERVE_STATS", "ServeMetrics", "serve_stats", "percentile"]

# Process-wide aggregate (all Server instances). Field meanings:
#   requests        submitted (accepted into the queue)
#   replies         futures resolved with a result
#   rejected        admission-control failures (reject-newest policy)
#   shed            queued requests dropped by the shed-oldest policy
#   timeouts        requests failed for missing their deadline while queued
#   errors          requests failed by an execution error
#   batches         batch executions
#   padded_rows     pad rows added to round batches up to their bucket
#   programs_compiled  first-execution compiles (bucket warmups); steady
#                      state MUST hold this flat (zero-retrace contract)
# Continuous-batching family (serve/continuous.py ContinuousEngine):
#   decode_iterations     engine decode steps executed (each advances
#                         EVERY active KV slot one token)
#   decode_tokens         tokens generated across all requests
#   decode_prefill_tokens prompt tokens prefilled into KV slots
#   decode_admitted       requests granted a KV slot (deadline-aware)
#   decode_retired        requests finished and their slot freed
#   decode_sampled_tokens tokens produced by sampled (temperature > 0)
#                         lanes — greedy lanes never count here
#   decode_draft_accepted speculative draft tokens accepted (emitted
#                         without their own forward pass)
#   decode_draft_rejected speculative draft tokens rejected at verify
# Guards every SERVE_STATS mutation (all Server instances, all threads).
_STATS_LOCK = threading.Lock()

# Adopted into the telemetry registry as the `serve` stats group: increments
# stay `SERVE_STATS[k] += n` under _STATS_LOCK (the group's owner lock is
# THE SAME lock object, so snapshot+zero excludes concurrent increments),
# and the counters surface in telemetry.snapshot()/prometheus_text().
SERVE_STATS = _stats_group("serve", {
    "requests": 0, "replies": 0, "rejected": 0, "shed": 0,
    "timeouts": 0, "errors": 0, "batches": 0, "padded_rows": 0,
    "programs_compiled": 0,
    "decode_iterations": 0, "decode_tokens": 0,
    "decode_prefill_tokens": 0, "decode_admitted": 0,
    "decode_retired": 0, "decode_sampled_tokens": 0,
    "decode_draft_accepted": 0, "decode_draft_rejected": 0,
}, lock=_STATS_LOCK,
    help="process-wide serving counters (profiler.serve_stats)")


def serve_stats(reset=False):
    """Snapshot of the process-wide serving counters (read via
    `profiler.serve_stats()` or `mx.serve.stats()`). The snapshot and the
    optional reset are one atomic step, so no increment is ever lost
    between them."""
    return SERVE_STATS.snapshot(reset=reset)


def percentile(sorted_vals, q):
    """Nearest-rank percentile over an already-sorted list (no numpy needed
    on the reply path)."""
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class ServeMetrics:
    """Per-Server metrics. All mutators take the internal lock; `snapshot`
    returns plain data safe to json.dumps."""

    LATENCY_WINDOW = 4096    # bounded reservoir: recent-request percentiles
    SLOWEST_K = 8            # top-K slowest-request table in the timeline

    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._lat_ms = deque(maxlen=self.LATENCY_WINDOW)
        # bucket -> [batches, occupied_rows, padded_rows]
        self._occupancy = {}
        self.counters = {k: 0 for k in SERVE_STATS}
        self.queue_depth = 0
        self.queue_depth_max = 0
        # request-timeline attribution: total time requests spent QUEUED
        # (waiting for a batch slot — the serving analog of data-stall)
        # vs total batch EXECUTION time (the compute side)
        self.queue_wait_ms_total = 0.0
        self.exec_ms_total = 0.0
        # top-K slowest requests: min-heap of (total_ms, seq, row) — the
        # per-request forensics (trace id, wait/exec split, batch size,
        # deadline margin) percentiles cannot carry
        self._slowest = []
        self._slow_seq = 0

    def count(self, key, n=1):
        with self._lock:
            self.counters[key] += n
        with _STATS_LOCK:
            SERVE_STATS[key] += n

    def set_queue_depth(self, depth):
        with self._lock:
            self.queue_depth = depth
            if depth > self.queue_depth_max:
                self.queue_depth_max = depth

    def observe_batch(self, bucket, occupancy, exec_ms, queue_depth,
                      queue_wait_ms=0.0, member_traces=None):
        """One executed batch: occupancy rows served out of `bucket` slots.
        `queue_wait_ms` is the SUM over the batch's requests of their time
        spent queued (request-timeline attribution: wait vs compute).
        `member_traces` is the list of the batch's member request trace
        ids — the batch span links its N members in the Chrome trace."""
        pad = bucket - occupancy
        with self._lock:
            self.counters["batches"] += 1
            self.counters["padded_rows"] += pad
            row = self._occupancy.setdefault(bucket, [0, 0, 0])
            row[0] += 1
            row[1] += occupancy
            row[2] += pad
            self.queue_depth = queue_depth
            if queue_depth > self.queue_depth_max:
                self.queue_depth_max = queue_depth
            self.queue_wait_ms_total += queue_wait_ms
            self.exec_ms_total += exec_ms
        with _STATS_LOCK:
            SERVE_STATS["batches"] += 1
            SERVE_STATS["padded_rows"] += pad
        # unified span lane: `span.duration_us{name="serve.batch"}` in the
        # registry + a "serve.batch" Chrome-trace event while profiling.
        # The batch span LINKS its member requests: their trace ids ride
        # in args (capped — a bucket-256 batch must not bloat the event).
        # Recorded only while a collector is active: this runs ON the
        # batcher thread — the serving pipeline's serialization point —
        # at thousands of batches/s, where each ~10us record measurably
        # cuts throughput (the ≤2% A/B guard); the always-on batch
        # aggregates live in this object and SERVE_STATS either way
        from ..telemetry import record_span, trace as _trace
        if _trace.enabled() and _trace.collector_active():
            extra = {}
            if member_traces:
                extra["member_traces"] = ",".join(member_traces[:16])
                extra["n_member_traces"] = len(member_traces)
            record_span("serve.batch", exec_ms * 1000.0, cat="serve",
                        bucket=bucket, occupancy=occupancy,
                        queue_depth=queue_depth, **extra)

    def observe_latency(self, ms):
        with self._lock:
            self._lat_ms.append(ms)

    def observe_request(self, total_ms, trace_id=None, queue_wait_ms=0.0,
                        exec_ms=0.0, batch_size=None,
                        deadline_margin_ms=None):
        """Feed the top-K slowest-request table, keeping only the K
        slowest seen (bounded min-heap). Runs on the batcher thread per
        reply: admission is checked FIRST and the row dict is built only
        for the rare request that actually displaces one — steady state
        pays a compare, not a 7-key dict + four round()s."""
        with self._lock:
            if len(self._slowest) >= self.SLOWEST_K \
                    and total_ms <= self._slowest[0][0]:
                return
            row = {"total_ms": round(total_ms, 3),
                   "trace_id": trace_id,
                   "queue_wait_ms": round(queue_wait_ms, 3),
                   "exec_ms": round(exec_ms, 3),
                   "batch_size": batch_size,
                   "deadline_margin_ms": (round(deadline_margin_ms, 3)
                                          if deadline_margin_ms is not None
                                          else None)}
            self._slow_seq += 1
            item = (total_ms, self._slow_seq, row)
            if len(self._slowest) < self.SLOWEST_K:
                heapq.heappush(self._slowest, item)
            else:
                heapq.heapreplace(self._slowest, item)

    def snapshot(self):
        with self._lock:
            lat = sorted(self._lat_ms)
            elapsed = time.perf_counter() - self._t0
            counters = dict(self.counters)
            occ = {b: {"batches": r[0], "rows": r[1], "padded": r[2],
                       "mean_occupancy": round(r[1] / (r[0] * b), 4)}
                   for b, r in sorted(self._occupancy.items())}
            depth, depth_max = self.queue_depth, self.queue_depth_max
            wait_ms = self.queue_wait_ms_total
            exec_ms = self.exec_ms_total
            slowest = [row for _, _, row in
                       sorted(self._slowest, reverse=True)]
        out = dict(counters)
        out["queue_depth"] = depth
        out["queue_depth_max"] = depth_max
        out["batch_occupancy"] = occ
        out["elapsed_s"] = round(elapsed, 3)
        out["requests_per_sec"] = round(
            counters["replies"] / elapsed, 2) if elapsed > 0 else 0.0
        for q in (50, 95, 99):
            v = percentile(lat, q)
            out[f"p{q}_ms"] = round(v, 3) if v is not None else None
        # request timeline: where did request time go — queued (the serving
        # data-stall) vs executing (compute)? wait is summed PER REQUEST,
        # exec per batch, so wait can exceed exec under deep queues.
        busy = wait_ms + exec_ms
        out["timeline"] = {
            "queue_wait_ms": round(wait_ms, 3),
            "exec_ms": round(exec_ms, 3),
            "queue_wait_pct": round(100.0 * wait_ms / busy, 2) if busy
            else 0.0,
            "exec_pct": round(100.0 * exec_ms / busy, 2) if busy else 0.0,
            # top-K slowest requests, slowest first: the per-request
            # forensics row (trace id -> grep the Chrome trace / flight
            # recorder; wait-vs-exec split; batch size; how close the
            # deadline came)
            "slowest": slowest,
        }
        return out

    def prometheus_lines(self, server="serve"):
        """Per-server gauges in Prometheus text form — appended to the
        process registry text by `Server.metrics_text()` (per-instance
        state lives here, not in the process-wide SERVE_STATS group)."""
        from ..telemetry.registry import _prom_label_value
        snap = self.snapshot()
        lab = f'{{server="{_prom_label_value(server)}"}}'
        lines = []
        for k in ("queue_depth", "queue_depth_max", "requests_per_sec",
                  "elapsed_s"):
            lines.append(f"mx_server_{k}{lab} {snap[k]}")
        for q in (50, 95, 99):
            v = snap[f"p{q}_ms"]
            if v is not None:
                lines.append(f"mx_server_latency_p{q}_ms{lab} {v}")
        tl = snap["timeline"]
        lines.append(f"mx_server_queue_wait_ms_total{lab} "
                     f"{tl['queue_wait_ms']}")
        lines.append(f"mx_server_exec_ms_total{lab} {tl['exec_ms']}")
        return lines
