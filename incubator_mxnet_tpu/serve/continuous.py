"""Continuous (iteration-level) batching for autoregressive models.

`serve.Server` (PR 3) batches STATELESS single-shot requests: a request
joins exactly one batch, the batch runs one program, done. Autoregressive
models break that shape — one request is N sequential decode iterations
over private KV state, requests finish at different times, and a static
batch must run every member to the LONGEST member's length while admitted
work waits whole batches. This module is the Orca-style answer, rebuilt
JAX-native per the PAPER.md survey (one compiled decode program, state as
plain device buffers, zero retraces):

  * **Slot memory** (`serve.kv_pool.KVCachePool`): a fixed-shape KV slab
    carved once; each admitted request claims a slot ROW; join/leave is
    host bookkeeping and can never change a compiled program's shapes.
  * **Two fixed-shape programs** compiled once at warmup and reused for
    every mixed batch: `prefill` (writes a claimed slot's prompt KV page +
    emits the first token, pad lanes scatter into the pool's garbage row)
    and `decode` (steps ALL slots one token — inactive slots are masked
    lanes, their writes land in the garbage row). Both donate the KV
    buffers, so updates are in-place `dynamic_update_slice` scatters on
    accelerators (the `.at[rows, layer, pos].set(...)` idiom).
  * **Iteration-level scheduling**: every engine iteration first retires
    finished requests (their slots free IMMEDIATELY, not at batch end),
    then admits waiting requests under a prefill token budget
    (`MXNET_SERVE_PREFILL_BUDGET` — bounds how much prefill work may
    delay in-flight decode iterations), then runs one decode step for
    every active slot. Admission is DEADLINE-AWARE, not FIFO: waiting
    requests are granted slots earliest-deadline-first (SLO-aware
    admission over the PR-3 deadline plumbing), and a request whose
    deadline expires while waiting fails fast with `RequestTimeout`.
  * **Zero retraces after warmup** is asserted the PR-3 way: the
    `programs_compiled` counter and `compile_cache_size()` must stay flat
    over any join/leave pattern (tests/test_continuous.py drives ragged
    mixed traffic and checks both).
  * **O(load) warmup**: `deploy.maybe_enable_compile_cache()` wires
    `MXNET_COMPILE_CACHE_DIR` onto jax's persistent compilation cache
    before the first compile, so a second replica (or a restart) loads
    the serialized executables instead of recompiling — measured by
    `benchmark/serve_bench.py --autoregressive` (compile-skip section).

Tracing: one request = ONE trace across its N iterations. The root
`serve.request` context is minted at `submit()` (PR-13 plumbing); the
engine records `serve.prefill` (admission -> first token) and
`serve.decode` (first -> last token, N iterations) as children, and
closes the root at retirement — while the profiler collects, the whole
request renders as a single tree in the Chrome trace.

The bundled `CachedDecoder` is a small pre-norm transformer decoder over
the slot pool (greedy argmax decoding, deterministic) — the LLM-shaped
model side for tests and the bench; any object with the same
`prefill`/`decode`/`compile_cache_size` contract serves.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as _np

from ..base import MXNetError, get_env
from .. import fault as _fault
from ..telemetry import (record_span, trace as _trace, mem_on_oom,
                         mem_install_oom_hook)
from .batcher import (ServeError, QueueFullError, RequestTimeout,
                      ServerClosed, ReplicaDraining, _fail, _profiler_on)
from .metrics import SERVE_STATS, _STATS_LOCK, percentile
from .kv_pool import KVCachePool, SlotsFullError

__all__ = ["DecoderConfig", "CachedDecoder", "ContinuousEngine",
           "init_decoder_params"]


# ---------------------------------------------------------------------------
# model: a small cached-KV transformer decoder (greedy, deterministic)
# ---------------------------------------------------------------------------
class DecoderConfig:
    """Static shape/config record for `CachedDecoder` (all ints; nothing
    here ever becomes a tracer)."""

    def __init__(self, vocab=256, embed=64, layers=2, heads=4,
                 head_dim=16, mlp_hidden=None, max_len=128,
                 dtype="float32"):
        self.vocab = int(vocab)
        self.embed = int(embed)
        self.layers = int(layers)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.mlp_hidden = int(mlp_hidden if mlp_hidden is not None
                              else 4 * embed)
        self.max_len = int(max_len)
        self.dtype = str(dtype)
        if self.heads * self.head_dim != self.embed:
            raise ServeError(
                f"heads*head_dim ({self.heads}x{self.head_dim}) must "
                f"equal embed ({self.embed})")

    def as_dict(self):
        return {k: getattr(self, k) for k in
                ("vocab", "embed", "layers", "heads", "head_dim",
                 "mlp_hidden", "max_len", "dtype")}


def init_decoder_params(config, seed=0):
    """Deterministic random params (pytree of jnp arrays, layer-stacked
    on a leading L axis so the layer loop indexes one buffer)."""
    import jax
    import jax.numpy as jnp
    c = config
    keys = jax.random.split(jax.random.PRNGKey(seed), 8)
    s = 1.0 / _np.sqrt(c.embed)
    m = 1.0 / _np.sqrt(c.mlp_hidden)

    def rnd(key, shape, scale):
        return (jax.random.normal(key, shape) * scale).astype(c.dtype)

    return {
        "emb": rnd(keys[0], (c.vocab, c.embed), 1.0),
        "pos": rnd(keys[1], (c.max_len, c.embed), 0.1),
        "wq": rnd(keys[2], (c.layers, c.embed, c.embed), s),
        "wk": rnd(keys[3], (c.layers, c.embed, c.embed), s),
        "wv": rnd(keys[4], (c.layers, c.embed, c.embed), s),
        "wo": rnd(keys[5], (c.layers, c.embed, c.embed), s),
        "w1": rnd(keys[6], (c.layers, c.embed, c.mlp_hidden), s),
        "w2": rnd(keys[7], (c.layers, c.mlp_hidden, c.embed), m),
        "ln1": jnp.ones((c.layers, c.embed), dtype=c.dtype),
        "ln2": jnp.ones((c.layers, c.embed), dtype=c.dtype),
        "lnf": jnp.ones((c.embed,), dtype=c.dtype),
    }


def _rmsnorm(x, scale):
    import jax.numpy as jnp
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * scale / jnp.sqrt(var + 1e-6)


def _make_prefill(config, window=None):
    """Build the prefill step: full causal forward over the padded prompt
    page, KV written into the claimed slot rows, first token emitted.

    Shapes are FIXED by (P lanes, window, pool rows): the compiled
    program is reused for every admission wave — a lane that has no
    request this wave carries slot_row = garbage and its writes vanish.

    `window` (default max_len) is the prompt page width: attention and
    the KV write cover positions [0, window), so a serving config with
    short prompts pays O(window^2), not O(max_len^2), per wave. Slot
    positions past the window keep the PREVIOUS tenant's bytes — that is
    safe by the decode mask (reads clamp to the current request's
    `[0, cur_len]`), and exactly what the poison-fill isolation test
    proves."""
    import jax
    import jax.numpy as jnp
    c = config
    W = int(window if window is not None else c.max_len)
    if not 1 <= W <= c.max_len:
        raise ServeError(f"prefill window {W} outside [1, {c.max_len}]")
    scale = 1.0 / _np.sqrt(c.head_dim)

    def prefill(params, k_cache, v_cache, tokens, lengths, slot_rows):
        # tokens (P, W) int32, lengths (P,) int32, slot_rows (P,) int32
        P = tokens.shape[0]
        x = params["emb"][tokens] + params["pos"][None, :W]
        pos = jnp.arange(W)
        key_valid = pos[None, :] < lengths[:, None]            # (P, W)
        causal = pos[:, None] >= pos[None, :]                  # (W, W)
        mask = causal[None, None] & key_valid[:, None, None]   # (P,1,W,W)
        for l in range(c.layers):
            h = _rmsnorm(x, params["ln1"][l])
            q = (h @ params["wq"][l]).reshape(P, W, c.heads, c.head_dim)
            k = (h @ params["wk"][l]).reshape(P, W, c.heads, c.head_dim)
            v = (h @ params["wv"][l]).reshape(P, W, c.heads, c.head_dim)
            # positions past `lengths` hold pad-token KV, positions past
            # the window hold the previous tenant's bytes; both are
            # unreachable through the decode mask
            k_cache = k_cache.at[slot_rows, l, :W].set(k)
            v_cache = v_cache.at[slot_rows, l, :W].set(v)
            scores = jnp.einsum("pqhd,pkhd->phqk", q, k) * scale
            scores = jnp.where(mask, scores, -1e30)
            att = jnp.einsum("phqk,pkhd->pqhd",
                             jax.nn.softmax(scores, axis=-1), v)
            x = x + att.reshape(P, W, c.embed) @ params["wo"][l]
            h2 = _rmsnorm(x, params["ln2"][l])
            x = x + jax.nn.gelu(h2 @ params["w1"][l]) @ params["w2"][l]
        xf = _rmsnorm(x, params["lnf"])
        last = xf[jnp.arange(P), jnp.maximum(lengths - 1, 0)]   # (P, E)
        logits = last @ params["emb"].T
        first_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return k_cache, v_cache, first_tok

    return prefill


def _make_decode(config, steps=1, eos_id=None):
    """Build the decode step: EVERY pool slot advances up to `steps`
    tokens inside ONE compiled program (`lax.scan` over the micro-step).
    Fixed (S,) shapes and a FIXED step count, so join/leave — and lanes
    finishing mid-scan — never change the program; inactive lanes
    (steps_left == 0) write to the garbage row and their outputs are
    ignored. `steps > 1` amortizes the per-dispatch host cost over K
    tokens (the engine's `decode_steps` knob): admission/retirement move
    to wave granularity, TTFT stays prefill-bound.

    Signature: `decode(params, k_cache, v_cache, tokens, lengths,
    steps_left) -> (k_cache, v_cache, out_tokens (steps, S), emitted)`.
    `emitted[s]` is the EXACT number of tokens lane s produced this wave
    (rows [0:emitted] of its column) — counted in-scan, because deriving
    it from the steps_left delta would overcount when `eos_id` zeroes a
    lane's remaining budget mid-wave."""
    import jax
    import jax.numpy as jnp
    c = config
    scale = 1.0 / _np.sqrt(c.head_dim)

    def micro(params, k_cache, v_cache, tokens, lengths, active):
        # one token for every active lane. tokens (S,) int32 last emitted
        # token; lengths (S,) int32 current cache length (the new token's
        # KV lands at position `lengths`); active (S,) bool
        S = tokens.shape[0]
        T = c.max_len
        rows = jnp.where(active, jnp.arange(S), S)       # garbage row = S
        wpos = jnp.clip(lengths, 0, T - 1)
        x = params["emb"][tokens] + params["pos"][wpos]  # (S, E)
        # attention reads positions 0..lengths INCLUSIVE (the new token's
        # KV is written before the read); anything past that — pad-token
        # KV from prefill or a previous tenant's garbage — is masked
        tmask = jnp.arange(T)[None, :] <= lengths[:, None]   # (S, T)
        for l in range(c.layers):
            h = _rmsnorm(x, params["ln1"][l])
            q = (h @ params["wq"][l]).reshape(S, c.heads, c.head_dim)
            k = (h @ params["wk"][l]).reshape(S, c.heads, c.head_dim)
            v = (h @ params["wv"][l]).reshape(S, c.heads, c.head_dim)
            k_cache = k_cache.at[rows, l, wpos].set(k)
            v_cache = v_cache.at[rows, l, wpos].set(v)
            K = k_cache[:S, l]                           # (S, T, H, D)
            V = v_cache[:S, l]
            scores = jnp.einsum("shd,sthd->sht", q, K) * scale
            scores = jnp.where(tmask[:, None, :], scores, -1e30)
            att = jnp.einsum("sht,sthd->shd",
                             jax.nn.softmax(scores, axis=-1), V)
            x = x + att.reshape(S, c.embed) @ params["wo"][l]
            h2 = _rmsnorm(x, params["ln2"][l])
            x = x + jax.nn.gelu(h2 @ params["w1"][l]) @ params["w2"][l]
        logits = _rmsnorm(x, params["lnf"]) @ params["emb"].T
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return k_cache, v_cache, jnp.where(active, nxt, 0)

    def decode(params, k_cache, v_cache, tokens, lengths, steps_left):
        def step(carry, _):
            k_cache, v_cache, last, lens, left, emitted = carry
            act = left > 0
            k_cache, v_cache, nxt = micro(params, k_cache, v_cache,
                                          last, lens, act)
            new_left = jnp.where(act, left - 1, left)
            if eos_id is not None:
                new_left = jnp.where(act & (nxt == eos_id), 0, new_left)
            lens = jnp.where(act, lens + 1, lens)
            last = jnp.where(act, nxt, last)
            emitted = emitted + act.astype(jnp.int32)
            return (k_cache, v_cache, last, lens, new_left, emitted), nxt

        zero = jnp.zeros_like(steps_left)
        (k_cache, v_cache, _, _, _, emitted), toks = jax.lax.scan(
            step, (k_cache, v_cache, tokens, lengths, steps_left, zero),
            None, length=steps)
        return k_cache, v_cache, toks, emitted

    return decode


class CachedDecoder:
    """The model side of the continuous engine: two jitted programs over
    a KV slot pool. Programs are shape-generic in the POOL (the garbage
    row is `k_cache.shape[0] - 1` at trace time), so one CachedDecoder
    serves pools of any slot count — each pool size compiles once.

    `params=` shares weights across instances (e.g. a reference decoder
    for tests); `seed=` controls the deterministic random init.
    """

    def __init__(self, config, params=None, seed=0):
        import jax
        from ..deploy import maybe_enable_compile_cache
        # arm the persistent compilation cache BEFORE the first compile:
        # warm replicas deserialize instead of recompiling
        maybe_enable_compile_cache()
        self.config = config
        self.params = params if params is not None \
            else init_decoder_params(config, seed)
        # census attribution (mx.inspect.memory): the decoder weights are
        # serving's second-biggest resident set after the KV slabs
        try:
            from ..inspect import memory as _mem
            _mem.register(self.params, owner="decoder_params")
        except Exception:
            pass
        # programs keyed by their trace-time constants (prefill window /
        # decode scan length + eos), each its own jit: built once per
        # engine at construction — steady state replays, never re-builds
        self._prefills = {}
        self._decodes = {}
        self._prefill = self.prefill_program(config.max_len)
        self._decode = self.decode_program(1, None)

    def new_pool(self, max_slots=None, dtype=None):
        c = self.config
        return KVCachePool(max_slots, layers=c.layers, max_len=c.max_len,
                           heads=c.heads, head_dim=c.head_dim,
                           dtype=dtype or c.dtype)

    def prefill_program(self, window):
        """The jitted prefill program for a prompt-page width."""
        import jax
        key = int(window)
        fn = self._prefills.get(key)
        if fn is None:
            fn = jax.jit(_make_prefill(self.config, window=key),
                         donate_argnums=(1, 2))
            self._prefills[key] = fn
        return fn

    def decode_program(self, steps, eos_id=None):
        """The jitted decode program for a (steps, eos) variant (built
        and memoized on first request; the engine asks once at init)."""
        import jax
        key = (int(steps), eos_id)
        fn = self._decodes.get(key)
        if fn is None:
            fn = jax.jit(_make_decode(self.config, steps=key[0],
                                      eos_id=eos_id),
                         donate_argnums=(1, 2))
            self._decodes[key] = fn
        return fn

    def prefill(self, k_cache, v_cache, tokens, lengths, slot_rows):
        # window inferred from the token page width (a compiled program
        # exists per width; the engine always sends its own window)
        return self.prefill_program(tokens.shape[1])(
            self.params, k_cache, v_cache, tokens, lengths, slot_rows)

    def decode(self, k_cache, v_cache, tokens, lengths, steps_left,
               steps=1, eos_id=None):
        return self.decode_program(steps, eos_id)(
            self.params, k_cache, v_cache, tokens, lengths, steps_left)

    def compile_cache_size(self):
        """Total compiled programs across every jit (-1 unknown) — the
        zero-retrace observable (≙ ExportedModel.compile_cache_size)."""
        fns = list(self._prefills.values()) + list(self._decodes.values())
        sizes = [int(getattr(f, "_cache_size", lambda: -1)())
                 for f in fns]
        if any(s < 0 for s in sizes):
            return -1
        return sum(sizes)

    def reference_generate(self, prompt, max_new_tokens, eos_id=None,
                           window=None):
        """Greedy generation through a PRIVATE 1-slot pool — the
        scheduling-free reference the engine's mixed-batch outputs must
        match token-for-token (tests). Uses the same compiled math; pass
        the engine's `prefill_window` so the prefill page width (and so
        the float-op layout) matches bit-for-bit."""
        import jax.numpy as jnp
        pool = self.new_pool(max_slots=1)
        W = int(window if window is not None else self.config.max_len)
        plen = len(prompt)
        if plen < 1 or plen > W or plen >= self.config.max_len:
            raise ServeError(
                f"prompt length {plen} outside [1, min(window={W}, "
                f"max_len-1={self.config.max_len - 1})]")
        toks = _np.zeros((1, W), dtype=_np.int32)
        toks[0, :plen] = prompt
        k, v, first = self.prefill(
            pool.k, pool.v, jnp.asarray(toks),
            jnp.asarray([plen], dtype=jnp.int32),
            jnp.asarray([0], dtype=jnp.int32))
        pool.swap_buffers(k, v)
        out = [int(first[0])]
        cache_len = plen
        while (len(out) < max_new_tokens
               and (eos_id is None or out[-1] != eos_id)
               and cache_len + 1 < self.config.max_len):
            k, v, toks, _ = self.decode(
                pool.k, pool.v,
                jnp.asarray([out[-1]], dtype=jnp.int32),
                jnp.asarray([cache_len], dtype=jnp.int32),
                jnp.asarray([1], dtype=jnp.int32))
            pool.swap_buffers(k, v)
            out.append(int(toks[0, 0]))
            cache_len += 1
        return _np.asarray(out, dtype=_np.int32)


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------
class _GenRequest:
    __slots__ = ("prompt", "max_new", "future", "deadline", "t_submit",
                 "ctx", "slot", "generated", "cache_len", "t_first",
                 "t_last")

    def __init__(self, prompt, max_new, deadline, ctx):
        self.prompt = prompt                 # np.int32 (plen,)
        self.max_new = max_new
        self.future = Future()
        self.deadline = deadline             # perf_counter deadline or None
        self.t_submit = time.perf_counter()
        self.ctx = ctx                       # serve.request root context
        self.slot = None
        self.generated = []
        self.cache_len = 0
        self.t_first = None                  # first token (TTFT anchor)
        self.t_last = None

    def sort_key(self):
        """Earliest-deadline-first; deadline-less requests rank after
        every deadline-holder, FIFO among themselves."""
        return (self.deadline is None,
                self.deadline if self.deadline is not None
                else self.t_submit,
                self.t_submit)


class ContinuousEngine:
    """Iteration-level batching decode engine over a `CachedDecoder`.

    ::

        model = serve.CachedDecoder(serve.DecoderConfig(max_len=64))
        with serve.ContinuousEngine(model, max_slots=8) as eng:
            fut = eng.submit([3, 14, 15], max_new_tokens=16)
            tokens = fut.result()            # np.int32 generated ids

    Knobs (constructor arg > MXNET_SERVE_* env > default):

      max_slots        KV slots = max concurrently-decoding requests
      prefill_budget   max prompt TOKENS prefilled per engine iteration
                       (bounds how long admission may stall in-flight
                       decode; >= 1 request always admitted when a slot
                       is free)
      prefill_lanes    FIXED lane count of the prefill program (default
                       min(max_slots, 8)): its cost is paid in full per
                       admission wave regardless of how many lanes carry
                       real requests, so it is sized for the admission
                       RATE, not the pool — a max_slots-wide prefill
                       would bill a 1-request wave the whole pool's
                       prefill FLOPs
      max_queue        waiting-request bound (admission control; reuses
                       MXNET_SERVE_MAX_QUEUE; reject-newest)
      default_deadline_ms  queue deadline (MXNET_SERVE_DEADLINE_MS);
                       expiry while WAITING fails fast with
                       RequestTimeout — admitted requests always finish

    Exactly one scheduler thread runs the compiled steps, so the donated
    KV buffers have a single writer; submit() is safe from any thread.
    """

    def __init__(self, model, *, max_slots=None, prefill_budget=None,
                 prefill_lanes=None, prefill_window=None, decode_steps=4,
                 max_queue=None, default_deadline_ms=None, eos_id=None,
                 name="serve.continuous"):
        self.model = model
        self.name = name
        self.eos_id = eos_id
        self.pool = model.new_pool(max_slots)
        self.max_slots = self.pool.max_slots
        # micro-iterations per compiled decode dispatch: >1 amortizes the
        # host round-trip over K tokens; admission/retirement happen at
        # wave granularity (a lane finishing mid-wave holds its slot
        # until the wave ends, never computes past its budget)
        self.decode_steps = max(1, int(decode_steps))
        self._decode_prog = model.decode_program(self.decode_steps,
                                                 eos_id)
        # prompt page width: prompts are bounded by it, and the prefill
        # program pays O(window^2) attention instead of O(max_len^2) —
        # size it to the served prompt distribution, not the page
        self.prefill_window = int(
            prefill_window if prefill_window is not None
            else model.config.max_len)
        if not 1 <= self.prefill_window <= model.config.max_len:
            raise ServeError(
                f"prefill_window must be in [1, max_len], got "
                f"{self.prefill_window}")
        self._prefill_prog = model.prefill_program(self.prefill_window)
        self.prefill_budget = int(
            prefill_budget if prefill_budget is not None
            else get_env("MXNET_SERVE_PREFILL_BUDGET", 256, typ=int))
        if self.prefill_budget < 1:
            raise ServeError("prefill_budget must be >= 1")
        self.prefill_lanes = int(prefill_lanes if prefill_lanes is not None
                                 else min(self.max_slots, 8))
        if not 1 <= self.prefill_lanes <= self.max_slots:
            raise ServeError(
                f"prefill_lanes must be in [1, max_slots], got "
                f"{self.prefill_lanes}")
        self.max_queue = int(
            max_queue if max_queue is not None
            else get_env("MXNET_SERVE_MAX_QUEUE", 256, typ=int))
        dl = (default_deadline_ms if default_deadline_ms is not None
              else get_env("MXNET_SERVE_DEADLINE_MS", typ=float))
        self.default_deadline_s = None if dl is None else float(dl) / 1e3
        self.max_len = model.config.max_len

        self._cv = threading.Condition()
        self._waiting = deque()              # submitted, no slot yet
        self._running = {}                   # slot -> _GenRequest
        self._closing = False
        self._drain = True
        self._started = False
        self._warm_cache_size = None
        self.warmup_s = None
        self._thread = threading.Thread(
            target=self._loop, name=f"{name}-scheduler", daemon=True)

        # per-engine metrics (all mutation under _mlock)
        self._mlock = threading.Lock()
        self._t0 = time.perf_counter()
        self._counters = {k: 0 for k in (
            "requests", "replies", "rejected", "timeouts", "errors",
            "admitted", "retired", "decode_iterations", "decode_tokens",
            "prefill_tokens", "prefill_batches", "programs_compiled",
            "active_sum")}
        self._ttft_ms = deque(maxlen=4096)
        self._tpot_ms = deque(maxlen=4096)
        self._e2e_ms = deque(maxlen=4096)

    # -- lifecycle ---------------------------------------------------------
    def start(self, warmup=True):
        """Compile (or persistent-cache-load) both step programs before
        traffic, then start the scheduler thread. Returns self; records
        the warm compile-cache size for the zero-retrace assertion."""
        if self._started:
            return self
        t0 = time.perf_counter()
        if warmup:
            self._warmup()
        with self._cv:
            self._warm_cache_size = self.model.compile_cache_size()
            self._started = True
        self.warmup_s = round(time.perf_counter() - t0, 3)
        _trace.install_crash_hooks()
        mem_install_oom_hook()
        self._thread.start()
        return self

    def _warmup(self):
        """One garbage-lane prefill + one all-inactive decode: compiles
        (or loads from MXNET_COMPILE_CACHE_DIR) both programs without
        touching any real slot."""
        import jax.numpy as jnp
        g = self.pool.garbage_row
        P = self.prefill_lanes
        k, v, _ = self._prefill_prog(
            self.model.params, self.pool.k, self.pool.v,
            jnp.zeros((P, self.prefill_window), dtype=jnp.int32),
            jnp.ones((P,), dtype=jnp.int32),
            jnp.full((P,), g, dtype=jnp.int32))
        self.pool.swap_buffers(k, v)
        k, v, _, _ = self._decode_prog(
            self.model.params, self.pool.k, self.pool.v,
            jnp.zeros((self.max_slots,), dtype=jnp.int32),
            jnp.zeros((self.max_slots,), dtype=jnp.int32),
            jnp.zeros((self.max_slots,), dtype=jnp.int32))
        self.pool.swap_buffers(k, v)
        # wait for the compiles to actually finish so warmup_s is honest
        k.block_until_ready()
        self._count("programs_compiled", 2)

    def __enter__(self):
        return self.start()

    def close(self, drain=True, timeout=60.0):
        """Stop the scheduler. `drain=True` finishes admitted AND waiting
        requests first; `drain=False` fails the waiting queue (admitted
        requests still finish — their slots hold real state)."""
        with self._cv:
            if not self._closing:
                self._closing = True
                self._drain = drain
                pending = [] if drain else list(self._waiting)
                if not drain:
                    self._waiting.clear()
            else:
                pending = []
            self._cv.notify_all()
        for req in pending:
            _fail(req, ServerClosed("engine closed before admission"))
        if self._started:
            self._thread.join(timeout=timeout)

    def __exit__(self, *exc):
        self.close()

    def begin_drain(self):
        """Stop admitting (submit() raises `ReplicaDraining`) while the
        scheduler finishes every waiting AND admitted request. Non-blocking
        by design — the drain-and-swap replica keeps answering heartbeats
        while its KV-resident requests finish; `close()` joins after."""
        with self._cv:
            if not self._closing:
                self._closing = True
                self._drain = True
            self._cv.notify_all()

    @property
    def draining(self):
        """True while a drain is in progress (resident requests still
        finishing); False once the scheduler has exited."""
        return self._closing and self._drain and self._thread.is_alive()

    def queue_depth(self):
        """(waiting, running) request counts — the fleet router's
        least-loaded placement signal."""
        with self._cv:
            return len(self._waiting), len(self._running)

    # -- submission --------------------------------------------------------
    def submit(self, prompt_tokens, max_new_tokens=16, deadline_ms=None):
        """Enqueue one generation request; returns a Future resolving to
        the np.int32 array of generated token ids (greedy; cut at
        `eos_id`, `max_new_tokens`, or a full KV page)."""
        if not self._started:
            raise ServeError(
                "ContinuousEngine.start() (or `with engine:`) first")
        prompt = _np.asarray(prompt_tokens, dtype=_np.int32).ravel()
        if prompt.size < 1:
            raise ServeError("prompt must have at least one token")
        if prompt.size >= self.max_len:
            raise ServeError(
                f"prompt length {prompt.size} >= max_len {self.max_len} "
                f"(one slot page holds prompt + generated tokens)")
        if prompt.size > self.prefill_window:
            raise ServeError(
                f"prompt length {prompt.size} > prefill_window "
                f"{self.prefill_window} (raise the engine's "
                f"prefill_window for longer prompts)")
        if max_new_tokens < 1:
            raise ServeError("max_new_tokens must be >= 1")
        _fault.inject("serve.enqueue")
        dl = (deadline_ms / 1e3 if deadline_ms is not None
              else self.default_deadline_s)
        ctx = _trace.request_root("serve.request")
        req = _GenRequest(prompt, int(max_new_tokens),
                          None if dl is None
                          else time.perf_counter() + dl, ctx)
        with self._cv:
            if self._closing:
                # typed split, not one generic ServerClosed: DRAINING means
                # "resident requests still finishing before a restart" and
                # the fleet router re-routes it silently; CLOSED is final
                if self._drain and self._thread.is_alive():
                    raise ReplicaDraining(
                        "engine is draining (finishing resident requests "
                        "before restart); route to another replica")
                raise ServerClosed("engine is closed")
            if len(self._waiting) >= self.max_queue:
                depth = len(self._waiting)
                rejected = True
            else:
                rejected = False
                self._waiting.append(req)
                self._cv.notify()
        if rejected:
            self._count("rejected")
            _trace.flightrec_record(
                "serve.reject", self.name, depth=depth,
                trace_id=ctx.trace_id if ctx else None)
            _trace.flightrec_maybe_dump("serve.overload")
            raise QueueFullError(
                f"waiting queue full ({self.max_queue}); request "
                f"rejected", policy="reject")
        self._count("requests")
        return req.future

    def generate(self, prompt_tokens, max_new_tokens=16, timeout=None,
                 deadline_ms=None):
        """submit() + wait."""
        return self.submit(prompt_tokens, max_new_tokens,
                           deadline_ms=deadline_ms).result(timeout=timeout)

    # -- metrics -----------------------------------------------------------
    def _count(self, key, n=1):
        with self._mlock:
            self._counters[key] += n
        stats_key = _ENGINE_TO_SERVE_KEY.get(key)
        if stats_key is not None:
            with _STATS_LOCK:
                SERVE_STATS[stats_key] += n

    def compile_cache_size(self):
        return self.model.compile_cache_size()

    def retraces_after_warmup(self):
        """Compiled-program growth since start() — MUST be 0 in steady
        state (-1 when the jax version hides the counter)."""
        if self._warm_cache_size is None or self._warm_cache_size < 0:
            return -1
        now = self.model.compile_cache_size()
        return -1 if now < 0 else now - self._warm_cache_size

    def assert_no_retraces(self):
        r = self.retraces_after_warmup()
        if r > 0:
            raise MXNetError(
                f"continuous engine retraced {r} program(s) after warmup "
                f"— a shape leaked into the compiled step")
        return r

    def memory_plans(self):
        """Predicted device-memory plans of the TWO compiled step
        programs (`mx.inspect.memory.memory_plan` over the prefill and
        decode jits, lowered at the exact warmup shapes via abstract
        avals — no buffers touched, no extra compile in steady state:
        the lowering hits the same jit cache entry the engine replays).
        The KV slab dominates both plans' argument size and is donated,
        so `alias_size` covering ~2x the slab is the zero-copy-update
        evidence."""
        import jax
        import jax.tree_util as jtu
        from ..inspect.memory import memory_plan

        def aval(shape, dtype="int32"):
            return jax.ShapeDtypeStruct(shape, dtype)

        params_avals = jtu.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            self.model.params)
        pool_aval = jax.ShapeDtypeStruct(self.pool.shape, self.pool.dtype)
        P, S, W = self.prefill_lanes, self.max_slots, self.prefill_window
        prefill = self._prefill_prog.lower(
            params_avals, pool_aval, pool_aval, aval((P, W)), aval((P,)),
            aval((P,)))
        decode = self._decode_prog.lower(
            params_avals, pool_aval, pool_aval, aval((S,)), aval((S,)),
            aval((S,)))
        return {
            "prefill": memory_plan(prefill, name=f"{self.name}.prefill"),
            "decode": memory_plan(decode, name=f"{self.name}.decode"),
        }

    def stats(self):
        """Plain-data snapshot: counters, slot occupancy, TTFT/TPOT
        percentiles, decode tokens/s, and the zero-retrace observables."""
        with self._mlock:
            c = dict(self._counters)
            ttft = sorted(self._ttft_ms)
            tpot = sorted(self._tpot_ms)
            e2e = sorted(self._e2e_ms)
            elapsed = time.perf_counter() - self._t0
        out = dict(c)
        out["elapsed_s"] = round(elapsed, 3)
        out["decode_tokens_per_sec"] = round(
            c["decode_tokens"] / elapsed, 2) if elapsed > 0 else 0.0
        out["mean_active_slots"] = round(
            c["active_sum"] / c["decode_iterations"], 3) \
            if c["decode_iterations"] else 0.0
        for nm, vals in (("ttft", ttft), ("tpot", tpot), ("e2e", e2e)):
            for q in (50, 99):
                v = percentile(vals, q)
                out[f"{nm}_p{q}_ms"] = round(v, 3) if v is not None \
                    else None
        out["pool"] = self.pool.stats()
        out["decode_steps"] = self.decode_steps
        out["prefill_lanes"] = self.prefill_lanes
        out["prefill_window"] = self.prefill_window
        out["compile_cache_size"] = self.compile_cache_size()
        out["retraces_after_warmup"] = self.retraces_after_warmup()
        return out

    # -- scheduler ---------------------------------------------------------
    def _loop(self):
        import jax.numpy as jnp
        while True:
            with self._cv:
                while (not self._waiting and not self._running
                       and not self._closing):
                    self._cv.wait()
                if self._closing and not self._running \
                        and (not self._drain or not self._waiting):
                    for req in self._waiting:
                        _fail(req, ServerClosed(
                            "engine closed before admission"))
                    self._waiting.clear()
                    return
                admitted, expired = self._admit_locked()
            # expired waiters resolve OUTSIDE self._cv: Future callbacks
            # run inline and may re-enter submit()
            now = time.perf_counter()
            for req in expired:
                self._count("timeouts")
                _trace.flightrec_record(
                    "serve.timeout", self.name,
                    waited_ms=round((now - req.t_submit) * 1e3, 1),
                    trace_id=req.ctx.trace_id if req.ctx else None)
                _fail(req, RequestTimeout(
                    f"deadline expired after "
                    f"{(now - req.t_submit) * 1e3:.1f}ms waiting for a "
                    f"KV slot"))
            if not admitted and not expired and not self._running:
                # waiting requests exist but no slot freed up (something
                # outside the engine holds claims): timed wait, re-check —
                # never a busy spin
                with self._cv:
                    if self._waiting and not self._running:
                        self._cv.wait(timeout=0.005)
                continue
            try:
                if admitted:
                    self._run_prefill(admitted, jnp)
                if self._running:
                    self._run_decode(jnp)
            except BaseException as e:
                # a step failure fails the IN-FLIGHT requests, frees
                # their slots, and the engine keeps serving (the PR-3
                # batch-error contract). A RESOURCE_EXHAUSTED step
                # additionally leaves the OOM black box (census + plans
                # + flightrec ring) BEFORE the slab reallocation below
                # rewrites the memory picture.
                mem_on_oom(e, where="serve.continuous")
                err = e if isinstance(e, MXNetError) else ServeError(
                    f"engine step failed: {type(e).__name__}: {e}")
                with self._cv:
                    doomed = list(self._running.values())
                    self._running.clear()
                for req in doomed:
                    if req.slot is not None:
                        self.pool.free(req.slot)
                    _fail(req, err)
                self._count("errors", len(doomed))
                # the step programs DONATE the KV buffers: an exception
                # raised mid-execution (after donation) leaves pool.k/v
                # invalidated — fresh buffers or every later wave dies
                # on 'Array has been deleted'. Every in-flight request
                # was just failed, so zeroed slabs are the correct state.
                self.pool.reallocate()

    def _admit_locked(self):
        """Deadline-aware admission (runs under self._cv): drop expired
        waiters from the queue, then grant free slots
        earliest-deadline-first within the prefill token budget. Returns
        (admitted, expired); the caller resolves expired futures off-lock."""
        now = time.perf_counter()
        expired = [r for r in self._waiting
                   if r.deadline is not None and now > r.deadline]
        if expired:
            dropset = set(id(r) for r in expired)
            self._waiting = deque(r for r in self._waiting  # mxlint: disable=lock-shared-mutation -- _admit_locked runs with self._cv held by its only caller (_loop)
                                  if id(r) not in dropset)
        admitted = []
        budget = self.prefill_budget
        free = self.pool.free_count()
        if free and self._waiting:
            ranked = sorted(self._waiting, key=_GenRequest.sort_key)
            for req in ranked:
                if not free or len(admitted) >= self.prefill_lanes:
                    break
                cost = int(req.prompt.size)
                if admitted and budget - cost < 0:
                    break               # budget spent; next iteration
                try:
                    req.slot = self.pool.claim()
                except SlotsFullError:   # raced a test's direct claim
                    break
                free -= 1
                budget -= cost
                admitted.append(req)
            if admitted:
                dropset = set(id(r) for r in admitted)
                self._waiting = deque(r for r in self._waiting  # mxlint: disable=lock-shared-mutation -- _admit_locked runs with self._cv held by its only caller (_loop)
                                      if id(r) not in dropset)
        for req in admitted:
            self._running[req.slot] = req  # mxlint: disable=lock-shared-mutation -- _admit_locked runs with self._cv held by its only caller (_loop)
        return admitted, expired

    def _run_prefill(self, admitted, jnp):
        """One fixed-shape prefill wave for the just-admitted requests."""
        _fault.inject("serve.execute")
        P = self.prefill_lanes
        g = self.pool.garbage_row
        toks = _np.zeros((P, self.prefill_window), dtype=_np.int32)
        lens = _np.ones((P,), dtype=_np.int32)
        rows = _np.full((P,), g, dtype=_np.int32)
        for i, req in enumerate(admitted):
            toks[i, :req.prompt.size] = req.prompt
            lens[i] = req.prompt.size
            rows[i] = req.slot
        t0 = time.perf_counter()
        k, v, first = self._prefill_prog(
            self.model.params, self.pool.k, self.pool.v,
            jnp.asarray(toks), jnp.asarray(lens), jnp.asarray(rows))
        self.pool.swap_buffers(k, v)
        first_host = _np.asarray(first)
        now = time.perf_counter()
        n_tokens = int(sum(r.prompt.size for r in admitted))
        self._count("admitted", len(admitted))
        self._count("prefill_batches")
        self._count("prefill_tokens", n_tokens)
        prof = _profiler_on()
        done = []
        for i, req in enumerate(admitted):
            req.cache_len = int(req.prompt.size)
            req.generated.append(int(first_host[i]))
            req.t_first = req.t_last = now
            with self._mlock:
                self._ttft_ms.append((now - req.t_submit) * 1e3)
            if req.ctx is not None and prof:
                # admission -> first token, child of the request root:
                # iteration 0 of the request's one trace
                record_span("serve.prefill", (now - req.t_submit) * 1e6,
                            ts_us=req.t_submit * 1e6, cat="serve",
                            ctx=_trace.child_context(req.ctx,
                                                     "serve.prefill"),
                            prompt_tokens=req.prompt.size,
                            slot=req.slot)
            if self._finished(req):
                done.append(req)
        if _trace.enabled() and _trace.collector_active():
            record_span("serve.prefill_batch", (now - t0) * 1e6,
                        ts_us=t0 * 1e6, cat="serve",
                        requests=len(admitted), tokens=n_tokens)
        self._retire(done)

    def _run_decode(self, jnp):
        """ONE decode wave: every active slot advances up to
        `decode_steps` tokens through the compiled multi-step program."""
        S = self.max_slots
        toks = _np.zeros((S,), dtype=_np.int32)
        lens = _np.zeros((S,), dtype=_np.int32)
        left = _np.zeros((S,), dtype=_np.int32)
        with self._cv:
            running = dict(self._running)
        for slot, req in running.items():
            toks[slot] = req.generated[-1]
            lens[slot] = req.cache_len
            # this wave's per-lane budget: what the request still wants,
            # capped by its page space. The cap mirrors _finished's
            # `cache_len + 1 >= max_len` stop: the K=1 engine (and the
            # reference) emit their last token FROM state max_len - 2,
            # so a multi-step wave may advance cache_len at most to
            # max_len - 1 — not max_len, which would emit one extra
            # token and break the K-invariance contract
            left[slot] = min(req.max_new - len(req.generated),
                             self.max_len - 1 - req.cache_len)
        t0 = time.perf_counter()
        k, v, out_toks, emitted = self._decode_prog(
            self.model.params, self.pool.k, self.pool.v,
            jnp.asarray(toks), jnp.asarray(lens), jnp.asarray(left))
        self.pool.swap_buffers(k, v)
        out_host = _np.asarray(out_toks)            # (decode_steps, S)
        emitted_host = _np.asarray(emitted)
        now = time.perf_counter()
        n_active = len(running)
        n_tokens = 0
        done = []
        for slot, req in running.items():
            n_new = int(emitted_host[slot])
            if n_new > 0:
                req.generated.extend(
                    int(t) for t in out_host[:n_new, slot])
                req.cache_len += n_new
                req.t_last = now
                n_tokens += n_new
            if self._finished(req):
                done.append(req)
        self._count("decode_iterations")
        self._count("decode_tokens", n_tokens)
        self._count("active_sum", n_active)
        if _trace.enabled() and _trace.collector_active():
            record_span("serve.decode_batch", (now - t0) * 1e6,
                        ts_us=t0 * 1e6, cat="serve", active=n_active,
                        tokens=n_tokens, steps=self.decode_steps)
        self._retire(done)

    def _finished(self, req):
        if len(req.generated) >= req.max_new:
            return True
        if self.eos_id is not None and req.generated[-1] == self.eos_id:
            return True
        # page full: the NEXT decode would write past the slot
        return req.cache_len + 1 >= self.max_len

    def _retire(self, done):
        """Free slots and resolve futures; one request's whole life —
        prefill + N decode iterations — closes as ONE trace here."""
        if not done:
            return
        prof = _profiler_on()
        for req in done:
            with self._cv:
                self._running.pop(req.slot, None)
            self.pool.free(req.slot)
            out = _np.asarray(req.generated, dtype=_np.int32)
            if self.eos_id is not None:
                hits = _np.nonzero(out == self.eos_id)[0]
                if hits.size:
                    out = out[:int(hits[0]) + 1]
            if req.future.set_running_or_notify_cancel():
                req.future.set_result(out)
            now = time.perf_counter()
            total_ms = (now - req.t_submit) * 1e3
            with self._mlock:
                self._e2e_ms.append(total_ms)
                if len(req.generated) > 1 and req.t_first is not None:
                    self._tpot_ms.append(
                        (req.t_last - req.t_first) * 1e3
                        / (len(req.generated) - 1))
            self._count("replies")
            self._count("retired")
            if req.ctx is not None and prof:
                if req.t_first is not None and req.t_last > req.t_first:
                    # first -> last token: the N decode iterations as one
                    # node (per-iteration spans at thousands of tokens/s
                    # would swamp the trace; the batch lane has them)
                    record_span("serve.decode",
                                (req.t_last - req.t_first) * 1e6,
                                ts_us=req.t_first * 1e6, cat="serve",
                                ctx=_trace.child_context(req.ctx,
                                                         "serve.decode"),
                                tokens=len(req.generated), slot=req.slot)
                record_span("serve.request", total_ms * 1e3,
                            ts_us=req.t_submit * 1e6, cat="serve",
                            ctx=req.ctx, tokens=len(req.generated))


# engine counter -> process-wide SERVE_STATS key (profiler.serve_stats()):
# the decode_* family is the continuous-batching analog of the PR-3 rows
_ENGINE_TO_SERVE_KEY = {
    "requests": "requests", "replies": "replies",
    "rejected": "rejected", "timeouts": "timeouts", "errors": "errors",
    "programs_compiled": "programs_compiled",
    "decode_iterations": "decode_iterations",
    "decode_tokens": "decode_tokens",
    "prefill_tokens": "decode_prefill_tokens",
    "admitted": "decode_admitted",
    "retired": "decode_retired",
}
