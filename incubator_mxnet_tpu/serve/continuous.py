"""Continuous (iteration-level) batching for autoregressive models.

`serve.Server` (PR 3) batches STATELESS single-shot requests: a request
joins exactly one batch, the batch runs one program, done. Autoregressive
models break that shape — one request is N sequential decode iterations
over private KV state, requests finish at different times, and a static
batch must run every member to the LONGEST member's length while admitted
work waits whole batches. This module is the Orca-style answer, rebuilt
JAX-native per the PAPER.md survey (one compiled decode program, state as
plain device buffers, zero retraces):

  * **Slot memory** (`serve.kv_pool.KVCachePool`): a fixed-shape KV slab
    carved once; each admitted request claims a slot ROW; join/leave is
    host bookkeeping and can never change a compiled program's shapes.
  * **Two fixed-shape programs** compiled once at warmup and reused for
    every mixed batch: `prefill` (writes a claimed slot's prompt KV page +
    emits the first token, pad lanes scatter into the pool's garbage row)
    and `decode` (steps ALL slots one token — inactive slots are masked
    lanes, their writes land in the garbage row). Both donate the KV
    buffers, so updates are in-place `dynamic_update_slice` scatters on
    accelerators (the `.at[rows, layer, pos].set(...)` idiom).
  * **Iteration-level scheduling**: every engine iteration first retires
    finished requests (their slots free IMMEDIATELY, not at batch end),
    then admits waiting requests under a prefill token budget
    (`MXNET_SERVE_PREFILL_BUDGET` — bounds how much prefill work may
    delay in-flight decode iterations), then runs one decode step for
    every active slot. Admission is DEADLINE-AWARE, not FIFO: waiting
    requests are granted slots earliest-deadline-first (SLO-aware
    admission over the PR-3 deadline plumbing), and a request whose
    deadline expires while waiting fails fast with `RequestTimeout`.
  * **Zero retraces after warmup** is asserted the PR-3 way: the
    `programs_compiled` counter and `compile_cache_size()` must stay flat
    over any join/leave pattern (tests/test_continuous.py drives ragged
    mixed traffic and checks both).
  * **O(load) warmup**: `deploy.maybe_enable_compile_cache()` wires
    `MXNET_COMPILE_CACHE_DIR` onto jax's persistent compilation cache
    before the first compile, so a second replica (or a restart) loads
    the serialized executables instead of recompiling — measured by
    `benchmark/serve_bench.py --autoregressive` (compile-skip section).

Tracing: one request = ONE trace across its N iterations. The root
`serve.request` context is minted at `submit()` (PR-13 plumbing); the
engine records `serve.prefill` (admission -> first token) and
`serve.decode` (first -> last token, N iterations) as children, and
closes the root at retirement — while the profiler collects, the whole
request renders as a single tree in the Chrome trace.

The bundled `CachedDecoder` is a small pre-norm transformer decoder over
the slot pool — the LLM-shaped model side for tests and the bench; any
object with the same `prefill`/`decode`/`compile_cache_size` contract
serves.

Decode raw speed (the ROADMAP item-2 axes), all inside the SAME two
fixed-shape programs so the zero-retrace contract survives untouched:

  * **Sampling as data**: temperature/top-k/top-p and a per-lane PRNG
    key ride into the compiled programs as (S,)-shaped ARRAYS (the PR-9
    key-as-data idiom) — a mixed greedy/sampled batch is just different
    array values through one program. The per-token key is
    `fold_in(request_key, position)`, a pure function of the token's
    page position, so the key schedule is WAVE-INVARIANT: the engine
    (any decode_steps, any join/leave pattern) and the 1-slot
    `reference_generate` twin draw identical tokens.
  * **Speculative decoding** (`draft_tokens > 0`): each scan micro-step
    drafts k tokens by prompt-lookup (latest n-gram match over the
    lane's token page history, passed in as a fixed (S, max_len) array)
    and verifies them with ONE chunked forward over the k+1 positions —
    KV for the whole chunk scatters into the slot page, queries mask to
    `[0, cur_len + j]`. The longest draft prefix that EXACTLY matches
    the base model's own choice is emitted plus one bonus token, so
    output token streams are identical to non-speculative decoding for
    greedy AND sampled lanes (exact-match verification); acceptance
    counts are in-scan data, so acceptance variance never changes
    program shapes. Rejected-position KV is dead by construction: the
    next chunk overwrites positions `[cur_len, cur_len+k]` before any
    mask can reach them.
  * **Paged attention**: the decode-side attention read is
    `ops.fused.paged_attention` — a Pallas kernel over the slotted slab
    with block-sparse reads clamped to each lane's live prefix (TPU, or
    `MXNET_FUSION_INTERPRET=1` for CPU CI) and the identical
    masked-einsum jnp fallback elsewhere.
  * **int8 KV** (`kv_dtype="int8"`): the pool stores int8 codes + f32
    per-position scales; writes quantize by per-position absmax over
    (heads, head_dim), reads dequantize in the attention op. A
    position's scale is written exactly once with its KV, so the stale-
    scale story is the stale-KV story (same mask, same poison test).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as _np

from ..base import MXNetError, get_env
from .. import fault as _fault
from .. import sanitize as _sanitize
from ..telemetry import (record_span, trace as _trace, mem_on_oom,
                         mem_install_oom_hook)
from .batcher import (ServeError, QueueFullError, RequestTimeout,
                      ServerClosed, ReplicaDraining, _fail, _profiler_on)
from .metrics import SERVE_STATS, _STATS_LOCK, percentile
from .kv_pool import KVCachePool, SlotsFullError
from .prefix_cache import PrefixCache

__all__ = ["DecoderConfig", "CachedDecoder", "ContinuousEngine",
           "init_decoder_params"]


# ---------------------------------------------------------------------------
# model: a small cached-KV transformer decoder (greedy, deterministic)
# ---------------------------------------------------------------------------
class DecoderConfig:
    """Static shape/config record for `CachedDecoder` (all ints; nothing
    here ever becomes a tracer)."""

    def __init__(self, vocab=256, embed=64, layers=2, heads=4,
                 head_dim=16, mlp_hidden=None, max_len=128,
                 dtype="float32"):
        self.vocab = int(vocab)
        self.embed = int(embed)
        self.layers = int(layers)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.mlp_hidden = int(mlp_hidden if mlp_hidden is not None
                              else 4 * embed)
        self.max_len = int(max_len)
        self.dtype = str(dtype)
        if self.heads * self.head_dim != self.embed:
            raise ServeError(
                f"heads*head_dim ({self.heads}x{self.head_dim}) must "
                f"equal embed ({self.embed})")

    def as_dict(self):
        return {k: getattr(self, k) for k in
                ("vocab", "embed", "layers", "heads", "head_dim",
                 "mlp_hidden", "max_len", "dtype")}


def init_decoder_params(config, seed=0):
    """Deterministic random params (pytree of jnp arrays, layer-stacked
    on a leading L axis so the layer loop indexes one buffer)."""
    import jax
    import jax.numpy as jnp
    c = config
    keys = jax.random.split(jax.random.PRNGKey(seed), 8)
    s = 1.0 / _np.sqrt(c.embed)
    m = 1.0 / _np.sqrt(c.mlp_hidden)

    def rnd(key, shape, scale):
        return (jax.random.normal(key, shape) * scale).astype(c.dtype)

    return {
        "emb": rnd(keys[0], (c.vocab, c.embed), 1.0),
        "pos": rnd(keys[1], (c.max_len, c.embed), 0.1),
        "wq": rnd(keys[2], (c.layers, c.embed, c.embed), s),
        "wk": rnd(keys[3], (c.layers, c.embed, c.embed), s),
        "wv": rnd(keys[4], (c.layers, c.embed, c.embed), s),
        "wo": rnd(keys[5], (c.layers, c.embed, c.embed), s),
        "w1": rnd(keys[6], (c.layers, c.embed, c.mlp_hidden), s),
        "w2": rnd(keys[7], (c.layers, c.mlp_hidden, c.embed), m),
        "ln1": jnp.ones((c.layers, c.embed), dtype=c.dtype),
        "ln2": jnp.ones((c.layers, c.embed), dtype=c.dtype),
        "lnf": jnp.ones((c.embed,), dtype=c.dtype),
    }


def _rmsnorm(x, scale):
    import jax.numpy as jnp
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * scale / jnp.sqrt(var + 1e-6)


def _seed_key(seed):
    """Host-side PRNG key bytes for a request seed — the same uint32
    pair `jax.random.PRNGKey(seed)` holds, built without a device
    round-trip so submit() stays cheap."""
    seed = int(seed) & 0xFFFFFFFFFFFFFFFF
    return _np.array([(seed >> 32) & 0xFFFFFFFF, seed & 0xFFFFFFFF],
                     dtype=_np.uint32)


def _sample_tokens(logits, temps, top_ks, top_ps, keys, positions):
    """Per-lane next-token choice with sampling params AS DATA: every
    lane runs the same temperature/top-k/top-p/categorical math and a
    `temps > 0` select keeps greedy lanes exactly argmax — one compiled
    program serves any greedy/sampled mix. The draw key is
    `fold_in(lane_key, position)` (position = the query token's cache
    position), a pure function of request state, so any wave schedule
    draws the same tokens."""
    import jax
    import jax.numpy as jnp
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    srt = jnp.sort(scaled, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        srt, jnp.clip(top_ks - 1, 0, V - 1)[:, None], axis=-1)
    keep_k = (top_ks[:, None] <= 0) | (scaled >= kth)
    probs = jax.nn.softmax(srt, axis=-1)
    csum = jnp.cumsum(probs, axis=-1)
    # smallest prefix whose mass reaches top_p (the kept-set INCLUDES
    # the crossing token, hence the exclusive-cumsum comparison)
    keepn = jnp.sum((csum - probs) < top_ps[:, None], axis=-1)
    pth = jnp.take_along_axis(
        srt, jnp.clip(keepn - 1, 0, V - 1)[:, None], axis=-1)
    masked = jnp.where(keep_k & (scaled >= pth), scaled, -1e30)
    kfold = jax.vmap(jax.random.fold_in)(keys, positions)
    sampled = jax.vmap(
        lambda kk, lg: jax.random.categorical(kk, lg))(kfold, masked)
    return jnp.where(temps > 0, sampled.astype(jnp.int32), greedy)


_SAMPLE_JIT = None


def _sample_first(logits, temps, top_ks, top_ps, keys, positions):
    """First-token draw from prefill logits through ONE process-wide
    jitted sampler. The sampling math compiles once per (lanes, vocab)
    shape for every model and engine in the process, instead of being
    re-traced into each model's prefill program (the decode program
    keeps its own in-scan copy, where it must live). Identical math
    either way, so engine == reference still holds bit-for-bit."""
    global _SAMPLE_JIT
    if _SAMPLE_JIT is None:
        import jax
        _SAMPLE_JIT = jax.jit(_sample_tokens)
    return _SAMPLE_JIT(logits, temps, top_ks, top_ps, keys, positions)


def _kv_split(cache):
    """A pool buffer is either a raw slab or a (codes, scales) pair
    (int8 mode); normalize to (slab, scales_or_None)."""
    if isinstance(cache, tuple):
        return cache
    return cache, None


def _quantize_kv(val):
    """int8 KV codes + f32 scale per written (lane, position): absmax
    over (heads, head_dim). The scale is final at write time — a
    position is quantized exactly once, with its KV."""
    import jax.numpy as jnp
    a = jnp.max(jnp.abs(val), axis=(-2, -1))
    s = jnp.maximum(a.astype(jnp.float32), 1e-8) / 127.0
    q = jnp.clip(jnp.round(val / s[..., None, None]), -127, 127)
    return q.astype(jnp.int8), s


def _store_page(cache, rows, l, W, val):
    """Scatter a (P, W, H, D) KV page into [rows, l, :W] (quantizing
    into codes+scales when the pool is int8)."""
    slab, scales = _kv_split(cache)
    if scales is None:
        return slab.at[rows, l, :W].set(val)
    q, s = _quantize_kv(val)
    return (slab.at[rows, l, :W].set(q), scales.at[rows, l, :W].set(s))


def _store_pos(cache, rows, l, wpos, val):
    """Scatter KV at explicit positions (rows/wpos broadcast to the
    leading dims of `val`), quantizing when the pool is int8."""
    slab, scales = _kv_split(cache)
    if scales is None:
        return slab.at[rows, l, wpos].set(val)
    q, s = _quantize_kv(val)
    return (slab.at[rows, l, wpos].set(q),
            scales.at[rows, l, wpos].set(s))


def _paged_attn(k_cache, v_cache, q, lengths, l, extent=None):
    """Decode-side attention read over the slot slab via
    `ops.fused.paged_attention`: Pallas block-sparse kernel on TPU (or
    interpret-mode CI), identical masked-einsum jnp fallback elsewhere.
    q is (S, C, H, D); chunk offset j reads positions [0, lengths+j].

    `extent` statically slices the slab's position axis to [0, extent)
    before the read: when the caller can bound `lengths + j < extent`
    for every lane, the masked positions beyond it contribute exact
    zeros, so the output is bit-identical to the full-width read at a
    fraction of the cost (the chunk-prefill extent ladder)."""
    from ..ops import fused as _fused
    k_slab, k_scale = _kv_split(k_cache)
    v_slab, v_scale = _kv_split(v_cache)
    if extent is not None and extent < k_slab.shape[2]:
        k_slab = k_slab[:, :, :extent]
        v_slab = v_slab[:, :, :extent]
        if k_scale is not None:
            k_scale = k_scale[:, :, :extent]
        if v_scale is not None:
            v_scale = v_scale[:, :, :extent]
    return _fused.paged_attention(q, k_slab, v_slab, lengths, l,
                                  k_scale=k_scale, v_scale=v_scale)


def _make_prefill(config, window=None):
    """Build the prefill step: full causal forward over the padded prompt
    page, KV written into the claimed slot rows, first token emitted.

    Shapes are FIXED by (P lanes, window, pool rows): the compiled
    program is reused for every admission wave — a lane that has no
    request this wave carries slot_row = garbage and its writes vanish.

    `window` (default max_len) is the prompt page width: attention and
    the KV write cover positions [0, window), so a serving config with
    short prompts pays O(window^2), not O(max_len^2), per wave. Slot
    positions past the window keep the PREVIOUS tenant's bytes — that is
    safe by the decode mask (reads clamp to the current request's
    `[0, cur_len]`), and exactly what the poison-fill isolation test
    proves."""
    import jax
    import jax.numpy as jnp
    c = config
    W = int(window if window is not None else c.max_len)
    if not 1 <= W <= c.max_len:
        raise ServeError(f"prefill window {W} outside [1, {c.max_len}]")
    scale = 1.0 / _np.sqrt(c.head_dim)

    def prefill(params, k_cache, v_cache, tokens, lengths, slot_rows):
        # tokens (P, W) int32, lengths (P,) int32, slot_rows (P,) int32
        P = tokens.shape[0]
        x = params["emb"][tokens] + params["pos"][None, :W]
        pos = jnp.arange(W)
        key_valid = pos[None, :] < lengths[:, None]            # (P, W)
        causal = pos[:, None] >= pos[None, :]                  # (W, W)
        mask = causal[None, None] & key_valid[:, None, None]   # (P,1,W,W)
        for l in range(c.layers):
            h = _rmsnorm(x, params["ln1"][l])
            q = (h @ params["wq"][l]).reshape(P, W, c.heads, c.head_dim)
            k = (h @ params["wk"][l]).reshape(P, W, c.heads, c.head_dim)
            v = (h @ params["wv"][l]).reshape(P, W, c.heads, c.head_dim)
            # positions past `lengths` hold pad-token KV, positions past
            # the window hold the previous tenant's bytes; both are
            # unreachable through the decode mask
            k_cache = _store_page(k_cache, slot_rows, l, W, k)
            v_cache = _store_page(v_cache, slot_rows, l, W, v)
            scores = jnp.einsum("pqhd,pkhd->phqk", q, k) * scale
            scores = jnp.where(mask, scores, -1e30)
            att = jnp.einsum("phqk,pkhd->pqhd",
                             jax.nn.softmax(scores, axis=-1), v)
            x = x + att.reshape(P, W, c.embed) @ params["wo"][l]
            h2 = _rmsnorm(x, params["ln2"][l])
            x = x + jax.nn.gelu(h2 @ params["w1"][l]) @ params["w2"][l]
        xf = _rmsnorm(x, params["lnf"])
        last = xf[jnp.arange(P), jnp.maximum(lengths - 1, 0)]   # (P, E)
        logits = last @ params["emb"].T
        # the FIRST token is drawn from these logits by the caller
        # (`_sample_first`, the process-shared sampler program) at fold
        # position lengths-1, continuing into decode at `lengths`
        return k_cache, v_cache, logits

    return prefill


def _make_chunk_prefill(config, window=None, extent=None):
    """Build the CHUNK prefill step: one window-sized slice of a prompt,
    scattered into its slot page at an arbitrary offset — the PR-17
    spec-decode verify-chunk idiom (explicit-position `_store_pos`
    writes + a paged-attention read clamped to `[0, offset + j]`)
    widened from draft+1 to `window` positions. This is how prompts
    longer than `prefill_window` stream in across waves, and how a
    prefix-cache hit prefills only its suffix.

    Unlike `_make_prefill`, lanes here are POOL ROWS (lane s writes row
    s, exactly like decode): ONE fixed-shape dispatch advances EVERY
    slot with pending chunk work — a long cold prompt mid-stream and a
    cache hit's suffix alike — and a lane with `nvalid == 0` scatters
    into the garbage row. Queries at chunk offset j attend over slab
    positions [0, offsets + j]; positions below `offsets` must already
    hold the prefix KV (earlier chunks, or a prefix-cache row copy).
    Logits come from each lane's LAST valid chunk position — only
    meaningful for a lane whose chunk ends at its prompt tail, which is
    exactly when the engine samples the first token from them.

    `extent` bounds the attention read to slab positions [0, extent):
    valid for a wave whose furthest lane satisfies offset + nvalid <=
    extent. Positions past the bound are mask-excluded zeros either
    way, so a smaller extent is bit-identical and cheaper — the engine
    warms a ladder of extents and dispatches the smallest one that
    covers the wave."""
    import jax
    import jax.numpy as jnp
    c = config
    W = int(window if window is not None else c.max_len)
    if not 1 <= W <= c.max_len:
        raise ServeError(f"chunk window {W} outside [1, {c.max_len}]")
    E = int(extent if extent is not None else c.max_len)
    if not W <= E <= c.max_len:
        raise ServeError(
            f"chunk extent {E} outside [window={W}, {c.max_len}]")

    def chunk_prefill(params, k_cache, v_cache, tokens, offsets, nvalid):
        # tokens (S, W) int32 chunk slice; offsets (S,) page position of
        # tokens[:, 0]; nvalid (S,) valid token count (0 = idle lane)
        S = tokens.shape[0]
        T = c.max_len
        j = jnp.arange(W)
        wposs = jnp.clip(offsets[:, None] + j[None, :], 0, T - 1)
        valid = j[None, :] < nvalid[:, None]                    # (S, W)
        rows = jnp.where(valid, jnp.arange(S)[:, None], S)   # garbage=S
        x = params["emb"][tokens] + params["pos"][wposs]
        for l in range(c.layers):
            h = _rmsnorm(x, params["ln1"][l])
            q = (h @ params["wq"][l]).reshape(S, W, c.heads, c.head_dim)
            k = (h @ params["wk"][l]).reshape(S, W, c.heads, c.head_dim)
            v = (h @ params["wv"][l]).reshape(S, W, c.heads, c.head_dim)
            k_cache = _store_pos(k_cache, rows, l, wposs, k)
            v_cache = _store_pos(v_cache, rows, l, wposs, v)
            att = _paged_attn(k_cache, v_cache, q, offsets, l, extent=E)
            x = x + att.reshape(S, W, c.embed) @ params["wo"][l]
            h2 = _rmsnorm(x, params["ln2"][l])
            x = x + jax.nn.gelu(h2 @ params["w1"][l]) @ params["w2"][l]
        xf = _rmsnorm(x, params["lnf"])
        last = xf[jnp.arange(S), jnp.maximum(nvalid - 1, 0)]
        logits = last @ params["emb"].T
        return k_cache, v_cache, logits

    return chunk_prefill


def _copy_slot_rows(k_cache, v_cache, src_rows, dst_rows):
    """Whole-row slab-to-slab KV copy — the prefix-cache data mover: a
    cache row gathers into a claimed request slot at admission (the
    memory-bound copy that replaces compute-bound prefill attention) and
    a retiring request's slot gathers into a cache row at publish. Fixed
    (C,) lane shapes; an idle lane copies the garbage row onto itself.
    Donation makes it an in-place slab update on accelerators. int8
    pools copy codes AND scales, so a copied position dequantizes
    bit-identically to the original."""
    k_slab, k_scale = _kv_split(k_cache)
    v_slab, v_scale = _kv_split(v_cache)
    k_slab = k_slab.at[dst_rows].set(k_slab[src_rows])
    v_slab = v_slab.at[dst_rows].set(v_slab[src_rows])
    if k_scale is None:
        return k_slab, v_slab
    k_scale = k_scale.at[dst_rows].set(k_scale[src_rows])
    v_scale = v_scale.at[dst_rows].set(v_scale[src_rows])
    return (k_slab, k_scale), (v_slab, v_scale)


def _make_decode(config, steps=1, eos_id=None):
    """Build the decode step: EVERY pool slot advances up to `steps`
    tokens inside ONE compiled program (`lax.scan` over the micro-step).
    Fixed (S,) shapes and a FIXED step count, so join/leave — and lanes
    finishing mid-scan — never change the program; inactive lanes
    (steps_left == 0) write to the garbage row and their outputs are
    ignored. `steps > 1` amortizes the per-dispatch host cost over K
    tokens (the engine's `decode_steps` knob): admission/retirement move
    to wave granularity, TTFT stays prefill-bound.

    Signature: `decode(params, k_cache, v_cache, tokens, lengths,
    steps_left, temps, top_ks, top_ps, keys) -> (k_cache, v_cache,
    out_tokens (steps, S), emitted)`. `emitted[s]` is the EXACT number
    of tokens lane s produced this wave (rows [0:emitted] of its
    column) — counted in-scan, because deriving it from the steps_left
    delta would overcount when `eos_id` zeroes a lane's remaining
    budget mid-wave. Sampling params ride as (S,) data (greedy lane =
    temp 0), so a mixed batch replays the one program."""
    import jax
    import jax.numpy as jnp
    c = config

    def micro(params, k_cache, v_cache, tokens, lengths, active,
              temps, top_ks, top_ps, keys):
        # one token for every active lane. tokens (S,) int32 last emitted
        # token; lengths (S,) int32 current cache length (the new token's
        # KV lands at position `lengths`); active (S,) bool
        S = tokens.shape[0]
        T = c.max_len
        rows = jnp.where(active, jnp.arange(S), S)       # garbage row = S
        wpos = jnp.clip(lengths, 0, T - 1)
        x = params["emb"][tokens] + params["pos"][wpos]  # (S, E)
        # attention reads positions 0..lengths INCLUSIVE (the new token's
        # KV is written before the read); anything past that — pad-token
        # KV from prefill or a previous tenant's garbage — is masked
        # inside paged_attention's [0, lengths + chunk_offset] clamp
        for l in range(c.layers):
            h = _rmsnorm(x, params["ln1"][l])
            q = (h @ params["wq"][l]).reshape(S, c.heads, c.head_dim)
            k = (h @ params["wk"][l]).reshape(S, c.heads, c.head_dim)
            v = (h @ params["wv"][l]).reshape(S, c.heads, c.head_dim)
            k_cache = _store_pos(k_cache, rows, l, wpos, k)
            v_cache = _store_pos(v_cache, rows, l, wpos, v)
            att = _paged_attn(k_cache, v_cache, q[:, None], lengths,
                              l)[:, 0]
            x = x + att.reshape(S, c.embed) @ params["wo"][l]
            h2 = _rmsnorm(x, params["ln2"][l])
            x = x + jax.nn.gelu(h2 @ params["w1"][l]) @ params["w2"][l]
        logits = _rmsnorm(x, params["lnf"]) @ params["emb"].T
        nxt = _sample_tokens(logits, temps, top_ks, top_ps, keys,
                             lengths)
        return k_cache, v_cache, jnp.where(active, nxt, 0)

    def decode(params, k_cache, v_cache, tokens, lengths, steps_left,
               temps, top_ks, top_ps, keys):
        def step(carry, _):
            k_cache, v_cache, last, lens, left, emitted = carry
            act = left > 0
            k_cache, v_cache, nxt = micro(params, k_cache, v_cache,
                                          last, lens, act,
                                          temps, top_ks, top_ps, keys)
            new_left = jnp.where(act, left - 1, left)
            if eos_id is not None:
                new_left = jnp.where(act & (nxt == eos_id), 0, new_left)
            lens = jnp.where(act, lens + 1, lens)
            last = jnp.where(act, nxt, last)
            emitted = emitted + act.astype(jnp.int32)
            return (k_cache, v_cache, last, lens, new_left, emitted), nxt

        zero = jnp.zeros_like(steps_left)
        (k_cache, v_cache, _, _, _, emitted), toks = jax.lax.scan(
            step, (k_cache, v_cache, tokens, lengths, steps_left, zero),
            None, length=steps)
        return k_cache, v_cache, toks, emitted

    return decode


def _make_spec_decode(config, steps=1, eos_id=None, draft=2):
    """Build the SPECULATIVE decode step: each of the `steps` scan
    micro-steps advances every active lane by up to `draft + 1` tokens
    — k drafted by prompt-lookup (latest n-gram match in the lane's
    token history page) plus one bonus token, verified by ONE chunked
    forward over the k+1 positions. Acceptance is EXACT match against
    the base model's own next-token choice, so the emitted stream is
    token-identical to non-speculative decode (greedy and sampled);
    acceptance counts are in-scan DATA, so acceptance variance never
    changes program shapes and the zero-retrace contract holds.

    Safety of the chunk writes:
      * a REJECTED position's KV is stale, but the lane's next chunk
        starts at its new length and rewrites [len, len+k] before any
        mask can expose them;
      * near the page end, write positions clip to max_len-1 and may
        collide — only queries whose outputs are DISCARDED (offset >=
        emitted count) ever sit past max_len-2, and a query only reads
        positions <= its own, so the clipped junk is unreachable from
        any emitted token.

    Signature: `spec(params, k_cache, v_cache, tokens, lengths,
    steps_left, temps, top_ks, top_ps, keys, token_buf) -> (k_cache,
    v_cache, tok_blocks (steps, S, draft+1), n_emits (steps, S),
    emitted (S,), accepted (S,), rejected (S,))`. `token_buf` is the
    (S, max_len) token history page (prompt + generated so far; entries
    [0, lengths] valid) — the draft source, updated in-scan exactly as
    a host rebuild would, so wave boundaries stay invisible. Lane s's
    wave output is rows `tok_blocks[i, s, :n_emits[i, s]]` in scan
    order; accepted/rejected count draft tokens for telemetry."""
    import jax
    import jax.numpy as jnp
    c = config
    draft = int(draft)
    if draft < 1:
        raise ServeError(f"draft must be >= 1, got {draft}")
    C = draft + 1

    def micro(params, k_cache, v_cache, last, lens, act, left,
              temps, top_ks, top_ps, keys, token_buf):
        S = last.shape[0]
        T = c.max_len
        rows = jnp.where(act, jnp.arange(S), S)          # garbage row
        coffs = jnp.arange(C)
        # -- prompt-lookup draft: the LATEST earlier occurrence of the
        # current tail token predicts its historical successors
        idx = jnp.arange(T)
        hit = (idx[None, :] < lens[:, None]) & (token_buf == last[:, None])
        p = jnp.max(jnp.where(hit, idx[None, :], -1), axis=1)    # (S,)
        dsrc = p[:, None] + 1 + jnp.arange(draft)[None, :]       # (S, k)
        ok = (p[:, None] >= 0) & (dsrc <= lens[:, None])
        cand = jnp.take_along_axis(token_buf, jnp.clip(dsrc, 0, T - 1),
                                   axis=1)
        drafts = jnp.where(ok, cand, last[:, None])              # (S, k)
        # -- ONE verify forward over the whole chunk [last, drafts...]
        chunk = jnp.concatenate([last[:, None], drafts], axis=1)  # (S, C)
        wposs = jnp.clip(lens[:, None] + coffs[None, :], 0, T - 1)
        x = params["emb"][chunk] + params["pos"][wposs]   # (S, C, E)
        for l in range(c.layers):
            h = _rmsnorm(x, params["ln1"][l])
            q = (h @ params["wq"][l]).reshape(S, C, c.heads, c.head_dim)
            k = (h @ params["wk"][l]).reshape(S, C, c.heads, c.head_dim)
            v = (h @ params["wv"][l]).reshape(S, C, c.heads, c.head_dim)
            k_cache = _store_pos(k_cache, rows[:, None], l, wposs, k)
            v_cache = _store_pos(v_cache, rows[:, None], l, wposs, v)
            att = _paged_attn(k_cache, v_cache, q, lens, l)
            x = x + att.reshape(S, C, c.embed) @ params["wo"][l]
            h2 = _rmsnorm(x, params["ln2"][l])
            x = x + jax.nn.gelu(h2 @ params["w1"][l]) @ params["w2"][l]
        logits = _rmsnorm(x, params["lnf"]) @ params["emb"].T  # (S,C,V)
        # -- the base model's own choice at EVERY chunk position, keyed
        # by that position — identical draws to non-spec decode
        positions = (lens[:, None] + coffs[None, :]).reshape(-1)
        base_next = _sample_tokens(
            logits.reshape(S * C, -1),
            jnp.repeat(temps, C), jnp.repeat(top_ks, C),
            jnp.repeat(top_ps, C), jnp.repeat(keys, C, axis=0),
            positions).reshape(S, C)
        # -- accept the longest draft prefix the base model agrees with,
        # plus the bonus token sampled after it; cap to the lane budget
        match = jnp.cumprod(
            (drafts == base_next[:, :draft]).astype(jnp.int32), axis=1)
        n = jnp.minimum(jnp.sum(match, axis=1) + 1, left)
        if eos_id is not None:
            is_eos = (base_next == eos_id) & (coffs[None, :] < n[:, None])
            n = jnp.where(jnp.any(is_eos, axis=1),
                          jnp.argmax(is_eos, axis=1) + 1, n)
        n = jnp.where(act, n, 0)
        new_last = jnp.where(
            act,
            jnp.take_along_axis(base_next,
                                jnp.maximum(n - 1, 0)[:, None],
                                axis=1)[:, 0],
            last)
        new_lens = lens + n
        # -- history page update, exactly what a host rebuild would hold:
        # chunk token at each written position, new tail at new_lens
        buf2 = token_buf.at[jnp.arange(S)[:, None], wposs].set(
            jnp.concatenate([last[:, None], base_next[:, :draft]],
                            axis=1))
        buf2 = buf2.at[jnp.arange(S),
                       jnp.clip(new_lens, 0, T - 1)].set(new_last)
        token_buf = jnp.where(act[:, None], buf2, token_buf)
        return (k_cache, v_cache, token_buf, base_next, n, new_last,
                new_lens)

    def spec(params, k_cache, v_cache, tokens, lengths, steps_left,
             temps, top_ks, top_ps, keys, token_buf):
        def step(carry, _):
            (k_cache, v_cache, last, lens, left, emitted, buf,
             acc, rej) = carry
            act = left > 0
            (k_cache, v_cache, buf, base_next, n, new_last,
             new_lens) = micro(params, k_cache, v_cache, last, lens,
                               act, left, temps, top_ks, top_ps, keys,
                               buf)
            left = jnp.where(act, left - n, left)
            if eos_id is not None:
                left = jnp.where(act & (n > 0) & (new_last == eos_id),
                                 0, left)
            emitted = emitted + n
            acc = acc + jnp.where(act, n - 1, 0)
            rej = rej + jnp.where(act, draft - (n - 1), 0)
            return ((k_cache, v_cache, new_last, new_lens, left,
                     emitted, buf, acc, rej), (base_next, n))

        zero = jnp.zeros_like(steps_left)
        carry0 = (k_cache, v_cache, tokens, lengths, steps_left, zero,
                  token_buf, zero, zero)
        ((k_cache, v_cache, _, _, _, emitted, _, acc, rej),
         (tok_blocks, n_emits)) = jax.lax.scan(step, carry0, None,
                                               length=steps)
        return k_cache, v_cache, tok_blocks, n_emits, emitted, acc, rej

    return spec


class CachedDecoder:
    """The model side of the continuous engine: two jitted programs over
    a KV slot pool. Programs are shape-generic in the POOL (the garbage
    row is `k_cache.shape[0] - 1` at trace time), so one CachedDecoder
    serves pools of any slot count — each pool size compiles once.

    `params=` shares weights across instances (e.g. a reference decoder
    for tests); `seed=` controls the deterministic random init.
    """

    def __init__(self, config, params=None, seed=0):
        import jax
        from ..deploy import maybe_enable_compile_cache
        # arm the persistent compilation cache BEFORE the first compile:
        # warm replicas deserialize instead of recompiling
        maybe_enable_compile_cache()
        self.config = config
        self.params = params if params is not None \
            else init_decoder_params(config, seed)
        # census attribution (mx.inspect.memory): the decoder weights are
        # serving's second-biggest resident set after the KV slabs
        try:
            from ..inspect import memory as _mem
            _mem.register(self.params, owner="decoder_params")
        except Exception:
            pass
        # programs keyed by their trace-time constants (prefill window /
        # decode scan length + eos), each its own jit: built once per
        # engine at construction — steady state replays, never re-builds
        self._prefills = {}
        self._chunks = {}
        self._decodes = {}
        self._copy = None
        self._prefill = self.prefill_program(config.max_len)
        self._decode = self.decode_program(1, None)

    @staticmethod
    def _greedy_defaults(jnp, n, temps, top_ks, top_ps, keys):
        """Fill missing sampling arrays with the greedy encoding (temp
        0 selects argmax in-program) so pre-sampling call sites keep
        working unchanged."""
        if temps is None:
            temps = jnp.zeros((n,), dtype=jnp.float32)
        if top_ks is None:
            top_ks = jnp.zeros((n,), dtype=jnp.int32)
        if top_ps is None:
            top_ps = jnp.ones((n,), dtype=jnp.float32)
        if keys is None:
            keys = jnp.zeros((n, 2), dtype=jnp.uint32)
        return temps, top_ks, top_ps, keys

    def new_pool(self, max_slots=None, dtype=None):
        c = self.config
        return KVCachePool(max_slots, layers=c.layers, max_len=c.max_len,
                           heads=c.heads, head_dim=c.head_dim,
                           dtype=dtype or c.dtype)

    def prefill_program(self, window):
        """The jitted prefill program for a prompt-page width."""
        import jax
        key = int(window)
        fn = self._prefills.get(key)
        if fn is None:
            fn = _sanitize.maybe_wrap_donated(
                jax.jit(_make_prefill(self.config, window=key),
                        donate_argnums=(1, 2)),
                (1, 2), f"prefill[w={key}]")
            self._prefills[key] = fn
        return fn

    def chunk_prefill_program(self, window, extent=None):
        """The jitted CHUNK prefill program for a (window, extent) pair:
        scatter one window-sized prompt slice at an arbitrary page
        offset and emit logits at each lane's chunk tail (chunked
        prefill of long prompts + prefix-cache suffix prefill,
        serve/continuous.py). `extent` bounds the attention read — the
        engine warms a ladder of extents per window and dispatches the
        smallest one covering each wave."""
        import jax
        key = (int(window),
               int(extent if extent is not None else self.config.max_len))
        fn = self._chunks.get(key)
        if fn is None:
            fn = _sanitize.maybe_wrap_donated(
                jax.jit(_make_chunk_prefill(self.config, window=key[0],
                                            extent=key[1]),
                        donate_argnums=(1, 2)),
                (1, 2), f"chunk_prefill[w={key[0]},e={key[1]}]")
            self._chunks[key] = fn
        return fn

    def copy_program(self):
        """The jitted slab-to-slab KV row-copy program (prefix-cache
        admission hit / retire publish): a memory-bound gather, no
        attention math, donated like every other slab consumer."""
        import jax
        if self._copy is None:
            self._copy = _sanitize.maybe_wrap_donated(
                jax.jit(_copy_slot_rows, donate_argnums=(0, 1)),
                (0, 1), "copy_slot_rows")
        return self._copy

    def decode_program(self, steps, eos_id=None, draft=0):
        """The jitted decode program for a (steps, eos, draft) variant
        (built and memoized on first request; the engine asks once at
        init). `draft > 0` selects the speculative program — a
        DIFFERENT fixed shape (chunked verify), compiled once like any
        other variant."""
        import jax
        key = (int(steps), eos_id, int(draft))
        fn = self._decodes.get(key)
        if fn is None:
            if key[2] > 0:
                built = _make_spec_decode(self.config, steps=key[0],
                                          eos_id=eos_id, draft=key[2])
            else:
                built = _make_decode(self.config, steps=key[0],
                                     eos_id=eos_id)
            fn = _sanitize.maybe_wrap_donated(
                jax.jit(built, donate_argnums=(1, 2)), (1, 2),
                f"decode[s={key[0]},eos={key[1]},d={key[2]}]")
            self._decodes[key] = fn
        return fn

    def prefill(self, k_cache, v_cache, tokens, lengths, slot_rows,
                temps=None, top_ks=None, top_ps=None, keys=None):
        # window inferred from the token page width (a compiled program
        # exists per width; the engine always sends its own window)
        import jax.numpy as jnp
        temps, top_ks, top_ps, keys = self._greedy_defaults(
            jnp, tokens.shape[0], temps, top_ks, top_ps, keys)
        k_cache, v_cache, logits = self.prefill_program(tokens.shape[1])(
            self.params, k_cache, v_cache, tokens, lengths, slot_rows)
        first = _sample_first(logits, temps, top_ks, top_ps, keys,
                              lengths - 1)
        return k_cache, v_cache, first

    def decode(self, k_cache, v_cache, tokens, lengths, steps_left,
               steps=1, eos_id=None, temps=None, top_ks=None,
               top_ps=None, keys=None, draft=0, token_buf=None):
        import jax.numpy as jnp
        temps, top_ks, top_ps, keys = self._greedy_defaults(
            jnp, tokens.shape[0], temps, top_ks, top_ps, keys)
        prog = self.decode_program(steps, eos_id, draft)
        if draft > 0:
            if token_buf is None:
                raise ServeError(
                    "speculative decode (draft > 0) needs token_buf — "
                    "the (S, max_len) prompt+generated history page")
            return prog(self.params, k_cache, v_cache, tokens, lengths,
                        steps_left, temps, top_ks, top_ps, keys,
                        token_buf)
        return prog(self.params, k_cache, v_cache, tokens, lengths,
                    steps_left, temps, top_ks, top_ps, keys)

    def compile_cache_size(self):
        """Total compiled programs across every jit (-1 unknown) — the
        zero-retrace observable (≙ ExportedModel.compile_cache_size)."""
        fns = (list(self._prefills.values())
               + list(self._chunks.values())
               + list(self._decodes.values()))
        if self._copy is not None:
            fns.append(self._copy)
        sizes = [int(getattr(f, "_cache_size", lambda: -1)())
                 for f in fns]
        if any(s < 0 for s in sizes):
            return -1
        return sum(sizes)

    def reference_generate(self, prompt, max_new_tokens, eos_id=None,
                           window=None, temperature=0.0, top_k=0,
                           top_p=1.0, seed=0, draft_tokens=0,
                           kv_dtype=None, cached_prefix_len=0):
        """Generation through a PRIVATE 1-slot pool — the
        scheduling-free reference the engine's mixed-batch outputs must
        match token-for-token (tests). Uses the same compiled math; pass
        the engine's `prefill_window` so the prefill page width (and so
        the float-op layout) matches bit-for-bit. Prompts longer than
        the window replay the engine's CHUNKED prefill: a windowed first
        chunk at offset 0, then window-sized slices through the chunk
        program. `cached_prefix_len=L` mirrors a prefix-cache HIT —
        positions [0, L) carry the canonical cold provenance (windowed
        head + chunked remainder; a cache row copy is bit-identical to
        that by the causal mask, which makes prefix KV depend only on
        prefix tokens), while the suffix [L, plen) goes through the
        chunk program exactly as the engine prefills it after the row
        copy — so hit-path outputs are checked against an explicit
        reference, never assumed. Sampling (`temperature > 0` with the
        request seed) matches the engine because the draw key is a pure
        function of (seed, position); `draft_tokens > 0` runs the
        speculative program one wave at a time with a host-rebuilt
        history page — same tokens, by the exact-verification contract.
        `kv_dtype="int8"` mirrors an int8 engine pool."""
        import jax.numpy as jnp
        pool = self.new_pool(max_slots=1, dtype=kv_dtype)
        W = int(window if window is not None else self.config.max_len)
        prompt = _np.asarray(prompt, dtype=_np.int32).ravel()
        plen = int(prompt.size)
        if plen < 1 or plen >= self.config.max_len:
            raise ServeError(
                f"prompt length {plen} outside [1, max_len-1="
                f"{self.config.max_len - 1}]")
        L = int(cached_prefix_len)
        if not 0 <= L < plen:
            raise ServeError(
                f"cached_prefix_len {L} outside [0, plen-1={plen - 1}]")
        temps = jnp.asarray([float(temperature)], dtype=jnp.float32)
        tks = jnp.asarray([int(top_k)], dtype=jnp.int32)
        tps = jnp.asarray([float(top_p)], dtype=jnp.float32)
        keys = jnp.asarray(_seed_key(seed)[None, :])
        # windowed head: a cold request's offset-0 wave covers
        # min(plen, W) tokens; a hit's head stops at the cache boundary
        # (its suffix is chunk-prefilled even when it would fit windowed)
        head = min(plen if L == 0 else L, W)
        toks = _np.zeros((1, W), dtype=_np.int32)
        toks[0, :head] = prompt[:head]
        k, v = pool.buffers()
        k, v, logits = self.prefill_program(W)(
            self.params, k, v, jnp.asarray(toks),
            jnp.asarray([head], dtype=jnp.int32),
            jnp.asarray([0], dtype=jnp.int32))
        pool.swap_buffers(k, v)
        # chunked remainder through the SAME chunk program the engine
        # dispatches; the final chunk's logits sit at the prompt tail
        pos = head
        while pos < plen:
            n = min(W, plen - pos)
            ctoks = _np.zeros((1, W), dtype=_np.int32)
            ctoks[0, :n] = prompt[pos:pos + n]
            k, v = pool.buffers()
            k, v, logits = self.chunk_prefill_program(W)(
                self.params, k, v, jnp.asarray(ctoks),
                jnp.asarray([pos], dtype=jnp.int32),
                jnp.asarray([n], dtype=jnp.int32))
            pool.swap_buffers(k, v)
            pos += n
        first = _sample_first(
            logits, temps, tks, tps, keys,
            jnp.asarray([plen - 1], dtype=jnp.int32))
        out = [int(first[0])]
        cache_len = plen
        draft = int(draft_tokens)
        while (len(out) < max_new_tokens
               and (eos_id is None or out[-1] != eos_id)
               and cache_len + 1 < self.config.max_len):
            k, v = pool.buffers()
            if draft > 0:
                left = min(max_new_tokens - len(out),
                           self.config.max_len - 1 - cache_len)
                buf = _np.zeros((1, self.config.max_len),
                                dtype=_np.int32)
                hist = list(prompt) + out
                buf[0, :len(hist)] = hist
                k, v, blocks, n_emits, _, _, _ = self.decode(
                    k, v, jnp.asarray([out[-1]], dtype=jnp.int32),
                    jnp.asarray([cache_len], dtype=jnp.int32),
                    jnp.asarray([left], dtype=jnp.int32),
                    steps=1, eos_id=eos_id, temps=temps, top_ks=tks,
                    top_ps=tps, keys=keys, draft=draft,
                    token_buf=jnp.asarray(buf))
                pool.swap_buffers(k, v)
                n = int(_np.asarray(n_emits)[0, 0])
                out.extend(int(t) for t in
                           _np.asarray(blocks)[0, 0, :n])
                cache_len += n
            else:
                k, v, toks1, _ = self.decode(
                    k, v, jnp.asarray([out[-1]], dtype=jnp.int32),
                    jnp.asarray([cache_len], dtype=jnp.int32),
                    jnp.asarray([1], dtype=jnp.int32),
                    temps=temps, top_ks=tks, top_ps=tps, keys=keys)
                pool.swap_buffers(k, v)
                out.append(int(toks1[0, 0]))
                cache_len += 1
        return _np.asarray(out, dtype=_np.int32)


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------
class _GenRequest:
    __slots__ = ("prompt", "max_new", "future", "deadline", "t_submit",
                 "ctx", "slot", "generated", "cache_len", "t_first",
                 "t_last", "temperature", "top_k", "top_p", "key",
                 "entry", "cached_len", "prefill_pos")

    def __init__(self, prompt, max_new, deadline, ctx,
                 temperature=0.0, top_k=0, top_p=1.0, key=None):
        self.prompt = prompt                 # np.int32 (plen,)
        self.max_new = max_new
        self.future = Future()
        self.deadline = deadline             # perf_counter deadline or None
        self.t_submit = time.perf_counter()
        self.ctx = ctx                       # serve.request root context
        self.slot = None
        self.generated = []
        self.cache_len = 0
        self.t_first = None                  # first token (TTFT anchor)
        self.t_last = None
        self.temperature = temperature       # 0.0 = greedy lane
        self.top_k = top_k
        self.top_p = top_p
        self.key = key if key is not None else _seed_key(0)  # uint32 (2,)
        self.entry = None        # pinned prefix-cache entry (hit path)
        self.cached_len = 0      # prompt tokens served from the cache
        self.prefill_pos = 0     # prompt tokens already in KV (chunked)

    def sort_key(self):
        """Earliest-deadline-first; deadline-less requests rank after
        every deadline-holder, FIFO among themselves."""
        return (self.deadline is None,
                self.deadline if self.deadline is not None
                else self.t_submit,
                self.t_submit)


class ContinuousEngine:
    """Iteration-level batching decode engine over a `CachedDecoder`.

    ::

        model = serve.CachedDecoder(serve.DecoderConfig(max_len=64))
        with serve.ContinuousEngine(model, max_slots=8) as eng:
            fut = eng.submit([3, 14, 15], max_new_tokens=16)
            tokens = fut.result()            # np.int32 generated ids

    Knobs (constructor arg > deployment profile (mx.tune) >
    MXNET_SERVE_* env > default — the profile is a measured,
    fingerprint-checked artifact, so ambient shell exports must not
    defeat it; MXNET_TUNE_DISABLE=1 restores raw env behavior):

      max_slots        KV slots = max concurrently-decoding requests
      prefill_budget   max prompt TOKENS prefilled per engine iteration
                       (bounds how long admission may stall in-flight
                       decode; >= 1 request always admitted when a slot
                       is free)
      prefill_lanes    FIXED lane count of the prefill program (default
                       min(max_slots, 8)): its cost is paid in full per
                       admission wave regardless of how many lanes carry
                       real requests, so it is sized for the admission
                       RATE, not the pool — a max_slots-wide prefill
                       would bill a 1-request wave the whole pool's
                       prefill FLOPs
      max_queue        waiting-request bound (admission control; reuses
                       MXNET_SERVE_MAX_QUEUE; reject-newest)
      default_deadline_ms  queue deadline (MXNET_SERVE_DEADLINE_MS);
                       expiry while WAITING fails fast with
                       RequestTimeout — admitted requests always finish
      draft_tokens     speculative decode depth k
                       (MXNET_SERVE_DRAFT_TOKENS, default 0 = off):
                       each scan micro-step drafts k tokens by
                       prompt-lookup and verifies them in one chunked
                       forward; output tokens are IDENTICAL to
                       draft_tokens=0 (exact-match verification)
      kv_dtype         KV pool storage dtype; "int8" stores quantized
                       codes + per-position f32 scales (~4x KV bytes
                       saved at float32 serving dtype — see
                       pool.stats()["slots_per_gb"])
      prefix_cache_slots  dedicated pool rows holding shared-prefix KV
                       (MXNET_SERVE_PREFIX_CACHE_SLOTS, default 0 =
                       off): admission matches the longest cached
                       prefix, row-copies its KV into the claimed slot,
                       and prefills ONLY the suffix
      prefix_block     prefix-cache granularity in tokens
                       (MXNET_SERVE_PREFIX_BLOCK): prefixes cache and
                       match on whole blocks only
      prefix_cache_insert  publish a retiring request's own prompt
                       prefix back into the cache
                       (MXNET_SERVE_PREFIX_CACHE_INSERT, default on)

    Prompts longer than `prefill_window` stream in window-sized CHUNKS
    across successive waves (the chunk program advances every
    mid-prefill lane per wave), so one long prompt never monopolizes a
    prefill wave and short requests' TTFT stays bounded.

    Exactly one scheduler thread runs the compiled steps, so the donated
    KV buffers have a single writer; submit() is safe from any thread.
    """

    def __init__(self, model, *, max_slots=None, prefill_budget=None,
                 prefill_lanes=None, prefill_window=None, decode_steps=None,
                 max_queue=None, default_deadline_ms=None, eos_id=None,
                 draft_tokens=None, kv_dtype=None, prefix_block=None,
                 prefix_cache_slots=None, prefix_cache_insert=None,
                 name="serve.continuous"):
        from ..tune.profile import resolve as _tune_resolve
        self.model = model
        self.name = name
        self.eos_id = eos_id
        if max_slots is None:
            max_slots = _tune_resolve("serve.max_slots")
        if kv_dtype is None:
            kv_dtype = _tune_resolve("serve.kv_dtype")
            if kv_dtype is None:
                kv_dtype = get_env("MXNET_SERVE_KV_DTYPE")
        self.kv_dtype = kv_dtype
        # shared-prefix reuse tier (serve/prefix_cache.py): cached
        # prefixes live in DEDICATED pool rows claimed once here, so
        # admission capacity (max_slots) and cache capacity are
        # independent knobs and SlotsFullError semantics are unchanged
        if prefix_block is None:
            prefix_block = _tune_resolve("serve.prefix_block")
            if prefix_block is None:
                prefix_block = get_env("MXNET_SERVE_PREFIX_BLOCK", 16,
                                       typ=int)
        self.prefix_block = int(prefix_block)
        if self.prefix_block < 1:
            raise ServeError("prefix_block must be >= 1")
        if prefix_cache_slots is None:
            prefix_cache_slots = _tune_resolve("serve.prefix_cache_slots")
            if prefix_cache_slots is None:
                prefix_cache_slots = get_env(
                    "MXNET_SERVE_PREFIX_CACHE_SLOTS", 0, typ=int)
        self.prefix_cache_slots = int(prefix_cache_slots)
        if self.prefix_cache_slots < 0:
            raise ServeError("prefix_cache_slots must be >= 0")
        if prefix_cache_insert is None:
            prefix_cache_insert = _tune_resolve(
                "serve.prefix_cache_insert")
            if prefix_cache_insert is None:
                prefix_cache_insert = bool(get_env(
                    "MXNET_SERVE_PREFIX_CACHE_INSERT", 1, typ=int))
        self.prefix_cache_insert = bool(prefix_cache_insert)
        if max_slots is None:
            max_slots = get_env("MXNET_SERVE_MAX_SLOTS", 8, typ=int)
        self.max_slots = int(max_slots)
        if self.max_slots < 1:
            raise ServeError("max_slots must be >= 1")
        # the pool is carved with max_slots REQUEST rows plus the
        # dedicated prefix-cache rows; self.max_slots stays the request
        # capacity every admission/queue bound sees
        self.pool = model.new_pool(
            self.max_slots + self.prefix_cache_slots, dtype=kv_dtype)
        self._cache = None
        if self.prefix_cache_slots:
            self._cache = PrefixCache(
                self.prefix_block,
                [self.pool.claim()
                 for _ in range(self.prefix_cache_slots)])
        # micro-iterations per compiled decode dispatch: >1 amortizes the
        # host round-trip over K tokens; admission/retirement happen at
        # wave granularity (a lane finishing mid-wave holds its slot
        # until the wave ends, never computes past its budget)
        if decode_steps is None:
            decode_steps = _tune_resolve("serve.decode_steps")
            if decode_steps is None:
                decode_steps = get_env("MXNET_SERVE_DECODE_STEPS", 4,
                                       typ=int)
        self.decode_steps = max(1, int(decode_steps))
        if draft_tokens is None:
            draft_tokens = _tune_resolve("serve.draft_tokens")
        self.draft_tokens = int(
            draft_tokens if draft_tokens is not None
            else get_env("MXNET_SERVE_DRAFT_TOKENS", 0, typ=int))
        if self.draft_tokens < 0:
            raise ServeError("draft_tokens must be >= 0")
        self._decode_prog = model.decode_program(self.decode_steps,
                                                 eos_id,
                                                 self.draft_tokens)
        # prompt page width: prompts are bounded by it, and the prefill
        # program pays O(window^2) attention instead of O(max_len^2) —
        # size it to the served prompt distribution, not the page
        self.prefill_window = int(
            prefill_window if prefill_window is not None
            else model.config.max_len)
        if not 1 <= self.prefill_window <= model.config.max_len:
            raise ServeError(
                f"prefill_window must be in [1, max_len], got "
                f"{self.prefill_window}")
        self._prefill_prog = model.prefill_program(self.prefill_window)
        # the chunk programs exist whenever a prompt can outgrow the
        # window (chunked streaming) or a cache hit leaves a suffix to
        # prefill at a nonzero page offset. They form an EXTENT LADDER
        # (window, 2*window, ... max_len): attention cost follows how
        # far a wave's furthest lane has actually streamed, not
        # max_len — every rung is warmed, so picking one per wave is
        # still zero-retrace
        self._chunk_progs = None
        self._chunk_extents = ()
        if (self.prefill_window < model.config.max_len
                or self._cache is not None):
            exts, e = [], self.prefill_window
            while e < model.config.max_len:
                exts.append(e)
                e *= 2
            exts.append(model.config.max_len)
            self._chunk_extents = tuple(exts)
            self._chunk_progs = {
                x: model.chunk_prefill_program(self.prefill_window,
                                               extent=x)
                for x in exts}
        self._copy_prog = (model.copy_program()
                           if self._cache is not None else None)
        self.prefill_budget = int(
            prefill_budget if prefill_budget is not None
            else get_env("MXNET_SERVE_PREFILL_BUDGET", 256, typ=int))
        if self.prefill_budget < 1:
            raise ServeError("prefill_budget must be >= 1")
        if prefill_lanes is None:
            prefill_lanes = _tune_resolve("serve.prefill_lanes")
            if prefill_lanes is None:
                prefill_lanes = get_env("MXNET_SERVE_PREFILL_LANES",
                                        typ=int)
        self.prefill_lanes = int(prefill_lanes if prefill_lanes is not None
                                 else min(self.max_slots, 8))
        if not 1 <= self.prefill_lanes <= self.max_slots:
            raise ServeError(
                f"prefill_lanes must be in [1, max_slots], got "
                f"{self.prefill_lanes}")
        self.max_queue = int(
            max_queue if max_queue is not None
            else get_env("MXNET_SERVE_MAX_QUEUE", 256, typ=int))
        dl = (default_deadline_ms if default_deadline_ms is not None
              else get_env("MXNET_SERVE_DEADLINE_MS", typ=float))
        self.default_deadline_s = None if dl is None else float(dl) / 1e3
        self.max_len = model.config.max_len

        self._cv = threading.Condition()
        self._waiting = deque()              # submitted, no slot yet
        self._prefilling = {}                # slot -> req, prompt KV partial
        self._running = {}                   # slot -> _GenRequest
        self._closing = False
        self._drain = True
        self._started = False
        self._warm_cache_size = None
        self._canary = None
        self.warmup_s = None
        self._thread = threading.Thread(
            target=self._loop, name=f"{name}-scheduler", daemon=True)

        # per-engine metrics (all mutation under _mlock)
        self._mlock = threading.Lock()
        self._t0 = time.perf_counter()
        self._counters = {k: 0 for k in (
            "requests", "replies", "rejected", "timeouts", "errors",
            "admitted", "retired", "decode_iterations", "decode_tokens",
            "prefill_tokens", "prefill_batches", "programs_compiled",
            "active_sum", "sampled_tokens", "draft_accepted",
            "draft_rejected", "prefix_hits", "prefix_misses",
            "prefix_cached_tokens")}
        self._auto_seed = 0                  # per-engine seed fountain
        self._ttft_ms = deque(maxlen=4096)
        self._tpot_ms = deque(maxlen=4096)
        self._e2e_ms = deque(maxlen=4096)

    # -- lifecycle ---------------------------------------------------------
    def start(self, warmup=True):
        """Compile (or persistent-cache-load) both step programs before
        traffic, then start the scheduler thread. Returns self; records
        the warm compile-cache size for the zero-retrace assertion."""
        if self._started:
            return self
        t0 = time.perf_counter()
        if warmup:
            self._warmup()
        with self._cv:
            self._warm_cache_size = self.model.compile_cache_size()
            self._started = True
        self.warmup_s = round(time.perf_counter() - t0, 3)
        if _sanitize.enabled("retrace"):
            # warmup compiled everything; from here any growth is a
            # broken zero-retrace contract (polled once per decode wave)
            _sanitize.arm()
        if _sanitize.enabled("slot"):
            with self._cv:
                if self._canary is None:
                    self._canary = _sanitize.SlotCanary(self.pool)
        _trace.install_crash_hooks()
        mem_install_oom_hook()
        self._thread.start()
        return self

    def _warmup(self):
        """One garbage-lane pass through EVERY step program (prefill +
        decode, plus the chunk-prefill and row-copy programs when
        configured): compiles (or loads from MXNET_COMPILE_CACHE_DIR)
        each without touching any real slot."""
        import jax
        import jax.numpy as jnp
        g = self.pool.garbage_row
        P = self.prefill_lanes
        S = self.pool.max_slots
        kb, vb = self.pool.buffers()
        lens = jnp.ones((P,), dtype=jnp.int32)
        k, v, logits = self._prefill_prog(
            self.model.params, kb, vb,
            jnp.zeros((P, self.prefill_window), dtype=jnp.int32),
            lens, jnp.full((P,), g, dtype=jnp.int32))
        # warm the shared first-token sampler at this (P, vocab) shape
        # too — it is part of the steady-state prefill wave
        _sample_first(logits, jnp.zeros((P,), dtype=jnp.float32),
                      jnp.zeros((P,), dtype=jnp.int32),
                      jnp.ones((P,), dtype=jnp.float32),
                      jnp.zeros((P, 2), dtype=jnp.uint32), lens - 1)
        self.pool.swap_buffers(k, v)
        kb, vb = self.pool.buffers()
        args = [self.model.params, kb, vb,
                jnp.zeros((S,), dtype=jnp.int32),
                jnp.zeros((S,), dtype=jnp.int32),
                jnp.zeros((S,), dtype=jnp.int32),
                jnp.zeros((S,), dtype=jnp.float32),
                jnp.zeros((S,), dtype=jnp.int32),
                jnp.ones((S,), dtype=jnp.float32),
                jnp.zeros((S, 2), dtype=jnp.uint32)]
        if self.draft_tokens:
            args.append(jnp.zeros((S, self.max_len), dtype=jnp.int32))
        out = self._decode_prog(*args)
        k, v = out[0], out[1]
        self.pool.swap_buffers(k, v)
        n_progs = 2
        if self._chunk_progs is not None:
            # all-idle chunk wave (every lane scatters into garbage)
            # through EVERY extent rung, so wave-time extent selection
            # never compiles; warm the first-token sampler at the
            # (S, vocab) shape the chunk path samples from too
            logits = None
            for prog in self._chunk_progs.values():
                kb, vb = self.pool.buffers()
                k, v, logits = prog(
                    self.model.params, kb, vb,
                    jnp.zeros((S, self.prefill_window), dtype=jnp.int32),
                    jnp.zeros((S,), dtype=jnp.int32),
                    jnp.zeros((S,), dtype=jnp.int32))
                self.pool.swap_buffers(k, v)
                n_progs += 1
            _sample_first(logits, jnp.zeros((S,), dtype=jnp.float32),
                          jnp.zeros((S,), dtype=jnp.int32),
                          jnp.ones((S,), dtype=jnp.float32),
                          jnp.zeros((S, 2), dtype=jnp.uint32),
                          jnp.zeros((S,), dtype=jnp.int32))
        if self._copy_prog is not None:
            # garbage-onto-garbage row copy
            kb, vb = self.pool.buffers()
            k, v = self._copy_prog(
                kb, vb, jnp.full((P,), g, dtype=jnp.int32),
                jnp.full((P,), g, dtype=jnp.int32))
            self.pool.swap_buffers(k, v)
            n_progs += 1
        # wait for the compiles to actually finish so warmup_s is honest
        jax.block_until_ready(self.pool.buffers()[0])
        self._count("programs_compiled", n_progs)

    def __enter__(self):
        return self.start()

    def close(self, drain=True, timeout=60.0):
        """Stop the scheduler. `drain=True` finishes admitted AND waiting
        requests first; `drain=False` fails the waiting queue (admitted
        requests still finish — their slots hold real state)."""
        with self._cv:
            if not self._closing:
                self._closing = True
                self._drain = drain
                pending = [] if drain else list(self._waiting)
                if not drain:
                    self._waiting.clear()
            else:
                pending = []
            self._cv.notify_all()
        for req in pending:
            _fail(req, ServerClosed("engine closed before admission"))
        if self._started:
            self._thread.join(timeout=timeout)
        with self._cv:
            canary, self._canary = self._canary, None
        if canary is not None:
            canary.release()

    def __exit__(self, *exc):
        self.close()

    def begin_drain(self):
        """Stop admitting (submit() raises `ReplicaDraining`) while the
        scheduler finishes every waiting AND admitted request. Non-blocking
        by design — the drain-and-swap replica keeps answering heartbeats
        while its KV-resident requests finish; `close()` joins after."""
        with self._cv:
            if not self._closing:
                self._closing = True
                self._drain = True
            self._cv.notify_all()

    @property
    def draining(self):
        """True while a drain is in progress (resident requests still
        finishing); False once the scheduler has exited."""
        return self._closing and self._drain and self._thread.is_alive()

    def queue_depth(self):
        """(waiting, running) request counts — the fleet router's
        least-loaded placement signal. Mid-prefill (chunk-streaming)
        requests hold slots, so they count as running."""
        with self._cv:
            return (len(self._waiting),
                    len(self._running) + len(self._prefilling))

    # -- submission --------------------------------------------------------
    def submit(self, prompt_tokens, max_new_tokens=16, deadline_ms=None,
               temperature=0.0, top_k=0, top_p=1.0, seed=None):
        """Enqueue one generation request; returns a Future resolving to
        the np.int32 array of generated token ids (cut at `eos_id`,
        `max_new_tokens`, or a full KV page).

        `temperature=0` (default) is greedy; `temperature > 0` samples
        with optional `top_k`/`top_p` truncation, deterministically in
        `seed` (auto-assigned from a per-engine counter when omitted).
        Both kinds share one compiled program — sampling params are
        array data, never shapes."""
        temperature = float(temperature)
        if temperature < 0.0:
            raise ServeError("temperature must be >= 0")
        top_k = int(top_k)
        if top_k < 0:
            raise ServeError("top_k must be >= 0")
        top_p = float(top_p)
        if not 0.0 < top_p <= 1.0:
            raise ServeError(f"top_p must be in (0, 1], got {top_p}")
        if not self._started:
            raise ServeError(
                "ContinuousEngine.start() (or `with engine:`) first")
        prompt = _np.asarray(prompt_tokens, dtype=_np.int32).ravel()
        if prompt.size < 1:
            raise ServeError("prompt must have at least one token")
        if prompt.size >= self.max_len:
            raise ServeError(
                f"prompt length {prompt.size} >= max_len {self.max_len} "
                f"(one slot page holds prompt + generated tokens)")
        # prompts longer than prefill_window are fine: they stream in
        # window-sized chunks across successive waves (chunked prefill)
        if max_new_tokens < 1:
            raise ServeError("max_new_tokens must be >= 1")
        _fault.inject("serve.enqueue")
        dl = (deadline_ms / 1e3 if deadline_ms is not None
              else self.default_deadline_s)
        if seed is None:
            with self._mlock:
                seed = self._auto_seed
                self._auto_seed += 1
        ctx = _trace.request_root("serve.request")
        req = _GenRequest(prompt, int(max_new_tokens),
                          None if dl is None
                          else time.perf_counter() + dl, ctx,
                          temperature=temperature, top_k=top_k,
                          top_p=top_p, key=_seed_key(seed))
        with self._cv:
            if self._closing:
                # typed split, not one generic ServerClosed: DRAINING means
                # "resident requests still finishing before a restart" and
                # the fleet router re-routes it silently; CLOSED is final
                if self._drain and self._thread.is_alive():
                    raise ReplicaDraining(
                        "engine is draining (finishing resident requests "
                        "before restart); route to another replica")
                raise ServerClosed("engine is closed")
            if len(self._waiting) >= self.max_queue:
                depth = len(self._waiting)
                rejected = True
            else:
                rejected = False
                self._waiting.append(req)
                self._cv.notify()
        if rejected:
            self._count("rejected")
            _trace.flightrec_record(
                "serve.reject", self.name, depth=depth,
                trace_id=ctx.trace_id if ctx else None)
            _trace.flightrec_maybe_dump("serve.overload")
            raise QueueFullError(
                f"waiting queue full ({self.max_queue}); request "
                f"rejected", policy="reject")
        self._count("requests")
        return req.future

    def generate(self, prompt_tokens, max_new_tokens=16, timeout=None,
                 deadline_ms=None, temperature=0.0, top_k=0, top_p=1.0,
                 seed=None):
        """submit() + wait."""
        return self.submit(prompt_tokens, max_new_tokens,
                           deadline_ms=deadline_ms,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, seed=seed).result(timeout=timeout)

    # -- metrics -----------------------------------------------------------
    def _count(self, key, n=1):
        with self._mlock:
            self._counters[key] += n
        stats_key = _ENGINE_TO_SERVE_KEY.get(key)
        if stats_key is not None:
            with _STATS_LOCK:
                SERVE_STATS[stats_key] += n

    def compile_cache_size(self):
        return self.model.compile_cache_size()

    def prefix_hit_count(self):
        """Lifetime prefix-cache hits — the cheap accessor the replica
        heartbeat pong carries (full breakdown in `stats()`)."""
        with self._mlock:
            return self._counters["prefix_hits"]

    def retraces_after_warmup(self):
        """Compiled-program growth since start() — MUST be 0 in steady
        state (-1 when the jax version hides the counter)."""
        if self._warm_cache_size is None or self._warm_cache_size < 0:
            return -1
        now = self.model.compile_cache_size()
        return -1 if now < 0 else now - self._warm_cache_size

    def assert_no_retraces(self):
        r = self.retraces_after_warmup()
        if r > 0:
            raise MXNetError(
                f"continuous engine retraced {r} program(s) after warmup "
                f"— a shape leaked into the compiled step")
        return r

    def memory_plans(self):
        """Predicted device-memory plans of the TWO compiled step
        programs (`mx.inspect.memory.memory_plan` over the prefill and
        decode jits, lowered at the exact warmup shapes via abstract
        avals — no buffers touched, no extra compile in steady state:
        the lowering hits the same jit cache entry the engine replays).
        The KV slab dominates both plans' argument size and is donated,
        so `alias_size` covering ~2x the slab is the zero-copy-update
        evidence."""
        import jax
        import jax.tree_util as jtu
        from ..inspect.memory import memory_plan

        def aval(shape, dtype="int32"):
            return jax.ShapeDtypeStruct(shape, dtype)

        params_avals = jtu.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            self.model.params)
        slab = jax.ShapeDtypeStruct(self.pool.shape, self.pool.dtype)
        if self.pool.quantized:
            pool_aval = (slab, jax.ShapeDtypeStruct(
                self.pool.scale_shape, "float32"))
        else:
            pool_aval = slab
        P, S, W = (self.prefill_lanes, self.pool.max_slots,
                   self.prefill_window)
        prefill = self._prefill_prog.lower(
            params_avals, pool_aval, pool_aval, aval((P, W)), aval((P,)),
            aval((P,)))
        dec_avals = [params_avals, pool_aval, pool_aval, aval((S,)),
                     aval((S,)), aval((S,)), aval((S,), "float32"),
                     aval((S,)), aval((S,), "float32"),
                     aval((S, 2), "uint32")]
        if self.draft_tokens:
            dec_avals.append(aval((S, self.max_len)))
        decode = self._decode_prog.lower(*dec_avals)
        return {
            "prefill": memory_plan(prefill, name=f"{self.name}.prefill"),
            "decode": memory_plan(decode, name=f"{self.name}.decode"),
        }

    def stats(self):
        """Plain-data snapshot: counters, slot occupancy, TTFT/TPOT
        percentiles, decode tokens/s, and the zero-retrace observables."""
        with self._mlock:
            c = dict(self._counters)
            ttft = sorted(self._ttft_ms)
            tpot = sorted(self._tpot_ms)
            e2e = sorted(self._e2e_ms)
            elapsed = time.perf_counter() - self._t0
        out = dict(c)
        out["elapsed_s"] = round(elapsed, 3)
        out["decode_tokens_per_sec"] = round(
            c["decode_tokens"] / elapsed, 2) if elapsed > 0 else 0.0
        out["mean_active_slots"] = round(
            c["active_sum"] / c["decode_iterations"], 3) \
            if c["decode_iterations"] else 0.0
        for nm, vals in (("ttft", ttft), ("tpot", tpot), ("e2e", e2e)):
            for q in (50, 99):
                v = percentile(vals, q)
                out[f"{nm}_p{q}_ms"] = round(v, 3) if v is not None \
                    else None
        out["pool"] = self.pool.stats()
        out["decode_steps"] = self.decode_steps
        out["draft_tokens"] = self.draft_tokens
        if c["draft_accepted"] + c["draft_rejected"] > 0:
            out["draft_acceptance"] = round(
                c["draft_accepted"]
                / (c["draft_accepted"] + c["draft_rejected"]), 4)
        out["prefill_lanes"] = self.prefill_lanes
        out["prefill_window"] = self.prefill_window
        if self._cache is not None:
            out["prefix_block"] = self.prefix_block
            out["prefix_cache"] = self._cache.stats()
            if c["prefix_hits"] + c["prefix_misses"] > 0:
                out["prefix_hit_rate"] = round(
                    c["prefix_hits"]
                    / (c["prefix_hits"] + c["prefix_misses"]), 4)
            if c["prefill_tokens"] + c["prefix_cached_tokens"] > 0:
                # share of prompt tokens served by copy, not compute —
                # the bench's prefill_cached_token_share trend number
                out["prefill_cached_token_share"] = round(
                    c["prefix_cached_tokens"]
                    / (c["prefill_tokens"]
                       + c["prefix_cached_tokens"]), 4)
        out["compile_cache_size"] = self.compile_cache_size()
        out["retraces_after_warmup"] = self.retraces_after_warmup()
        return out

    # -- scheduler ---------------------------------------------------------
    def _loop(self):
        import jax.numpy as jnp
        while True:
            with self._cv:
                while (not self._waiting and not self._running
                       and not self._prefilling and not self._closing):
                    self._cv.wait()
                if self._closing and not self._running \
                        and not self._prefilling \
                        and (not self._drain or not self._waiting):
                    for req in self._waiting:
                        _fail(req, ServerClosed(
                            "engine closed before admission"))
                    self._waiting.clear()
                    return
                admitted, expired = self._admit_locked()
            # expired waiters resolve OUTSIDE self._cv: Future callbacks
            # run inline and may re-enter submit()
            now = time.perf_counter()
            for req in expired:
                self._count("timeouts")
                _trace.flightrec_record(
                    "serve.timeout", self.name,
                    waited_ms=round((now - req.t_submit) * 1e3, 1),
                    trace_id=req.ctx.trace_id if req.ctx else None)
                _fail(req, RequestTimeout(
                    f"deadline expired after "
                    f"{(now - req.t_submit) * 1e3:.1f}ms waiting for a "
                    f"KV slot"))
            if (not admitted and not expired and not self._running
                    and not self._prefilling):
                # waiting requests exist but no slot freed up (something
                # outside the engine holds claims): timed wait, re-check —
                # never a busy spin
                with self._cv:
                    if (self._waiting and not self._running
                            and not self._prefilling):
                        self._cv.wait(timeout=0.005)
                continue
            try:
                # _prefilling is only ever mutated on this thread, so the
                # unlocked read is single-writer safe
                if admitted or self._prefilling:
                    self._run_prefill(admitted, jnp)
                if self._running:
                    self._run_decode(jnp)
            except BaseException as e:
                # a step failure fails the IN-FLIGHT requests, frees
                # their slots, and the engine keeps serving (the PR-3
                # batch-error contract). A RESOURCE_EXHAUSTED step
                # additionally leaves the OOM black box (census + plans
                # + flightrec ring) BEFORE the slab reallocation below
                # rewrites the memory picture.
                mem_on_oom(e, where="serve.continuous")
                err = e if isinstance(e, MXNetError) else ServeError(
                    f"engine step failed: {type(e).__name__}: {e}")
                with self._cv:
                    doomed = (list(self._running.values())
                              + list(self._prefilling.values()))
                    self._running.clear()
                    self._prefilling.clear()
                for req in doomed:
                    if req.entry is not None:
                        self._cache.release(req.entry)
                        req.entry = None
                    if req.slot is not None:
                        self.pool.free(req.slot)
                    _fail(req, err)
                self._count("errors", len(doomed))
                # the step programs DONATE the KV buffers: an exception
                # raised mid-execution (after donation) leaves pool.k/v
                # invalidated — fresh buffers or every later wave dies
                # on 'Array has been deleted'. Every in-flight request
                # was just failed, so zeroed slabs are the correct state.
                self.pool.reallocate()
                if self._canary is not None:
                    # fresh zeroed slabs replaced the poisoned row
                    self._canary.rearm()
                if self._cache is not None:
                    # the reallocation zeroed the slab: every cached
                    # prefix's KV bytes are gone, so the index goes too
                    # (its dedicated rows stay claimed and refill later)
                    self._cache.clear()  # mxlint: disable=lock-shared-mutation -- PrefixCache serializes internally (leaf lock); every ref was just released above

    def _admit_locked(self):
        """Deadline-aware admission (runs under self._cv): drop expired
        waiters from the queue, then grant free slots
        earliest-deadline-first within the prefill token budget.

        A waiting request's cost is its POST-CACHE cost: the tokens the
        next prefill wave will actually process — the uncached suffix,
        capped at one window (longer suffixes stream chunk by chunk). So
        a fully-cached request is near-free, ranks ahead of a cold long
        prompt at an equal deadline, and a request that would bust the
        budget no longer blocks cheaper waiters behind it (`continue`,
        not `break` — the old full-prompt `break` both overbilled cache
        hits and head-of-line-blocked on them). Chunks already streaming
        bill the budget first. The >= 1 admission guarantee when a slot
        is free is unchanged. Returns (admitted, expired); the caller
        resolves expired futures off-lock."""
        now = time.perf_counter()
        expired = [r for r in self._waiting
                   if r.deadline is not None and now > r.deadline]
        if expired:
            dropset = set(id(r) for r in expired)
            self._waiting = deque(r for r in self._waiting  # mxlint: disable=lock-shared-mutation -- _admit_locked runs with self._cv held by its only caller (_loop)
                                  if id(r) not in dropset)
        admitted = []
        budget = self.prefill_budget
        for req in self._prefilling.values():
            budget -= min(self.prefill_window,
                          int(req.prompt.size) - req.prefill_pos)
        free = self.pool.free_count()
        if free and self._waiting:
            costs = {}
            for req in self._waiting:
                mlen = 0
                if self._cache is not None:
                    _, mlen = self._cache.match(req.prompt,
                                                acquire=False)
                costs[id(req)] = min(int(req.prompt.size) - mlen,
                                     self.prefill_window)
            ranked = sorted(
                self._waiting,
                key=lambda r: r.sort_key()[:2] + (costs[id(r)],
                                                  r.t_submit))
            for req in ranked:
                if not free or len(admitted) >= self.prefill_lanes:
                    break
                cost = costs[id(req)]
                if admitted and budget - cost < 0:
                    continue    # over budget; a cheaper waiter may fit
                try:
                    req.slot = self.pool.claim()
                except SlotsFullError:   # raced a test's direct claim
                    break
                if self._cache is not None:
                    # pin the matched prefix for this request's lifetime
                    # (released at retire); eviction can never reclaim
                    # the row while the copy/read is possible
                    entry, mlen = self._cache.match(req.prompt)
                    if entry is not None:
                        req.entry = entry
                        req.cached_len = mlen
                        req.prefill_pos = mlen
                free -= 1
                budget -= cost
                admitted.append(req)
            if admitted:
                dropset = set(id(r) for r in admitted)
                self._waiting = deque(r for r in self._waiting  # mxlint: disable=lock-shared-mutation -- _admit_locked runs with self._cv held by its only caller (_loop)
                                      if id(r) not in dropset)
        for req in admitted:
            self._prefilling[req.slot] = req  # mxlint: disable=lock-shared-mutation -- _admit_locked runs with self._cv held by its only caller (_loop)
        return admitted, expired

    def _run_prefill(self, admitted, jnp):
        """One prefill wave: slab-to-slab KV row copies for the admitted
        prefix-cache hits, the fixed-shape windowed program for lanes
        starting at page offset 0, then ONE chunk dispatch advancing
        EVERY lane with pending suffix/chunk work (admitted hits and
        long prompts mid-stream alike). A request emits its first token
        the wave its prefill completes — `prefill_tokens` bills only
        tokens a program actually processed (suffix-only on a hit)."""
        _fault.inject("serve.execute")
        W = self.prefill_window
        g = self.pool.garbage_row
        t0 = time.perf_counter()
        hits = [r for r in admitted if r.cached_len > 0]
        cold = [r for r in admitted if r.cached_len == 0]
        if hits:
            # memory-bound copy replaces compute-bound prefill: the
            # pinned cache rows land in the claimed slots before this
            # wave's programs run (same thread, same device stream)
            self._dispatch_copy([(r.entry.row, r.slot) for r in hits])
            self._count("prefix_hits", len(hits))
            self._count("prefix_cached_tokens",
                        int(sum(r.cached_len for r in hits)))
        if self._cache is not None and cold:
            self._count("prefix_misses", len(cold))
        n_tokens = 0
        finished = []                        # (req, first token)
        if cold:
            P = self.prefill_lanes
            toks = _np.zeros((P, W), dtype=_np.int32)
            lens = _np.ones((P,), dtype=_np.int32)
            rows = _np.full((P,), g, dtype=_np.int32)
            temps = _np.zeros((P,), dtype=_np.float32)
            tks = _np.zeros((P,), dtype=_np.int32)
            tps = _np.ones((P,), dtype=_np.float32)
            keys = _np.zeros((P, 2), dtype=_np.uint32)
            for i, req in enumerate(cold):
                head = min(int(req.prompt.size), W)
                toks[i, :head] = req.prompt[:head]
                lens[i] = head
                rows[i] = req.slot
                temps[i] = req.temperature
                tks[i] = req.top_k
                tps[i] = req.top_p
                keys[i] = req.key
            kb, vb = self.pool.buffers()
            jlens = jnp.asarray(lens)
            k, v, logits = self._prefill_prog(
                self.model.params, kb, vb,
                jnp.asarray(toks), jlens, jnp.asarray(rows))
            first = _sample_first(logits, jnp.asarray(temps),
                                  jnp.asarray(tks), jnp.asarray(tps),
                                  jnp.asarray(keys), jlens - 1)
            self.pool.swap_buffers(k, v)
            first_host = _np.asarray(first)
            for i, req in enumerate(cold):
                head = min(int(req.prompt.size), W)
                req.prefill_pos = head
                n_tokens += head
                if head == req.prompt.size:
                    finished.append((req, int(first_host[i])))
        # chunk wave: admitted hits prefill their suffix, long prompts
        # mid-stream advance one window — ONE fixed-shape dispatch at
        # pool width; lanes with no chunk work scatter into garbage
        with self._cv:
            pre = [self._prefilling[s] for s in sorted(self._prefilling)]
        coldset = set(id(r) for r in cold)
        chunkers = [r for r in pre
                    if id(r) not in coldset
                    and r.prefill_pos < int(r.prompt.size)]
        if chunkers:
            S = self.pool.max_slots
            ctoks = _np.zeros((S, W), dtype=_np.int32)
            offs = _np.zeros((S,), dtype=_np.int32)
            nval = _np.zeros((S,), dtype=_np.int32)
            temps = _np.zeros((S,), dtype=_np.float32)
            tks = _np.zeros((S,), dtype=_np.int32)
            tps = _np.ones((S,), dtype=_np.float32)
            keys = _np.zeros((S, 2), dtype=_np.uint32)
            fold = _np.zeros((S,), dtype=_np.int32)
            for req in chunkers:
                s = req.slot
                n = min(W, int(req.prompt.size) - req.prefill_pos)
                ctoks[s, :n] = req.prompt[req.prefill_pos:
                                          req.prefill_pos + n]
                offs[s] = req.prefill_pos
                nval[s] = n
                temps[s] = req.temperature
                tks[s] = req.top_k
                tps[s] = req.top_p
                keys[s] = req.key
                fold[s] = int(req.prompt.size) - 1
            # smallest warmed extent covering the furthest lane: the
            # wave's attention read scales with streamed progress
            need = max(int(offs[r.slot]) + int(nval[r.slot])
                       for r in chunkers)
            ext = next(x for x in self._chunk_extents if x >= need)
            kb, vb = self.pool.buffers()
            k, v, logits = self._chunk_progs[ext](
                self.model.params, kb, vb, jnp.asarray(ctoks),
                jnp.asarray(offs), jnp.asarray(nval))
            first = _sample_first(logits, jnp.asarray(temps),
                                  jnp.asarray(tks), jnp.asarray(tps),
                                  jnp.asarray(keys), jnp.asarray(fold))
            self.pool.swap_buffers(k, v)
            first_host = _np.asarray(first)
            for req in chunkers:
                n = int(nval[req.slot])
                req.prefill_pos += n
                n_tokens += n
                if req.prefill_pos == int(req.prompt.size):
                    finished.append((req, int(first_host[req.slot])))
        now = time.perf_counter()
        if admitted:
            self._count("admitted", len(admitted))
        if cold or chunkers:
            self._count("prefill_batches")
        if n_tokens:
            self._count("prefill_tokens", n_tokens)
        n_sampled = sum(1 for r, _ in finished if r.temperature > 0)
        if n_sampled:
            self._count("sampled_tokens", n_sampled)
        prof = _profiler_on()
        done = []
        for req, tok in finished:
            req.cache_len = int(req.prompt.size)
            req.generated.append(tok)
            req.t_first = req.t_last = now
            with self._mlock:
                self._ttft_ms.append((now - req.t_submit) * 1e3)
            if req.ctx is not None and prof:
                # admission -> first token, child of the request root:
                # iteration 0 of the request's one trace
                record_span("serve.prefill", (now - req.t_submit) * 1e6,
                            ts_us=req.t_submit * 1e6, cat="serve",
                            ctx=_trace.child_context(req.ctx,
                                                     "serve.prefill"),
                            prompt_tokens=req.prompt.size,
                            cached_tokens=req.cached_len,
                            slot=req.slot)
            if self._finished(req):
                done.append(req)
        with self._cv:
            for req, _ in finished:
                self._prefilling.pop(req.slot, None)
                self._running[req.slot] = req
        if _trace.enabled() and _trace.collector_active():
            record_span("serve.prefill_batch", (now - t0) * 1e6,
                        ts_us=t0 * 1e6, cat="serve",
                        requests=len(admitted), tokens=n_tokens)
        self._retire(done)

    def _dispatch_copy(self, pairs):
        """ONE fixed-shape donated gather program copies whole KV slot
        rows slab-to-slab: cache row -> claimed slot at admission,
        retiring slot -> cache row at publish. Idle lanes copy the
        garbage row onto itself."""
        import jax.numpy as jnp
        g = self.pool.garbage_row
        src = _np.full((self.prefill_lanes,), g, dtype=_np.int32)
        dst = _np.full((self.prefill_lanes,), g, dtype=_np.int32)
        for i, (s, d) in enumerate(pairs):
            src[i] = s
            dst[i] = d
        kb, vb = self.pool.buffers()
        k, v = self._copy_prog(kb, vb, jnp.asarray(src),
                               jnp.asarray(dst))
        self.pool.swap_buffers(k, v)

    def _run_decode(self, jnp):
        """ONE decode wave: every active slot advances up to
        `decode_steps` tokens (times up to `draft_tokens + 1` when
        speculating) through the compiled multi-step program. Lanes are
        ALL pool rows (request slots, mid-prefill slots, and prefix-cache
        rows alike) so lane index == slab row; non-decoding lanes are
        inactive and scatter into the garbage row."""
        S = self.pool.max_slots
        draft = self.draft_tokens
        toks = _np.zeros((S,), dtype=_np.int32)
        lens = _np.zeros((S,), dtype=_np.int32)
        left = _np.zeros((S,), dtype=_np.int32)
        temps = _np.zeros((S,), dtype=_np.float32)
        tks = _np.zeros((S,), dtype=_np.int32)
        tps = _np.ones((S,), dtype=_np.float32)
        keys = _np.zeros((S, 2), dtype=_np.uint32)
        buf = (_np.zeros((S, self.max_len), dtype=_np.int32)
               if draft else None)
        with self._cv:
            running = dict(self._running)
        for slot, req in running.items():
            toks[slot] = req.generated[-1]
            lens[slot] = req.cache_len
            # this wave's per-lane budget: what the request still wants,
            # capped by its page space. The cap mirrors _finished's
            # `cache_len + 1 >= max_len` stop: the K=1 engine (and the
            # reference) emit their last token FROM state max_len - 2,
            # so a multi-step wave may advance cache_len at most to
            # max_len - 1 — not max_len, which would emit one extra
            # token and break the K-invariance contract
            left[slot] = min(req.max_new - len(req.generated),
                             self.max_len - 1 - req.cache_len)
            temps[slot] = req.temperature
            tks[slot] = req.top_k
            tps[slot] = req.top_p
            keys[slot] = req.key
            if draft:
                # the draft source: token history = prompt + generated,
                # exactly cache_len + 1 valid entries (tail not yet in KV)
                plen = req.prompt.size
                buf[slot, :plen] = req.prompt
                buf[slot, plen:plen + len(req.generated)] = req.generated
        t0 = time.perf_counter()
        kb, vb = self.pool.buffers()
        args = [self.model.params, kb, vb, jnp.asarray(toks),
                jnp.asarray(lens), jnp.asarray(left),
                jnp.asarray(temps), jnp.asarray(tks), jnp.asarray(tps),
                jnp.asarray(keys)]
        if draft:
            k, v, blocks, n_emits, emitted, acc, rej = \
                self._decode_prog(*args, jnp.asarray(buf))
            blocks_host = _np.asarray(blocks)   # (steps, S, draft+1)
            nem_host = _np.asarray(n_emits)     # (steps, S)
        else:
            k, v, out_toks, emitted = self._decode_prog(*args)
            out_host = _np.asarray(out_toks)    # (decode_steps, S)
        self.pool.swap_buffers(k, v)
        if self._canary is not None:
            self._canary.check(where="serve.decode")
        _sanitize.poll(where="serve.decode")
        emitted_host = _np.asarray(emitted)
        now = time.perf_counter()
        n_active = len(running)
        n_tokens = 0
        n_sampled = 0
        done = []
        for slot, req in running.items():
            n_new = int(emitted_host[slot])
            if n_new > 0:
                if draft:
                    for i in range(nem_host.shape[0]):
                        m = int(nem_host[i, slot])
                        req.generated.extend(
                            int(t) for t in blocks_host[i, slot, :m])
                else:
                    req.generated.extend(
                        int(t) for t in out_host[:n_new, slot])
                req.cache_len += n_new
                req.t_last = now
                n_tokens += n_new
                if req.temperature > 0:
                    n_sampled += n_new
            if self._finished(req):
                done.append(req)
        self._count("decode_iterations")
        self._count("decode_tokens", n_tokens)
        self._count("active_sum", n_active)
        if n_sampled:
            self._count("sampled_tokens", n_sampled)
        if draft:
            self._count("draft_accepted", int(_np.asarray(acc).sum()))
            self._count("draft_rejected", int(_np.asarray(rej).sum()))
        if _trace.enabled() and _trace.collector_active():
            record_span("serve.decode_batch", (now - t0) * 1e6,
                        ts_us=t0 * 1e6, cat="serve", active=n_active,
                        tokens=n_tokens, steps=self.decode_steps)
        self._retire(done)

    def _finished(self, req):
        if len(req.generated) >= req.max_new:
            return True
        if self.eos_id is not None and req.generated[-1] == self.eos_id:
            return True
        # page full: the NEXT decode would write past the slot
        return req.cache_len + 1 >= self.max_len

    def _retire(self, done):
        """Free slots and resolve futures; one request's whole life —
        prefill + N decode iterations — closes as ONE trace here."""
        if not done:
            return
        prof = _profiler_on()
        for req in done:
            with self._cv:
                self._running.pop(req.slot, None)
            if self._cache is not None:
                if req.entry is not None:
                    # the hit path never publishes: its suffix KV came
                    # from the chunk program, and the cache must stay
                    # canonical-provenance (windowed head + chunks) so
                    # every later hit is bit-identical to a cold build
                    self._cache.release(req.entry)  # mxlint: disable=lock-shared-mutation -- PrefixCache serializes internally (leaf lock)
                    req.entry = None
                elif self.prefix_cache_insert:
                    row = self._cache.insert(req.prompt)  # mxlint: disable=lock-shared-mutation -- PrefixCache serializes internally (leaf lock)
                    if row is not None:
                        # publish BEFORE free: the copy is dispatched on
                        # this thread ahead of any wave that could
                        # rewrite the retiring slot's row
                        self._dispatch_copy([(req.slot, row)])
            self.pool.free(req.slot)
            out = _np.asarray(req.generated, dtype=_np.int32)
            if self.eos_id is not None:
                hits = _np.nonzero(out == self.eos_id)[0]
                if hits.size:
                    out = out[:int(hits[0]) + 1]
            if req.future.set_running_or_notify_cancel():
                req.future.set_result(out)
            now = time.perf_counter()
            total_ms = (now - req.t_submit) * 1e3
            with self._mlock:
                self._e2e_ms.append(total_ms)
                if len(req.generated) > 1 and req.t_first is not None:
                    self._tpot_ms.append(
                        (req.t_last - req.t_first) * 1e3
                        / (len(req.generated) - 1))
            self._count("replies")
            self._count("retired")
            if req.ctx is not None and prof:
                if req.t_first is not None and req.t_last > req.t_first:
                    # first -> last token: the N decode iterations as one
                    # node (per-iteration spans at thousands of tokens/s
                    # would swamp the trace; the batch lane has them)
                    record_span("serve.decode",
                                (req.t_last - req.t_first) * 1e6,
                                ts_us=req.t_first * 1e6, cat="serve",
                                ctx=_trace.child_context(req.ctx,
                                                         "serve.decode"),
                                tokens=len(req.generated), slot=req.slot)
                record_span("serve.request", total_ms * 1e3,
                            ts_us=req.t_submit * 1e6, cat="serve",
                            ctx=req.ctx, tokens=len(req.generated))


# engine counter -> process-wide SERVE_STATS key (profiler.serve_stats()):
# the decode_* family is the continuous-batching analog of the PR-3 rows
_ENGINE_TO_SERVE_KEY = {
    "requests": "requests", "replies": "replies",
    "rejected": "rejected", "timeouts": "timeouts", "errors": "errors",
    "programs_compiled": "programs_compiled",
    "decode_iterations": "decode_iterations",
    "decode_tokens": "decode_tokens",
    "prefill_tokens": "decode_prefill_tokens",
    "admitted": "decode_admitted",
    "retired": "decode_retired",
    "sampled_tokens": "decode_sampled_tokens",
    "draft_accepted": "decode_draft_accepted",
    "draft_rejected": "decode_draft_rejected",
}
