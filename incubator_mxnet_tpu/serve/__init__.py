"""mx.serve — dynamic-batching inference server over exported artifacts.

The ROADMAP north star serves "heavy traffic from millions of users"; the
deploy layer (deploy.py, ≙ the reference's c_predict_api.h predictor) stops
at single-shot `ExportedModel.run`. This subsystem adds the request-level
layer above it:

  serve.Server          thread-safe bounded queue + dynamic batcher:
                        concurrent requests coalesce into padded
                        power-of-two batch buckets, execute through one
                        compiled program per bucket, and split back to
                        per-request futures
  serve.BucketedModel   bucket -> ExportedModel map (+ `export_block` to
                        produce the per-bucket artifact set from one block)
  serve.CallableModel   the same contract over an in-process jax callable
  serve.stats()         process-wide serving counters (also
                        `profiler.serve_stats()`); per-server metrics —
                        requests/s, p50/p95/p99 latency, batch-occupancy
                        histogram, queue depth, request-timeline
                        queue-wait vs execute split — via `Server.stats()`
  serve.metrics_text()  Prometheus text of the telemetry registry;
                        `Server.metrics_text()` appends per-server gauges
                        and `serve.start_metrics_server(port)` (or
                        MXNET_METRICS_PORT at `Server.start()`) serves it
                        at `/metrics`

Autoregressive (stateful) serving — continuous batching (ISSUE 14):

  serve.ContinuousEngine  iteration-level batching decode engine:
                        requests admit/retire PER MODEL ITERATION over a
                        slotted KV-cache pool; two fixed-shape compiled
                        programs (prefill + multi-step decode) serve
                        every mixed batch — zero retraces after warmup;
                        deadline-aware slot grants (SLO-aware admission)
  serve.KVCachePool     preallocated `(max_slots+1, layers, max_len,
                        heads, head_dim)` KV slab + claim/free slots;
                        typed `SlotsFullError` on exhaustion
  serve.CachedDecoder   the bundled cached-KV transformer decoder model
                        (greedy, deterministic) the engine drives; see
                        docs/SERVING.md "Continuous batching"

Overload behavior is explicit, not emergent: admission control bounds the
queue (`MXNET_SERVE_MAX_QUEUE`), the overload policy picks reject-newest
or shed-oldest (`MXNET_SERVE_OVERLOAD_POLICY`), per-request deadlines fail
fast with typed errors (`MXNET_SERVE_DEADLINE_MS`), and the
`serve.enqueue` / `serve.execute` / `serve.reply` fault points make every
degraded path deterministically testable via `MXNET_FAULT_SPEC`. See
docs/SERVING.md.
"""
from __future__ import annotations

from ..base import _register_env
from ..telemetry import metrics_text, start_metrics_server
from .batcher import (ServeError, QueueFullError, RequestTimeout,
                      ServerClosed, ReplicaDraining, BucketedModel,
                      CallableModel, Server, pick_bucket)
from .metrics import SERVE_STATS, ServeMetrics, serve_stats as stats
from .kv_pool import (KVCachePool, SlotsFullError, KVPOOL_STATS,
                      kvpool_stats)
from .prefix_cache import (PrefixCache, PrefixCacheError, PREFIX_STATS,
                           prefix_stats)
from .continuous import (ContinuousEngine, CachedDecoder, DecoderConfig,
                         init_decoder_params)
from .fleet import (Fleet, FleetError, ReplicaDied, FLEET_STATS,
                    fleet_stats)

__all__ = [
    "Server", "BucketedModel", "CallableModel", "pick_bucket",
    "ServeError", "QueueFullError", "RequestTimeout", "ServerClosed",
    "ServeMetrics", "SERVE_STATS", "stats",
    "metrics_text", "start_metrics_server",
    # continuous (iteration-level) batching
    "ContinuousEngine", "CachedDecoder", "DecoderConfig",
    "init_decoder_params", "KVCachePool", "SlotsFullError",
    "KVPOOL_STATS", "kvpool_stats",
    # shared-prefix KV cache
    "PrefixCache", "PrefixCacheError", "PREFIX_STATS", "prefix_stats",
    # multi-replica serving fleet
    "Fleet", "FleetError", "ReplicaDied", "ReplicaDraining",
    "FLEET_STATS", "fleet_stats",
]

_register_env("MXNET_SERVE_MAX_QUEUE", int, 256,
              "Bound on queued inference requests (admission control)")
_register_env("MXNET_SERVE_BATCH_TIMEOUT_MS", float, 2.0,
              "Max wait to fill a batch after its first request")
_register_env("MXNET_SERVE_DEADLINE_MS", float, None,
              "Default per-request queue deadline (unset = none)")
_register_env("MXNET_SERVE_OVERLOAD_POLICY", str, "reject",
              "Queue-full behavior: 'reject' (newest) or 'shed' (oldest)")
_register_env("MXNET_SERVE_MAX_SLOTS", int, 8,
              "KV-cache slots in the continuous-batching engine = max "
              "concurrently-decoding requests (serve.KVCachePool)")
_register_env("MXNET_SERVE_PREFILL_BUDGET", int, 256,
              "Max prompt tokens prefilled per engine iteration "
              "(bounds prefill's added latency on in-flight decode)")
_register_env("MXNET_SERVE_DECODE_STEPS", int, 4,
              "Decode micro-iterations per compiled dispatch in the "
              "continuous engine (host round-trip amortization)")
_register_env("MXNET_SERVE_PREFILL_LANES", int, None,
              "Fixed lane count of the prefill program (unset = "
              "min(max_slots, 8)); sized to the admission rate")
_register_env("MXNET_SERVE_KV_DTYPE", str, None,
              "KV pool storage dtype ('int8' = quantized codes + "
              "scales; unset = model dtype)")
_register_env("MXNET_SERVE_PREFIX_BLOCK", int, 16,
              "Shared-prefix cache granularity in tokens (prefixes "
              "cache and match on whole blocks only)")
_register_env("MXNET_SERVE_PREFIX_CACHE_SLOTS", int, 0,
              "Dedicated KV-pool rows holding shared-prefix KV for "
              "reuse across requests (0 = prefix cache off)")
_register_env("MXNET_SERVE_PREFIX_CACHE_INSERT", int, 1,
              "Publish a retiring request's own prompt prefix back "
              "into the shared-prefix cache (0 = read-only cache)")
_register_env("MXNET_FLEET_REPLICAS", int, 2,
              "Replica worker processes a serve.Fleet spawns")
_register_env("MXNET_FLEET_HEARTBEAT_MS", float, 500.0,
              "Fleet heartbeat interval; a replica missing "
              "`heartbeat_misses` consecutive beats is declared hung")
_register_env("MXNET_FLEET_RETRY_BUDGET", int, 2,
              "Failover retries per request before the original replica "
              "error surfaces to the client")
_register_env("MXNET_FLEET_DRAIN_TIMEOUT_MS", float, 30000.0,
              "Max wait for a draining replica to finish its resident "
              "requests before the swap hard-stops it (survivors absorb "
              "its in-flight via failover)")
