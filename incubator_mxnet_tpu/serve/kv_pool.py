"""Slotted KV-cache pool for the continuous-batching decode engine.

vLLM-style slot memory rebuilt JAX-native (PAPER.md's CachedOp/imperative
survey: state must live as plain sharded buffers a single compiled program
reads and writes, never as per-request Python objects the tracer sees):

  * ONE pair of fixed-shape device buffers carved at startup —
    `k`/`v` of shape `(max_slots + 1, layers, max_len, heads, head_dim)`.
    Slot granularity: each admitted request claims one row (its whole
    `max_len` page); row `max_slots` is the GARBAGE ROW, a write target
    for the pad lanes of a fixed-shape scatter (a prefill program always
    writes `P` rows — inactive lanes land in garbage instead of branching,
    which would retrace).
  * Claim/free is pure host bookkeeping under one lock: the buffers never
    reallocate, so join/leave can never change a compiled program's
    shapes — the zero-retrace contract of `serve.continuous`.
  * Stale bytes are a CORRECTNESS boundary, not a hygiene one: a freed
    slot's cache rows are NOT zeroed (that would cost a device write per
    retire). Instead the attention masks in `serve.continuous` clamp
    every read to `[0, cur_len]` of the CURRENT request, so a reused slot
    cannot read its predecessor's cache. `poison()` exists so tests can
    prove that: fill the slab with a sentinel, run a request through a
    reused slot, and check the output matches a fresh-pool reference
    bit-for-bit (tests/test_continuous.py).
  * `dtype="int8"` opts a pool into QUANTIZED KV storage: int8 slabs plus
    float32 per-(slot, layer, position) scale buffers (`k_scale` /
    `v_scale`). Each written position is quantized by its absmax over
    (heads, head_dim) — a position's scale is final the moment its KV is
    written, so slot reuse never requantizes and a stale scale is exactly
    as unreachable as a stale KV row (the same `[0, cur_len]` mask
    governs both; `poison()` poisons the scales too so tests can prove
    it). ~3-4x more slots per HBM byte (`slots_per_gb()`), the number
    the memory bench commits.

Exhaustion is typed: `claim()` past capacity raises `SlotsFullError`
(a `ServeError`), the admission signal the engine's deadline-aware
scheduler acts on instead of blocking.

Counters: `KVPOOL_STATS` ("kvpool" stats group — `profiler`-style surface
via `serve.kv_pool.kvpool_stats()`; catalog in docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import threading

from ..base import get_env
from ..telemetry.registry import REGISTRY, stats_group as _stats_group
from .batcher import ServeError

__all__ = ["SlotsFullError", "KVCachePool", "KVPOOL_STATS", "kvpool_stats"]


class SlotsFullError(ServeError):
    """`claim()` found no free KV slot: the pool is at capacity. The
    engine's admission loop treats this as "stay queued" (and fails the
    request only when its deadline expires); direct callers get a typed,
    actionable error instead of an index out of range."""


# Guards every KVPOOL_STATS mutation AND the free-list bookkeeping of all
# pools (claim/free are rare, request-scale events — one shared lock keeps
# snapshot+reset atomic exactly like serve/metrics.py's _STATS_LOCK).
_STATS_LOCK = threading.Lock()

KVPOOL_STATS = _stats_group("kvpool", {
    "claims": 0,       # slots successfully claimed
    "frees": 0,        # slots returned to the pool
    "exhausted": 0,    # claim() attempts that found no free slot
}, lock=_STATS_LOCK,
    help="KV-cache slot-pool counters (serve.kv_pool.kvpool_stats)")


def kvpool_stats(reset=False):
    """Process-wide KV-pool counter snapshot (atomic with the optional
    reset, the serve_stats() contract)."""
    return KVPOOL_STATS.snapshot(reset=reset)


# the single biggest planned allocation in serving, previously invisible:
# set at every carve (allocate/reallocate/poison) so dashboards and the
# memory bench see the slab without holding a pool reference. Level, not
# flow — survives snapshot(reset=True). With several pools alive it holds
# the most recent carve; per-pool numbers live on pool.stats().
_SLAB_GAUGE = REGISTRY.gauge(
    "kvpool.slab_bytes",
    help="bytes of the most recently carved KV slab pair (k+v, incl. "
         "the garbage row)")


def _note_slab(pool):
    """Stamp the gauge and attribute the slab buffers to the census
    owner `kv_pool` (mx.inspect.memory). Attribution must never be able
    to break serving — failures are swallowed."""
    try:
        _SLAB_GAUGE.set(pool.nbytes())
        from ..inspect import memory as _mem
        bufs = (pool.k, pool.v)
        if pool.quantized:
            bufs = bufs + (pool.k_scale, pool.v_scale)
        _mem.register(bufs, owner="kv_pool")
    except Exception:
        pass


class KVCachePool:
    """Preallocated KV-cache slab + slot claim/free bookkeeping.

    ::

        pool = KVCachePool(max_slots=8, layers=2, max_len=128,
                           heads=4, head_dim=16)
        slot = pool.claim()          # 0 <= slot < max_slots
        ...                          # compiled steps read/write pool.k/v
        pool.free(slot)

    The device buffers `k` and `v` are plain jax arrays the engine's
    donated step programs consume and replace (`swap_buffers`), so
    updates are in-place on accelerators. `garbage_row == max_slots` is
    the scatter target for inactive lanes.

    Thread safety: `claim`/`free`/`free_count`/`in_use` take the module
    lock (the engine claims on its scheduler thread while tests hammer
    from many); buffer access is single-writer by the engine contract
    (exactly one scheduler thread runs the compiled steps).
    """

    def __init__(self, max_slots=None, *, layers, max_len, heads,
                 head_dim, dtype="float32", allocate=True):
        self.max_slots = int(
            max_slots if max_slots is not None
            else get_env("MXNET_SERVE_MAX_SLOTS", 8, typ=int))
        if self.max_slots < 1:
            raise ServeError("KVCachePool needs max_slots >= 1")
        self.layers = int(layers)
        self.max_len = int(max_len)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.dtype = str(dtype)
        # int8 = quantized storage: slabs hold int8 codes, the paired
        # k_scale/v_scale buffers hold one f32 dequant factor per
        # written (slot, layer, position)
        self.quantized = self.dtype == "int8"
        # LIFO free list: a just-freed slot is re-claimed first, which is
        # exactly what the poison-fill reuse test needs to exercise
        self._free = list(range(self.max_slots - 1, -1, -1))
        self._claimed = set()
        self.k = self.v = None
        self.k_scale = self.v_scale = None
        if allocate:
            self._allocate()

    # -- buffers -----------------------------------------------------------
    @property
    def shape(self):
        """Slab shape incl. the garbage row (the compiled-program view)."""
        return (self.max_slots + 1, self.layers, self.max_len,
                self.heads, self.head_dim)

    @property
    def scale_shape(self):
        """Per-position dequant-scale buffer shape (quantized pools)."""
        return (self.max_slots + 1, self.layers, self.max_len)

    @property
    def garbage_row(self):
        """Scatter target for a fixed-shape step's inactive lanes."""
        return self.max_slots

    def _allocate(self):
        import jax.numpy as jnp
        self.k = jnp.zeros(self.shape, dtype=self.dtype)
        self.v = jnp.zeros(self.shape, dtype=self.dtype)
        if self.quantized:
            self.k_scale = jnp.zeros(self.scale_shape, dtype="float32")
            self.v_scale = jnp.zeros(self.scale_shape, dtype="float32")
        _note_slab(self)

    def buffers(self):
        """The (k, v) arguments the step programs take: plain slabs, or
        `(slab, scales)` pytree pairs for a quantized pool (the program
        variant is chosen by `quantized` at build time, so the pytree
        STRUCTURE is a trace-time constant)."""
        if self.quantized:
            return (self.k, self.k_scale), (self.v, self.v_scale)
        return self.k, self.v

    def reallocate(self):
        """Replace the slab with fresh zeroed buffers. The engine's
        step-failure path needs this: the compiled programs DONATE the
        buffers, so an exception raised mid-execution leaves `k`/`v`
        pointing at already-invalidated arrays — without reallocation
        every later wave would die on 'Array has been deleted'."""
        self._allocate()

    def nbytes(self):
        """Host-visible size of the slab pair incl. the quantized pools'
        scale buffers (capacity-planning aid)."""
        import numpy as _np
        import ml_dtypes  # noqa: F401  (bf16 dtype string resolution)
        try:
            itemsize = _np.dtype(self.dtype).itemsize
        except TypeError:
            itemsize = 2      # bfloat16
        n = 1
        for d in self.shape:
            n *= d
        total = 2 * n * itemsize
        if self.quantized:
            s = 1
            for d in self.scale_shape:
                s *= d
            total += 2 * s * 4
        return total

    def bytes_per_slot(self):
        """Marginal device bytes one slot row costs (k + v pages, plus
        scale rows on a quantized pool)."""
        page = 2 * self.layers * self.max_len * self.heads * self.head_dim
        import numpy as _np
        try:
            itemsize = _np.dtype(self.dtype).itemsize
        except TypeError:
            itemsize = 2
        per = page * itemsize
        if self.quantized:
            per += 2 * self.layers * self.max_len * 4
        return per

    def slots_per_gb(self):
        """KV slots one GiB of device memory buys at this pool's shape —
        the capacity number the memory bench trends (int8 pools fit ~3-4x
        the slots of float32 at the same (layers, max_len, heads, dim))."""
        return round((1 << 30) / self.bytes_per_slot(), 2)

    def swap_buffers(self, k, v):
        """Install the step program's output buffers (the donated-update
        swap idiom: the old arrays were consumed by donation). Quantized
        pools take the `(slab, scales)` pairs `buffers()` hands out."""
        if self.quantized:
            (self.k, self.k_scale), (self.v, self.v_scale) = k, v
        else:
            self.k, self.v = k, v
        _note_slab(self)

    def poison(self, value=1e9):
        """Overwrite the WHOLE slab with a sentinel. Test hook for the
        slot-reuse isolation contract: after poisoning, any read that
        escapes the `[0, cur_len]` mask shows up as the sentinel in the
        output. On a quantized pool the codes are set to 1 and the SCALES
        to `value`, so a stale-scale read is as loud as a stale-code one.
        Never called on the serving path."""
        import jax.numpy as jnp
        if self.quantized:
            self.k = jnp.full(self.shape, 1, dtype=self.dtype)
            self.v = jnp.full(self.shape, 1, dtype=self.dtype)
            self.k_scale = jnp.full(self.scale_shape, value,
                                    dtype="float32")
            self.v_scale = jnp.full(self.scale_shape, value,
                                    dtype="float32")
        else:
            self.k = jnp.full(self.shape, value, dtype=self.dtype)
            self.v = jnp.full(self.shape, value, dtype=self.dtype)
        _note_slab(self)

    def poison_slot(self, slot, value=1e9):
        """`poison()` at slot granularity: overwrite ONE row (both slabs,
        plus its scale rows on a quantized pool) with the sentinel,
        leaving every other slot's live KV intact. Test hook for the
        shared-prefix isolation contract: poison a FREED prefix-cache
        row, keep serving, and any tenant that could still read it shows
        the sentinel. Never called on the serving path."""
        import jax.numpy as jnp
        slot = int(slot)
        if not 0 <= slot <= self.max_slots:
            raise ServeError(
                f"slot {slot} outside [0, {self.max_slots}]")
        code = 1 if self.quantized else value
        self.k = self.k.at[slot].set(jnp.asarray(code, dtype=self.dtype))
        self.v = self.v.at[slot].set(jnp.asarray(code, dtype=self.dtype))
        if self.quantized:
            self.k_scale = self.k_scale.at[slot].set(value)
            self.v_scale = self.v_scale.at[slot].set(value)
        _note_slab(self)

    # -- slot bookkeeping --------------------------------------------------
    def claim(self):
        """Take a free slot (int in [0, max_slots)); raises SlotsFullError
        when the pool is exhausted."""
        with _STATS_LOCK:
            if not self._free:
                KVPOOL_STATS["exhausted"] += 1
                raise SlotsFullError(
                    f"all {self.max_slots} KV slots are claimed")
            slot = self._free.pop()
            self._claimed.add(slot)
            KVPOOL_STATS["claims"] += 1
            return slot

    def free(self, slot):
        """Return a slot. Double-free (or freeing an unclaimed slot) is a
        bookkeeping bug upstream and raises ServeError rather than
        silently handing one slot to two requests."""
        slot = int(slot)
        with _STATS_LOCK:
            if slot not in self._claimed:
                raise ServeError(
                    f"KV slot {slot} is not claimed (double free?)")
            self._claimed.remove(slot)
            self._free.append(slot)
            KVPOOL_STATS["frees"] += 1

    def free_count(self):
        with _STATS_LOCK:
            return len(self._free)

    def in_use(self):
        with _STATS_LOCK:
            return sorted(self._claimed)

    def stats(self):
        """Plain-data snapshot of this pool's occupancy."""
        with _STATS_LOCK:
            used = len(self._claimed)
        return {"max_slots": self.max_slots, "in_use": used,
                "free": self.max_slots - used,
                "dtype": self.dtype,
                "slots_per_gb": self.slots_per_gb(),
                "slab_bytes": self.nbytes() if self.k is not None else 0}
