"""Ref-counted shared-prefix KV cache for the continuous-batching engine.

The RadixAttention / prompt-cache idea (vLLM/SGLang line of work) rebuilt
for the fixed-shape two-program design of `serve.continuous`: thousands of
requests sharing one system prompt should pay the O(prefix²) prefill
attention ONCE, and every later request should get that KV back with a
memory-bound slab-to-slab row copy instead of a compute-bound prefill.

Design constraints (docs/SERVING.md "Prefix caching & chunked prefill"):

  * Block quantization. Prefixes are cached at `prefix_block`-token
    granularity: an entry always holds `n_blocks * block` tokens, and a
    lookup can only hit on whole blocks. This keeps the index small (one
    hash per block boundary, not per token) and makes the published unit
    deterministic.
  * Rolling hash + MANDATORY verify. The index key is a polynomial
    rolling hash of the block-quantized token prefix. A hash hit is
    NEVER trusted: the candidate entry's stored tokens are compared
    against the actual prompt block before any KV is reused. A mismatch
    is a collision — counted in `prefix.collisions` — and the lookup
    falls through to shorter prefixes / recompute. Collisions can cost
    speed, never correctness (no "wrong KV" failure mode exists).
  * Ref-counted pinning. `match(acquire=True)` increments the winning
    entry's refcount; the engine holds that ref for the lifetime of the
    request reading the entry's pool row and `release()`s it at retire.
    LRU eviction can NEVER reclaim an entry whose refcount > 0 — a slab
    row is reused only when no in-flight request can read it. Releasing
    an unheld entry is a typed `PrefixCacheError` (the double-free
    analogue of the pool's "double free?" guard).
  * Dedicated slots. The cache owns pool rows claimed once at engine
    startup (they never mix with request slots), so admission capacity
    and cache capacity are separate knobs (`max_slots` vs
    `prefix_cache_slots`) and `SlotsFullError` semantics are unchanged.

Device KV movement is the ENGINE's job (one fixed-shape donated gather
program, `CachedDecoder.copy_program()`); this module is pure host
bookkeeping under one lock, exactly like `serve.kv_pool`.

Counters: `PREFIX_STATS` ("prefix" stats group —
`serve.prefix_cache.prefix_stats()`; catalog in docs/OBSERVABILITY.md).

Test hook: assigning `cache._hash_override = fn` replaces the rolling
hash with `fn(tokens) -> int` for BOTH insert and lookup, letting tests
force two distinct token blocks onto one hash value and prove the
verify-on-hit path rejects the collision (tests/test_prefix_cache.py).
"""
from __future__ import annotations

import threading

import numpy as np

from ..telemetry.registry import stats_group as _stats_group
from .batcher import ServeError

__all__ = ["PrefixCacheError", "PrefixCache", "PREFIX_STATS",
           "prefix_stats"]


class PrefixCacheError(ServeError):
    """Prefix-cache lifecycle misuse: releasing an entry that holds no
    reference (double release), or clearing a cache with live refs."""


# Guards every PREFIX_STATS mutation (same one-shared-lock pattern as
# serve/kv_pool.py: events are request-scale, snapshot+reset stays atomic).
_STATS_LOCK = threading.Lock()

PREFIX_STATS = _stats_group("prefix", {
    "hits": 0,           # acquiring lookups that reused a cached prefix
    "misses": 0,         # acquiring lookups that found nothing reusable
    "cached_tokens": 0,  # prompt tokens served from cache across all hits
    "evictions": 0,      # LRU-evicted entries (refcount 0 only, ever)
    "collisions": 0,     # hash hits rejected by the token-block verify
}, lock=_STATS_LOCK,
    help="Shared-prefix KV-cache counters (serve.prefix_cache."
         "prefix_stats)")


def prefix_stats(reset=False):
    """Process-wide prefix-cache counter snapshot (atomic with the
    optional reset, the serve_stats() contract)."""
    return PREFIX_STATS.snapshot(reset=reset)


# Polynomial rolling hash over token ids. The modulus is the Mersenne
# prime 2^61-1 (cheap host arithmetic, negligible accidental-collision
# rate); `+ 1` keeps leading token id 0 from hashing like an empty block.
_HASH_MOD = (1 << 61) - 1
_HASH_BASE = 1_000_003


def rolling_hash(tokens):
    """Hash of a full token sequence — the key `PrefixCache` indexes by
    and the fleet router's affinity key (serve/fleet.py), so one prefix
    maps to one replica fleet-wide without shipping token arrays around."""
    h = 0
    for t in np.asarray(tokens).reshape(-1):
        h = (h * _HASH_BASE + int(t) + 1) % _HASH_MOD
    return h


class _PrefixEntry:
    """One cached prefix: its verified tokens, the pool row holding its
    KV, and the pin/LRU bookkeeping. Host-only; never crosses into jit."""

    __slots__ = ("tokens", "row", "refs", "tick", "hash")

    def __init__(self, tokens, row, hash_):
        self.tokens = tokens      # np.int32 (n_blocks * block,) — verify set
        self.row = int(row)       # dedicated pool row holding the KV
        self.refs = 0             # in-flight requests reading `row`
        self.tick = 0             # LRU clock (monotonic touch counter)
        self.hash = hash_

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"_PrefixEntry(len={self.tokens.size}, row={self.row}, "
                f"refs={self.refs})")


class PrefixCache:
    """Host-side index over dedicated KV-pool rows holding shared-prefix
    KV. All methods are thread-safe; the lock is a leaf (nothing under it
    calls out), so callers may hold engine locks around these calls."""

    def __init__(self, block, rows):
        block = int(block)
        if block < 1:
            raise ServeError(f"prefix_block must be >= 1, got {block}")
        self.block = block
        self._rows_free = [int(r) for r in rows]
        self.capacity = len(self._rows_free)
        self._lock = threading.Lock()
        self._by_hash = {}        # hash -> [_PrefixEntry] (collision chain)
        self._tick = 0
        self._hash_override = None  # test hook: fn(tokens) -> int

    # -- hashing ---------------------------------------------------------
    def _hash(self, tokens):
        fn = self._hash_override
        return fn(tokens) if fn is not None else rolling_hash(tokens)

    def _prefix_hashes(self, prompt, nblocks):
        """Hashes of `prompt[:i*block]` for i in 1..nblocks. Rolling: one
        pass over the prompt, not O(len²) — unless the test hook replaced
        the hash, in which case each prefix is hashed independently."""
        if self._hash_override is not None:
            return [self._hash_override(prompt[:i * self.block])
                    for i in range(1, nblocks + 1)]
        out = []
        h = 0
        for i in range(nblocks):
            for t in prompt[i * self.block:(i + 1) * self.block]:
                h = (h * _HASH_BASE + int(t) + 1) % _HASH_MOD
            out.append(h)
        return out

    # -- lookup ----------------------------------------------------------
    def match(self, prompt, acquire=True):
        """Longest verified cached prefix of `prompt`.

        Returns `(entry, matched_len)` — `(None, 0)` on miss. The match is
        capped at `len(prompt) - 1` tokens: at least one suffix token must
        remain to prefill, because the first output token's logits come
        from processing the final prompt position. With `acquire=True`
        (the engine's admission path) a hit pins the entry (refcount+1;
        pair with `release()`) and the hit/miss/cached_tokens counters
        move; `acquire=False` is a free peek used for budget costing."""
        prompt = np.asarray(prompt).reshape(-1)
        nmax = (int(prompt.size) - 1) // self.block
        with self._lock:
            if nmax >= 1 and self._by_hash:
                hashes = self._prefix_hashes(prompt, nmax)
                for i in range(nmax, 0, -1):
                    chain = self._by_hash.get(hashes[i - 1])
                    if not chain:
                        continue
                    n = i * self.block
                    for entry in chain:
                        if entry.tokens.size != n:
                            continue
                        if not np.array_equal(entry.tokens, prompt[:n]):
                            # hash collision: NEVER reuse unverified KV
                            with _STATS_LOCK:
                                PREFIX_STATS["collisions"] += 1
                            continue
                        if acquire:
                            entry.refs += 1
                            self._tick += 1
                            entry.tick = self._tick
                            with _STATS_LOCK:
                                PREFIX_STATS["hits"] += 1
                                PREFIX_STATS["cached_tokens"] += n
                        return entry, n
            if acquire:
                with _STATS_LOCK:
                    PREFIX_STATS["misses"] += 1
            return None, 0

    # -- pinning ---------------------------------------------------------
    def release(self, entry):
        """Drop one reference acquired by `match(acquire=True)`. Releasing
        an unheld entry raises `PrefixCacheError` — a refcount that went
        negative would let eviction reclaim a row a request still reads."""
        with self._lock:
            if entry.refs <= 0:
                raise PrefixCacheError(
                    f"prefix entry (len={entry.tokens.size}, "
                    f"row={entry.row}) released with no live reference "
                    "(double release?)")
            entry.refs -= 1

    # -- publish ---------------------------------------------------------
    def insert(self, prompt):
        """Publish `prompt`'s block-quantized prefix.

        Returns the pool ROW the caller must copy the prefix KV into, or
        `None` when nothing was published (prefix shorter than one block,
        already cached — which refreshes its LRU tick — or no free row
        and every resident entry is pinned: eviction REFUSES refcount>0
        entries rather than reclaiming a row in use).

        The entry is indexed immediately; the engine's single scheduler
        thread dispatches the KV copy before any later wave can hit the
        entry, and device-stream ordering makes the copy land first."""
        prompt = np.asarray(prompt).reshape(-1)
        nblocks = int(prompt.size) // self.block
        if nblocks < 1:
            return None
        n = nblocks * self.block
        tokens = np.array(prompt[:n], copy=True)
        h = self._hash(tokens)
        with self._lock:
            for entry in self._by_hash.get(h, ()):
                if (entry.tokens.size == n
                        and np.array_equal(entry.tokens, tokens)):
                    self._tick += 1
                    entry.tick = self._tick
                    return None          # already cached: just touch LRU
            row = self._claim_row_locked()
            if row is None:
                return None
            entry = _PrefixEntry(tokens, row, h)
            self._tick += 1
            entry.tick = self._tick
            self._by_hash.setdefault(h, []).append(entry)
            return row

    def _claim_row_locked(self):
        if self._rows_free:
            return self._rows_free.pop()
        victim = None
        for chain in self._by_hash.values():
            for entry in chain:
                if entry.refs == 0 and (victim is None
                                        or entry.tick < victim.tick):
                    victim = entry
        if victim is None:
            return None                  # every entry pinned: refuse
        self._drop_entry_locked(victim)
        with _STATS_LOCK:
            PREFIX_STATS["evictions"] += 1
        return victim.row

    def _drop_entry_locked(self, entry):
        chain = self._by_hash.get(entry.hash, [])
        if entry in chain:
            chain.remove(entry)
        if not chain:
            self._by_hash.pop(entry.hash, None)

    # -- lifecycle -------------------------------------------------------
    def clear(self):
        """Drop every entry and reclaim its row (used after the engine's
        failure path reallocates the pool slab — the cached KV bytes are
        gone, so the index must go too). Refuses while any entry is
        pinned: the caller must release in-flight refs first."""
        with self._lock:
            held = sum(e.refs for c in self._by_hash.values() for e in c)
            if held:
                raise PrefixCacheError(
                    f"clear() with {held} live reference(s); release "
                    "in-flight requests first")
            for chain in self._by_hash.values():
                for entry in chain:
                    self._rows_free.append(entry.row)
            self._by_hash.clear()

    # -- introspection ---------------------------------------------------
    def entries(self):
        """Snapshot of resident entries for tests/diagnostics:
        `(prefix_len, row, refs)` tuples, LRU-oldest first."""
        with self._lock:
            flat = [e for c in self._by_hash.values() for e in c]
            flat.sort(key=lambda e: e.tick)
            return [(e.tokens.size, e.row, e.refs) for e in flat]

    def stats(self):
        with self._lock:
            flat = [e for c in self._by_hash.values() for e in c]
            return {
                "block": self.block,
                "capacity": self.capacity,
                "entries": len(flat),
                "resident_tokens": int(sum(e.tokens.size for e in flat)),
                "live_refs": int(sum(e.refs for e in flat)),
            }
