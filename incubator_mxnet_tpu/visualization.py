"""mx.visualization (≙ python/mxnet/visualization.py: print_summary,
plot_network). Graph rendering without graphviz: text tree + optional dot
source emission."""
from __future__ import annotations

__all__ = ["print_summary", "plot_network"]


def print_summary(block, *inputs):
    """≙ mx.viz.print_summary — per-layer table (delegates Block.summary)."""
    return block.summary(*inputs)


def plot_network(block, title="network", save_path=None):
    """Emit graphviz dot source for the block tree (≙ mx.viz.plot_network;
    rendering requires graphviz, which is not bundled — the dot text is
    returned/saved instead)."""
    lines = [f'digraph "{title}" {{', "  rankdir=TB;",
             '  node [shape=box, style="rounded,filled", '
             'fillcolor="#e8f0fe"];']
    counter = [0]

    def walk(blk, parent=None):
        nid = f"n{counter[0]}"
        counter[0] += 1
        label = type(blk).__name__
        params = sum(1 for _ in blk._reg_params)
        if params:
            label += f"\\n({params} params)"
        lines.append(f'  {nid} [label="{label}"];')
        if parent is not None:
            lines.append(f"  {parent} -> {nid};")
        for child in blk._children.values():
            walk(child, nid)

    walk(block)
    lines.append("}")
    dot = "\n".join(lines)
    if save_path:
        with open(save_path, "w") as f:
            f.write(dot)
    return dot
