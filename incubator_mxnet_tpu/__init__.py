"""incubator_mxnet_tpu — a TPU-native deep learning framework.

A from-scratch re-design of Apache MXNet 2.0's capabilities
(reference: /root/reference, see SURVEY.md) on the JAX/XLA/Pallas stack:

  - `mx.np` / `mx.npx`  NumPy frontend + NN extensions (≙ python/mxnet/numpy*)
  - `mx.nd`             legacy-style NDArray namespace
  - `mx.autograd`       tape-based AD (≙ src/imperative/imperative.cc taping)
  - `mx.gluon`          Block/HybridBlock/Trainer, nn/rnn layers, data, zoo
  - `mx.optimizer`      fused optimizer updates
  - `mx.kvstore`        Push/Pull facade over XLA collectives (≙ src/kvstore)
  - `mx.parallel`       SPMD meshes, DP/TP/SP sharding, ring attention
  - `mx.amp`            bf16 automatic mixed precision (≙ python/mxnet/amp)
  - `mx.profiler`       Chrome-trace profiling (≙ src/profiler)

Typical use:  import incubator_mxnet_tpu as mx
"""
from __future__ import annotations

__version__ = "0.1.0"

from .base import MXNetError, get_env, set_env, env_flags
from .device import (Device, Context, cpu, gpu, tpu, num_gpus, num_tpus,
                     current_device, current_context, device_memory_info,
                     gpu_memory_info)
from .ndarray import NDArray, waitall
from . import ndarray
from . import ndarray as nd
from . import numpy as np
from . import numpy_extension as npx
from . import autograd
from . import random
from .random import seed
from . import ops

# Heavier subsystems import lazily to keep `import mx` fast and allow partial
# builds during bring-up.
_LAZY = ("gluon", "optimizer", "kvstore", "parallel", "amp", "profiler",
         "fault", "serve", "telemetry", "inspect", "tune",
         "initializer", "lr_scheduler", "metric", "test_utils", "util",
         "runtime", "io", "image", "engine", "context", "recordio",
         "checkpoint", "visualization", "models", "native", "deploy",
         "symbol", "onnx", "contrib", "operator", "library", "name",
         "attribute", "sanitize", "analysis")


def __getattr__(name):
    if name == "kv":   # reference alias: mx.kv is mx.kvstore
        name = "kvstore"
    if name == "sym":  # reference alias: mx.sym is mx.symbol
        name = "symbol"
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        if name == "kvstore":
            globals().setdefault("kv", mod)
        if name == "symbol":
            globals().setdefault("sym", mod)
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
