"""mx.metric — alias of gluon.metric (the reference exposes both
mx.gluon.metric and the legacy mx.metric namespace)."""
from .gluon.metric import *  # noqa: F401,F403
from .gluon.metric import create, EvalMetric, CompositeEvalMetric  # noqa: F401
