"""mx.np.random — samplers over the global (or trace-scoped) PRNG key.

Reference: src/operator/numpy/random/np_*_op.* (4.2k LoC of curand/CPU sampler
kernels) + python/mxnet/numpy/random.py. TPU-native: jax.random bitgen; the
per-device parallel-random resources collapse into functional key splitting
(see incubator_mxnet_tpu/random.py).
"""
from __future__ import annotations

import numpy as _onp

from ..base import name_to_dtype
from ..ndarray import NDArray, _wrap, _as_nd
from ..ops.registry import invoke
from .. import random as _global_random

__all__ = [
    "seed", "uniform", "normal", "randn", "rand", "randint", "choice",
    "shuffle", "permutation", "multinomial", "categorical", "bernoulli",
    "gamma", "beta", "exponential", "poisson", "laplace", "gumbel",
    "logistic", "pareto", "power", "rayleigh", "weibull", "lognormal",
    "chisquare", "multivariate_normal",
]

seed = _global_random.seed


def _jr():
    import jax.random
    return jax.random


def _shape(size):
    if size is None:
        return ()
    if isinstance(size, int):
        return (size,)
    return tuple(size)


def _sample(fn_name, size, dtype, *params, jax_fn=None, **kw):
    """Generic sampler: splits a key, invokes jax.random.<fn> through the tape
    (samples are differentiable w.r.t. loc/scale via reparameterization when
    params are passed as traced inputs)."""
    key = _global_random.next_key()
    jr = _jr()
    jfn = jax_fn or getattr(jr, fn_name)
    shape = _shape(size)
    dt = name_to_dtype(dtype) if dtype else None
    arr_params = tuple(_as_nd(p) if not isinstance(p, NDArray) else p
                       for p in params)

    def call(*raws):
        return jfn(key, *raws, shape=shape, dtype=dt, **kw) if dt is not None \
            else jfn(key, *raws, shape=shape, **kw)

    return invoke(call, arr_params, name=f"random.{fn_name}")


def uniform(low=0.0, high=1.0, size=None, dtype=None, device=None, ctx=None, out=None):
    key = _global_random.next_key()
    jr = _jr()
    shape = _shape(size)
    dt = name_to_dtype(dtype or "float32")

    def call(lo, hi):
        u = jr.uniform(key, shape, dt.base if hasattr(dt, "base") else dt)
        return lo + (hi - lo) * u

    res = invoke(call, (_as_nd(low, dtype=dt), _as_nd(high, dtype=dt)),
                 name="random.uniform")
    if out is not None:
        out[:] = res
        return out
    return res


def normal(loc=0.0, scale=1.0, size=None, dtype=None, device=None, ctx=None, out=None):
    key = _global_random.next_key()
    jr = _jr()
    shape = _shape(size)
    dt = name_to_dtype(dtype or "float32")

    def call(mu, sigma):
        return mu + sigma * jr.normal(key, shape, dt)

    res = invoke(call, (_as_nd(loc, dtype=dt), _as_nd(scale, dtype=dt)),
                 name="random.normal")
    if out is not None:
        out[:] = res
        return out
    return res


def randn(*size, dtype=None):
    return normal(0.0, 1.0, size=size or None, dtype=dtype)


def rand(*size, dtype=None):
    return uniform(0.0, 1.0, size=size or None, dtype=dtype)


def lognormal(mean=0.0, sigma=1.0, size=None, dtype=None):
    from . import exp as _  # noqa: F401  (avoid circular at module load)
    n = normal(mean, sigma, size=size, dtype=dtype)
    return invoke(lambda x: __import__("jax.numpy", fromlist=["exp"]).exp(x),
                  (n,), name="random.lognormal")


def randint(low, high=None, size=None, dtype=None, device=None, ctx=None):
    key = _global_random.next_key()
    jr = _jr()
    if high is None:
        low, high = 0, low
    dt = name_to_dtype(dtype or "int32")
    return _wrap(jr.randint(key, _shape(size), int(low), int(high), dt))


def choice(a, size=None, replace=True, p=None, device=None, ctx=None):
    key = _global_random.next_key()
    jr = _jr()
    if isinstance(a, NDArray):
        a = a._arr
    pr = p._arr if isinstance(p, NDArray) else p
    return _wrap(jr.choice(key, a, _shape(size), replace, pr))


def permutation(x):
    key = _global_random.next_key()
    jr = _jr()
    if isinstance(x, NDArray):
        return _wrap(jr.permutation(key, x._arr))
    return _wrap(jr.permutation(key, int(x)))


def shuffle(x):
    """In-place row shuffle (≙ mx.np.random.shuffle)."""
    key = _global_random.next_key()
    jr = _jr()
    x._set_arr(jr.permutation(key, x._arr))


def multinomial(n, pvals, size=None):
    key = _global_random.next_key()
    jr = _jr()
    pv = pvals._arr if isinstance(pvals, NDArray) else _onp.asarray(pvals)
    shape = _shape(size)
    counts = jr.multinomial(key, n, pv, shape=shape + _onp.shape(pv)[:-1]
                            if shape else None)
    return _wrap(counts)


def categorical(logits, shape=None):
    key = _global_random.next_key()
    jr = _jr()
    lg = logits._arr if isinstance(logits, NDArray) else logits
    return _wrap(jr.categorical(key, lg, shape=_shape(shape) if shape else None))


def bernoulli(prob=None, logit=None, size=None, dtype=None):
    key = _global_random.next_key()
    jr = _jr()
    import jax.numpy as jnp
    dt = name_to_dtype(dtype or "float32")
    if prob is None:
        p = jnp.squeeze(1.0 / (1.0 + jnp.exp(
            -(logit._arr if isinstance(logit, NDArray) else logit))))
    else:
        p = prob._arr if isinstance(prob, NDArray) else prob
    shape = _shape(size) if size is not None else _onp.shape(p)
    return _wrap(jr.bernoulli(key, p, shape).astype(dt))


def gamma(shape_param, scale=1.0, size=None, dtype=None, device=None, ctx=None):
    key = _global_random.next_key()
    jr = _jr()
    sh = _shape(size) if size is not None else None

    def call(a, s):
        g = jr.gamma(key, a, shape=sh)
        return g * s

    return invoke(call, (_as_nd(shape_param), _as_nd(scale)), name="random.gamma")


def beta(a, b, size=None, dtype=None):
    key = _global_random.next_key()
    jr = _jr()
    sh = _shape(size) if size is not None else None
    return invoke(lambda x, y: jr.beta(key, x, y, shape=sh),
                  (_as_nd(a), _as_nd(b)), name="random.beta")


def exponential(scale=1.0, size=None, dtype=None, device=None, ctx=None):
    key = _global_random.next_key()
    jr = _jr()
    sh = _shape(size)
    return invoke(lambda s: jr.exponential(key, shape=sh) * s,
                  (_as_nd(scale),), name="random.exponential")


def poisson(lam=1.0, size=None, dtype=None):
    key = _global_random.next_key()
    jr = _jr()
    return _wrap(jr.poisson(key, lam._arr if isinstance(lam, NDArray) else lam,
                            shape=_shape(size) if size is not None else None))


def laplace(loc=0.0, scale=1.0, size=None, dtype=None, device=None, ctx=None):
    key = _global_random.next_key()
    jr = _jr()
    sh = _shape(size)
    return invoke(lambda m, s: m + s * jr.laplace(key, shape=sh),
                  (_as_nd(loc), _as_nd(scale)), name="random.laplace")


def gumbel(loc=0.0, scale=1.0, size=None, dtype=None):
    key = _global_random.next_key()
    jr = _jr()
    sh = _shape(size)
    return invoke(lambda m, s: m + s * jr.gumbel(key, shape=sh),
                  (_as_nd(loc), _as_nd(scale)), name="random.gumbel")


def logistic(loc=0.0, scale=1.0, size=None, dtype=None):
    key = _global_random.next_key()
    jr = _jr()
    sh = _shape(size)
    return invoke(lambda m, s: m + s * jr.logistic(key, shape=sh),
                  (_as_nd(loc), _as_nd(scale)), name="random.logistic")


def pareto(a, size=None):
    key = _global_random.next_key()
    jr = _jr()
    sh = _shape(size) if size is not None else None
    return invoke(lambda b: jr.pareto(key, b, shape=sh) - 1.0,
                  (_as_nd(a),), name="random.pareto")


def power(a, size=None):
    key = _global_random.next_key()
    jr = _jr()
    import jax.numpy as jnp
    sh = _shape(size)
    return invoke(lambda b: jnp.power(jr.uniform(key, sh), 1.0 / b),
                  (_as_nd(a),), name="random.power")


def rayleigh(scale=1.0, size=None):
    key = _global_random.next_key()
    jr = _jr()
    import jax.numpy as jnp
    sh = _shape(size)
    return invoke(
        lambda s: s * jnp.sqrt(-2.0 * jnp.log(jr.uniform(key, sh, minval=1e-20))),
        (_as_nd(scale),), name="random.rayleigh")


def weibull(a, size=None):
    key = _global_random.next_key()
    jr = _jr()
    import jax.numpy as jnp
    sh = _shape(size)
    return invoke(
        lambda b: jnp.power(-jnp.log(jr.uniform(key, sh, minval=1e-20)), 1.0 / b),
        (_as_nd(a),), name="random.weibull")


def chisquare(df, size=None, dtype=None):
    return gamma(df / 2.0, 2.0, size=size, dtype=dtype)


def multivariate_normal(mean, cov, size=None):
    key = _global_random.next_key()
    jr = _jr()
    sh = _shape(size) if size is not None else None
    return invoke(lambda m, c: jr.multivariate_normal(key, m, c, shape=sh),
                  (_as_nd(mean), _as_nd(cov)), name="random.multivariate_normal")
