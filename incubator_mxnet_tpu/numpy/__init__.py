"""mx.np — the NumPy-compatible array frontend.

Reference: python/mxnet/numpy/multiarray.py (12.2k LoC ndarray + ufuncs through
the `_npi.*` FFI; SURVEY §2.5). TPU-native: the `_npi` C++ shim layer and
per-op MXNET_REGISTER_API handlers (src/api/operator/numpy/*) collapse into
thin autograd-aware wrappers over jax.numpy — one generic adapter handles
NDArray unwrap/wrap, static-arg closure and taping for every op, instead of
9.6k LoC of per-op C++ argument parsing.
"""
from __future__ import annotations

import numpy as _onp

from ..base import name_to_dtype
from ..ndarray import (NDArray, _wrap, _as_nd, waitall,
                       array, zeros, ones, full, empty, arange,
                       save, load)
from ..ops.registry import invoke, register_op
from ..ops import segment as _segment
from . import random
from . import linalg

ndarray = NDArray

__all__ = [
    "ndarray", "array", "zeros", "ones", "full", "empty", "arange",
    "random", "linalg", "newaxis", "pi", "e", "inf", "nan",
    "float32", "float64", "float16", "bfloat16", "int8", "int16", "int32",
    "int64", "uint8", "bool_", "save", "load", "waitall",
]

newaxis = None
pi = _onp.pi
e = _onp.e
euler_gamma = _onp.euler_gamma
inf = _onp.inf
nan = _onp.nan

float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
bool_ = _onp.bool_
bfloat16 = "bfloat16"


def _jnp():
    import jax.numpy as jnp
    return jnp


def _make_wrapper(name, submodule=None):
    """Build an autograd-aware mx.np function delegating to jax.numpy.

    All NDArray leaves anywhere in args/kwargs become traced inputs; every
    other value is closed over as a static parameter (XLA specializes on it,
    mirroring the reference's dmlc::Parameter static op attributes).
    """
    def fn(*args, **kwargs):
        import jax.tree_util as jtu
        jnp = _jnp()
        mod = getattr(jnp, submodule) if submodule else jnp
        jfn = getattr(mod, name)
        dt = kwargs.pop("dtype", None)
        if dt is not None:
            kwargs["dtype"] = name_to_dtype(dt)
        device = kwargs.pop("device", kwargs.pop("ctx", None))
        leaves, treedef = jtu.tree_flatten((args, kwargs))
        arr_pos = [i for i, l in enumerate(leaves) if isinstance(l, NDArray)]
        arrs = tuple(leaves[i] for i in arr_pos)
        # placeholder-out the array leaves so the closure (which the bulking
        # replay cache retains) holds no buffer references
        statics = [None if isinstance(l, NDArray) else l for l in leaves]

        def call(*raws):
            ls = list(statics)
            for i, r in zip(arr_pos, raws):
                ls[i] = r
            a, kw = jtu.tree_unflatten(treedef, ls)
            out = jfn(*a, **kw)
            return tuple(out) if isinstance(out, (list, tuple)) else out

        # stable bulking key: op name + arg structure + every static leaf
        # (array leaves are traced, so they stay out of the key)
        try:
            skey = ("np", name, submodule, treedef, tuple(arr_pos),
                    _segment.canon(tuple(statics)))
        except _segment.Reject:
            skey = None
        out = invoke(call, arrs, name=name, key=skey)
        if device is not None and isinstance(out, NDArray):
            out = out.as_in_context(device)
        return out

    fn.__name__ = name
    fn.__qualname__ = name
    fn.__doc__ = f"TPU-native equivalent of np.{name} (reference: _npi.{name})."
    return fn


# The exported op surface (reference inventory: SURVEY §A.3 src/operator/numpy/,
# 31.4k LoC of _npi_* registrations). Anything jax.numpy implements is one
# wrapper away; list curated to the reference's documented API.
_JNP_NAMES = [
    # elemwise arithmetic / ufuncs
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "mod", "remainder", "fmod", "power", "float_power", "negative", "positive",
    "absolute", "abs", "fabs", "sign", "rint", "reciprocal", "square", "sqrt",
    "cbrt", "exp", "exp2", "expm1", "log", "log2", "log10", "log1p",
    "logaddexp", "logaddexp2", "sin", "cos", "tan", "arcsin", "arccos",
    "arctan", "arctan2", "sinh", "cosh", "tanh", "arcsinh", "arccosh",
    "arctanh", "hypot", "deg2rad", "rad2deg", "degrees", "radians", "ceil",
    "floor", "trunc", "round", "around", "clip", "maximum", "minimum",
    "fmax", "fmin", "heaviside", "nan_to_num", "real", "imag", "conj",
    "conjugate", "angle", "ldexp", "frexp", "copysign", "nextafter", "spacing",
    "gcd", "lcm", "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "invert", "left_shift", "right_shift", "sinc", "i0", "interp",
    # logic / comparison
    "equal", "not_equal", "less", "less_equal", "greater", "greater_equal",
    "logical_and", "logical_or", "logical_xor", "logical_not", "isfinite",
    "isinf", "isnan", "isneginf", "isposinf", "isclose", "allclose",
    "array_equal", "array_equiv", "signbit",
    # reductions / statistics
    "sum", "prod", "mean", "std", "var", "min", "max", "amin", "amax", "ptp",
    "nansum", "nanprod", "nanmean", "nanstd", "nanvar", "nanmin", "nanmax",
    "argmin", "argmax", "nanargmin", "nanargmax", "median", "nanmedian",
    "percentile", "nanpercentile", "quantile", "nanquantile", "average",
    "cumsum", "cumprod", "nancumsum", "nancumprod", "all", "any",
    "count_nonzero", "bincount", "histogram", "histogram2d", "corrcoef", "cov",
    "digitize",
    # linear algebra (flat namespace)
    "dot", "vdot", "inner", "outer", "matmul", "tensordot", "einsum", "kron",
    "cross", "trace", "diagonal",
    # shape manipulation
    "reshape", "ravel", "transpose", "swapaxes", "moveaxis", "rollaxis",
    "expand_dims", "squeeze", "broadcast_to", "broadcast_arrays", "atleast_1d",
    "atleast_2d", "atleast_3d", "concatenate", "stack", "vstack", "hstack",
    "dstack", "column_stack", "row_stack", "split", "array_split", "hsplit",
    "vsplit", "dsplit", "tile", "repeat", "flip", "fliplr", "flipud", "roll",
    "rot90", "resize", "append", "insert", "delete", "pad", "flatnonzero",
    # indexing / search / sort
    "take", "take_along_axis", "put_along_axis", "choose", "compress",
    "extract", "searchsorted", "argsort", "sort", "partition", "argpartition",
    "nonzero", "argwhere", "where", "unravel_index", "ravel_multi_index",
    "diag", "diagflat", "tril", "triu", "tril_indices", "triu_indices",
    "indices", "ix_", "select", "piecewise",
    # sets
    "unique", "union1d", "intersect1d", "setdiff1d", "setxor1d", "in1d", "isin",
    # creation (non-placing variants; placing ones defined above)
    "eye", "identity", "linspace", "logspace", "geomspace", "meshgrid",
    "tri", "vander", "fromfunction", "diff", "ediff1d", "gradient",
    "trapezoid", "convolve", "correlate",
    # windows
    "hanning", "hamming", "blackman", "bartlett", "kaiser",
    # misc
    "zeros_like", "ones_like", "full_like", "empty_like", "copy", "asarray",
    "ascontiguousarray", "shape", "size", "ndim", "result_type",
    "promote_types", "can_cast", "iscomplexobj", "isrealobj", "isscalar",
    "polyval", "polyadd", "polysub", "polymul", "polyder", "polyint", "polyfit",
    "apply_along_axis", "apply_over_axes", "expand_dims",
]

_missing = []
for _name in _JNP_NAMES:
    import jax.numpy as _jnp_mod
    if hasattr(_jnp_mod, _name):
        globals()[_name] = _make_wrapper(_name)
        register_op("np." + _name, globals()[_name])
        __all__.append(_name)
    else:
        _missing.append(_name)
# fallback to host numpy for names jax lacks (reference pattern:
# python/mxnet/numpy_op_fallback.py — host execution with device round-trip)
for _name in _missing:
    if hasattr(_onp, _name):
        def _host_fallback(*args, __f=_name, **kwargs):
            args = [a.asnumpy() if isinstance(a, NDArray) else a for a in args]
            out = getattr(_onp, __f)(*args, **kwargs)
            return array(out) if isinstance(out, _onp.ndarray) else out
        _host_fallback.__name__ = _name
        globals()[_name] = _host_fallback
        __all__.append(_name)


# dtype-metadata queries must NOT route through invoke (their results are
# dtypes/bools, not arrays — the array wrapper mangles them; caught by the
# r5 op sweep)
def _dtype_meta_override(name):
    def fn(*args, **kwargs):
        jnp = _jnp()
        conv = [a._arr if isinstance(a, NDArray) else a for a in args]
        return getattr(jnp, name)(*conv, **kwargs)
    fn.__name__ = name
    fn.__doc__ = f"TPU-native equivalent of np.{name} (metadata query)."
    return fn


for _name in ("promote_types", "result_type", "can_cast"):
    globals()[_name] = _dtype_meta_override(_name)
    register_op("np." + _name, globals()[_name])


# numpy's put_along_axis mutates in place; jax arrays are immutable, so the
# wrapper computes functionally (inplace=False) and writes back into the
# NDArray argument, returning the result as well
_pala_functional = _make_wrapper("put_along_axis")


def put_along_axis(arr, indices, values, axis):
    """TPU-native equivalent of np.put_along_axis — with a documented
    divergence from numpy: it RETURNS the updated array instead of
    returning None.

    numpy mutates `arr` in place. jax buffers are immutable, so this
    computes functionally (`jnp.put_along_axis(..., inplace=False)`) and
    then writes the result back into `arr` ONLY when `arr` is an NDArray
    (whose `[:] =` swaps the wrapped buffer). A raw numpy/jax array first
    argument is NOT mutated — code ported from numpy that relies on the
    in-place effect must use the returned array (a warning flags this
    case).
    """
    if not isinstance(arr, NDArray):
        import warnings
        warnings.warn(
            "mx.np.put_along_axis cannot mutate a non-NDArray first "
            "argument in place (jax buffers are immutable); the input is "
            "unchanged — use the RETURNED array (numpy's put_along_axis "
            "returns None and mutates, so ported code silently diverges "
            "here)", UserWarning, stacklevel=2)
    out = _pala_functional(arr, indices, values, axis, inplace=False)
    if isinstance(arr, NDArray):
        arr[:] = out
    return out


register_op("np.put_along_axis", put_along_axis)


def fix(x):
    """Round toward zero (mx.np.fix; jnp.fix is deprecated — trunc is the
    same operation)."""
    return globals()["trunc"](x)


__all__.append("fix")


def astype(a, dtype):
    return _as_nd(a).astype(dtype)


def may_share_memory(a, b, max_work=None):
    return a is b


def shares_memory(a, b, max_work=None):
    return a is b


def dtype(d):
    return name_to_dtype(d)


def get_include():
    return _onp.get_include()
