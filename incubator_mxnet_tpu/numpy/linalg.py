"""mx.np.linalg — linear algebra namespace.

Reference: src/operator/numpy/linalg/np_{eig,gesvd,lstsq,norm,pinv,potrf,qr,
solve,...}* (LAPACK/cuSOLVER backed) and src/operator/tensor/la_op.* (3.3k LoC).
TPU-native: XLA's native decompositions via jax.numpy.linalg — the whole
c_lapack_api shim layer disappears.
"""
from __future__ import annotations

from ..ndarray import NDArray
from ..ops.registry import invoke

_NAMES = [
    "norm", "det", "slogdet", "inv", "pinv", "solve", "lstsq", "matrix_rank",
    "matrix_power", "cholesky", "qr", "svd", "svdvals", "eig", "eigh",
    "eigvals", "eigvalsh", "multi_dot", "tensorinv", "tensorsolve", "cond",
    "cross", "outer", "matmul", "tensordot", "vector_norm", "matrix_norm",
]


def _make(name):
    def fn(*args, **kwargs):
        import jax.numpy as jnp
        import jax.tree_util as jtu
        jfn = getattr(jnp.linalg, name)
        leaves, treedef = jtu.tree_flatten((args, kwargs))
        pos = [i for i, l in enumerate(leaves) if isinstance(l, NDArray)]
        arrs = tuple(leaves[i] for i in pos)

        def call(*raws):
            ls = list(leaves)
            for i, r in zip(pos, raws):
                ls[i] = r
            a, kw = jtu.tree_unflatten(treedef, ls)
            out = jfn(*a, **kw)
            return tuple(out) if isinstance(out, tuple) else out

        return invoke(call, arrs, name="linalg." + name)

    fn.__name__ = name
    return fn


import jax.numpy as _jnp_mod  # noqa: E402
for _n in _NAMES:
    if hasattr(_jnp_mod.linalg, _n):
        globals()[_n] = _make(_n)


# ---------------------------------------------------------------------------
# reference la_op family (src/operator/tensor/la_op.cc — BLAS3/LAPACK ops the
# generic jnp.linalg surface doesn't name): syrk, trmm, trsm, potrf, potri,
# gelqf, syevd, gemm2. Same calling conventions as mx.nd.linalg.*.
# ---------------------------------------------------------------------------
def _la(fn, name, args):
    return invoke(fn, args, name="linalg." + name)


def syrk(A, transpose=False, alpha=1.0):
    """alpha * A @ A.T (or A.T @ A when transpose) ≙ linalg_syrk."""
    import jax.numpy as jnp

    def f(a):
        prod = (jnp.matmul(jnp.swapaxes(a, -1, -2), a) if transpose
                else jnp.matmul(a, jnp.swapaxes(a, -1, -2)))
        return alpha * prod
    return _la(f, "syrk", (A,))


def trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    """Triangular matrix multiply ≙ linalg_trmm: B <- alpha * op(tri(A)) B."""
    import jax.numpy as jnp

    def f(a, b):
        t = jnp.tril(a) if lower else jnp.triu(a)
        if transpose:
            t = jnp.swapaxes(t, -1, -2)
        return alpha * (jnp.matmul(b, t) if rightside else jnp.matmul(t, b))
    return _la(f, "trmm", (A, B))


def trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    """Triangular solve ≙ linalg_trsm: solve op(tri(A)) X = alpha B."""
    from jax.scipy.linalg import solve_triangular

    def f(a, b):
        import jax.numpy as jnp
        t = jnp.tril(a) if lower else jnp.triu(a)
        if rightside:
            # X op(A) = alpha B  <=>  op(A)^T X^T = alpha B^T
            x = solve_triangular(jnp.swapaxes(t, -1, -2),
                                 jnp.swapaxes(alpha * b, -1, -2),
                                 lower=not lower, trans=1 if transpose else 0)
            return jnp.swapaxes(x, -1, -2)
        return solve_triangular(t, alpha * b, lower=lower,
                                trans=1 if transpose else 0)
    return _la(f, "trsm", (A, B))


def potrf(A, lower=True):
    """Cholesky factor ≙ linalg_potrf."""
    import jax.numpy as jnp

    def f(a):
        L = jnp.linalg.cholesky(a)
        return L if lower else jnp.swapaxes(L, -1, -2)
    return _la(f, "potrf", (A,))


def potri(A, lower=True):
    """Inverse from the Cholesky factor ≙ linalg_potri: given L (or U),
    return (L L^T)^-1."""
    import jax.numpy as jnp

    def f(a):
        L = a if lower else jnp.swapaxes(a, -1, -2)
        n = a.shape[-1]
        eye = jnp.broadcast_to(jnp.eye(n, dtype=a.dtype), a.shape)
        from jax.scipy.linalg import solve_triangular
        Linv = solve_triangular(L, eye, lower=True)
        return jnp.matmul(jnp.swapaxes(Linv, -1, -2), Linv)
    return _la(f, "potri", (A,))


def gelqf(A):
    """LQ factorization ≙ linalg_gelqf: A = L Q with Q orthonormal rows.
    Via QR of A^T (XLA-native): A^T = Q' R'  =>  A = R'^T Q'^T."""
    import jax.numpy as jnp

    def f(a):
        q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
        return (jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2))
    return _la(f, "gelqf", (A,))


def syevd(A):
    """Symmetric eigendecomposition ≙ linalg_syevd: returns (U, lam) with
    A = U^T diag(lam) U (reference row-eigenvector convention)."""
    import jax.numpy as jnp

    def f(a):
        lam, v = jnp.linalg.eigh(a)
        return (jnp.swapaxes(v, -1, -2), lam)
    return _la(f, "syevd", (A,))


def gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0):
    """General matmul with transpose flags ≙ linalg_gemm2."""
    import jax.numpy as jnp

    def f(a, b):
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        return alpha * jnp.matmul(a, b)
    return _la(f, "gemm2", (A, B))


_LA_OPS = ["syrk", "trmm", "trsm", "potrf", "potri", "gelqf", "syevd",
           "gemm2"]
__all__ = [n for n in _NAMES if n in globals()] + _LA_OPS
