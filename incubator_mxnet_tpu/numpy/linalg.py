"""mx.np.linalg — linear algebra namespace.

Reference: src/operator/numpy/linalg/np_{eig,gesvd,lstsq,norm,pinv,potrf,qr,
solve,...}* (LAPACK/cuSOLVER backed) and src/operator/tensor/la_op.* (3.3k LoC).
TPU-native: XLA's native decompositions via jax.numpy.linalg — the whole
c_lapack_api shim layer disappears.
"""
from __future__ import annotations

from ..ndarray import NDArray
from ..ops.registry import invoke

_NAMES = [
    "norm", "det", "slogdet", "inv", "pinv", "solve", "lstsq", "matrix_rank",
    "matrix_power", "cholesky", "qr", "svd", "svdvals", "eig", "eigh",
    "eigvals", "eigvalsh", "multi_dot", "tensorinv", "tensorsolve", "cond",
    "cross", "outer", "matmul", "tensordot", "vector_norm", "matrix_norm",
]


def _make(name):
    def fn(*args, **kwargs):
        import jax.numpy as jnp
        import jax.tree_util as jtu
        jfn = getattr(jnp.linalg, name)
        leaves, treedef = jtu.tree_flatten((args, kwargs))
        pos = [i for i, l in enumerate(leaves) if isinstance(l, NDArray)]
        arrs = tuple(leaves[i] for i in pos)

        def call(*raws):
            ls = list(leaves)
            for i, r in zip(pos, raws):
                ls[i] = r
            a, kw = jtu.tree_unflatten(treedef, ls)
            out = jfn(*a, **kw)
            return tuple(out) if isinstance(out, tuple) else out

        return invoke(call, arrs, name="linalg." + name)

    fn.__name__ = name
    return fn


import jax.numpy as _jnp_mod  # noqa: E402
for _n in _NAMES:
    if hasattr(_jnp_mod.linalg, _n):
        globals()[_n] = _make(_n)
__all__ = [n for n in _NAMES if n in globals()]
