"""mx.runtime — feature introspection (≙ python/mxnet/runtime.py +
src/libinfo.cc). Features reflect the TPU-native build: what the reference
gated at compile time (CUDA/CUDNN/MKLDNN/...) is replaced by runtime facts
about the jax/XLA stack."""
from __future__ import annotations

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _detect():
    import jax
    feats = {
        "TPU": any(d.platform != "cpu" for d in jax.devices()),
        "CPU": True,
        "XLA": True,
        "PALLAS": True,
        "BF16": True,
        "F16C": True,
        "INT64_TENSOR_SIZE": True,
        "SPMD": True,
        "DIST_KVSTORE": True,
        "PROFILER": True,
        # reference features with no TPU equivalent:
        "CUDA": False, "CUDNN": False, "NCCL": False, "TENSORRT": False,
        "MKLDNN": False, "OPENCV": False, "OPENMP": True,
        "SIGNAL_HANDLER": False, "DEBUG": False, "TVM_OP": False,
    }
    return feats


class Features(dict):
    """≙ mx.runtime.Features()."""

    def __init__(self):
        super().__init__({k: Feature(k, v) for k, v in _detect().items()})

    def is_enabled(self, name):
        return self[name.upper()].enabled

    def __repr__(self):
        return str(list(self.values()))


def feature_list():
    return list(Features().values())
