"""mx.gluon — the imperative/hybrid module API.

Reference: python/mxnet/gluon/ (Block/HybridBlock block.py:202,997;
Parameter parameter.py:47; Trainer trainer.py:31; nn/rnn layer catalogs in
SURVEY.md Appendix B; loss.py; metric.py; data/; model_zoo/).
"""
from __future__ import annotations

from .parameter import Parameter, Constant, DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import subgraph
from . import nn
from . import loss
from . import metric
from . import utils

# lazy heavy submodules
_LAZY = ("rnn", "data", "model_zoo", "contrib", "probability")


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
