"""gluon.rnn — recurrent cells and fused recurrent layers.

Reference: python/mxnet/gluon/rnn/rnn_cell.py (RNNCell:304, LSTMCell:413,
GRUCell:540, SequentialRNNCell:670, DropoutCell:838, ZoneoutCell:935,
ResidualCell:984, BidirectionalCell:1029, VariationalDropoutCell:1110,
LSTMPCell:1284) and rnn_layer.py (fused RNN:260 / LSTM:353 / GRU:480 lowering
to the fused `rnn` op, src/operator/rnn.cc).

TPU-native: cells are plain HybridBlocks; `unroll` builds a lax.scan under
hybridization (through npx.foreach) or a Python loop eagerly. The fused
layers lower to ops.nn.rnn — a lax.scan over packed per-layer weights that
XLA maps onto the MXU (the cuDNN-RNN descriptor machinery has no equivalent).
"""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from ... import numpy as mxnp
from ... import numpy_extension as npx
from ... import random as _random
from ... import autograd
from ...ndarray import NDArray, _wrap
from ...ops.registry import invoke
from ...ops import nn as _nn
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = [
    "RecurrentCell", "RNNCell", "LSTMCell", "GRUCell", "SequentialRNNCell",
    "HybridSequentialRNNCell", "DropoutCell", "ZoneoutCell", "ResidualCell",
    "BidirectionalCell", "VariationalDropoutCell", "LSTMPCell",
    "RNN", "LSTM", "GRU",
]


class RecurrentCell(HybridBlock):
    """Base recurrent cell (≙ rnn_cell.py RecurrentCell)."""

    def __init__(self):
        super().__init__()
        self._modified = False

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial zero states (≙ RecurrentCell.begin_state)."""
        states = []
        for info in self.state_info(batch_size):
            shape = info["shape"]
            if func is None:
                states.append(mxnp.zeros(shape))
            else:
                states.append(func(shape=shape, **kwargs))
        return states

    def reset(self):
        pass

    def __call__(self, inputs, states, **kwargs):
        return super().__call__(inputs, states, **kwargs)

    def forward(self, inputs, states):
        raise NotImplementedError

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll over the time axis (≙ RecurrentCell.unroll)."""
        axis = layout.find("T")
        batch_axis = layout.find("N")
        if isinstance(inputs, (list, tuple)):
            seq = list(inputs)
            batch = seq[0].shape[batch_axis if batch_axis < axis else 0]
        else:
            batch = inputs.shape[batch_axis]
            if axis != 0:
                inputs = inputs.swapaxes(0, axis)
            seq = [inputs[t] for t in range(length)]
        states = begin_state if begin_state is not None \
            else self.begin_state(batch)
        outputs = []
        for t in range(length):
            out, states = self(seq[t], states)
            outputs.append(out)
        if merge_outputs is None or merge_outputs:
            from ...ndarray import stack
            merged = stack(*outputs, axis=axis)
            if valid_length is not None:
                merged = npx.sequence_mask(
                    merged, sequence_length=valid_length,
                    use_sequence_length=True, axis=axis)
            return merged, states
        return outputs, states


def _cell_param(shape, init, name):
    return Parameter(shape=shape, init=init, allow_deferred_init=True,
                     name=name)


class _BaseFusedGateCell(RecurrentCell):
    """Shared plumbing for RNN/LSTM/GRU cells: i2h/h2h weights + biases."""

    def __init__(self, hidden_size, num_gates, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros"):
        super().__init__()
        self._hidden_size = hidden_size
        self._input_size = input_size
        ng = num_gates
        self.i2h_weight = _cell_param((ng * hidden_size, input_size),
                                      i2h_weight_initializer, "i2h_weight")
        self.h2h_weight = _cell_param((ng * hidden_size, hidden_size),
                                      h2h_weight_initializer, "h2h_weight")
        self.i2h_bias = _cell_param((ng * hidden_size,),
                                    i2h_bias_initializer, "i2h_bias")
        self.h2h_bias = _cell_param((ng * hidden_size,),
                                    h2h_bias_initializer, "h2h_bias")

    def infer_shape(self, inputs, *states):
        in_sz = inputs.shape[-1]
        self.i2h_weight.shape = (self.i2h_weight.shape[0], in_sz)


class RNNCell(_BaseFusedGateCell):
    """Simple Elman cell (≙ rnn_cell.py RNNCell:304)."""

    def __init__(self, hidden_size, activation="tanh", input_size=0, **kwargs):
        super().__init__(hidden_size, 1, input_size, **kwargs)
        self._activation = activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def forward(self, inputs, states):
        act = self._activation
        out = invoke(
            lambda x, h, wx, wh, bx, bh: _nn.rnn_relu_cell(
                x, h, wx, wh, bx + bh, "relu" if act == "relu" else "tanh"),
            (inputs, states[0], self.i2h_weight.data(), self.h2h_weight.data(),
             self.i2h_bias.data(), self.h2h_bias.data()), name="rnn_cell")
        return out, [out]


class LSTMCell(_BaseFusedGateCell):
    """≙ rnn_cell.py LSTMCell:413 (gate order i,f,g,o)."""

    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 4, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def forward(self, inputs, states):
        h, c = invoke(
            lambda x, h0, c0, wx, wh, bx, bh: _nn.lstm_cell(
                x, h0, c0, wx, wh, bx + bh),
            (inputs, states[0], states[1], self.i2h_weight.data(),
             self.h2h_weight.data(), self.i2h_bias.data(),
             self.h2h_bias.data()),
            name="lstm_cell", multi_out=True)
        return h, [h, c]


class GRUCell(_BaseFusedGateCell):
    """≙ rnn_cell.py GRUCell:540 (gate order r,z,n)."""

    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 3, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def forward(self, inputs, states):
        out = invoke(
            lambda x, h, wx, wh, bx, bh: _nn.gru_cell(x, h, wx, wh, bx, bh),
            (inputs, states[0], self.i2h_weight.data(), self.h2h_weight.data(),
             self.i2h_bias.data(), self.h2h_bias.data()), name="gru_cell")
        return out, [out]


class LSTMPCell(RecurrentCell):
    """LSTM with hidden projection (≙ rnn_cell.py LSTMPCell:1284)."""

    def __init__(self, hidden_size, projection_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros"):
        super().__init__()
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self.i2h_weight = _cell_param((4 * hidden_size, input_size),
                                      i2h_weight_initializer, "i2h_weight")
        self.h2h_weight = _cell_param((4 * hidden_size, projection_size),
                                      h2h_weight_initializer, "h2h_weight")
        self.h2r_weight = _cell_param((projection_size, hidden_size),
                                      h2r_weight_initializer, "h2r_weight")
        self.i2h_bias = _cell_param((4 * hidden_size,),
                                    i2h_bias_initializer, "i2h_bias")
        self.h2h_bias = _cell_param((4 * hidden_size,),
                                    h2h_bias_initializer, "h2h_bias")

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, inputs, *states):
        self.i2h_weight.shape = (self.i2h_weight.shape[0], inputs.shape[-1])

    def forward(self, inputs, states):
        def f(x, r, c0, wx, wh, wr, bx, bh):
            h, c = _nn.lstm_cell(x, r, c0, wx, wh, bx + bh)
            import jax.numpy as jnp
            return jnp.matmul(h, wr.T), c

        r, c = invoke(f, (inputs, states[0], states[1],
                          self.i2h_weight.data(), self.h2h_weight.data(),
                          self.h2r_weight.data(), self.i2h_bias.data(),
                          self.h2h_bias.data()),
                      name="lstmp_cell", multi_out=True)
        return r, [r, c]


# ---------------------------------------------------------------------------
# modifier / composite cells
# ---------------------------------------------------------------------------
class SequentialRNNCell(RecurrentCell):
    """Stack cells vertically (≙ rnn_cell.py SequentialRNNCell:670)."""

    def __init__(self, *cells):
        super().__init__()
        for c in cells:
            self.add(c)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        out = []
        for c in self._children.values():
            out.extend(c.state_info(batch_size))
        return out

    def begin_state(self, batch_size=0, func=None, **kwargs):
        states = []
        for c in self._children.values():
            states.extend(c.begin_state(batch_size, func, **kwargs))
        return states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def forward(self, inputs, states):
        next_states = []
        pos = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            cell_states = states[pos:pos + n]
            pos += n
            inputs, new_s = cell(inputs, cell_states)
            next_states.extend(new_s)
        return inputs, next_states


HybridSequentialRNNCell = SequentialRNNCell


class _ModifierCell(RecurrentCell):
    def __init__(self, base_cell):
        super().__init__()
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        return self.base_cell.begin_state(batch_size, func, **kwargs)


class DropoutCell(RecurrentCell):
    """≙ rnn_cell.py DropoutCell:838."""

    def __init__(self, rate, axes=()):
        super().__init__()
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def forward(self, inputs, states):
        if self._rate > 0:
            inputs = npx.dropout(inputs, p=self._rate, axes=self._axes or None)
        return inputs, states


class ZoneoutCell(_ModifierCell):
    """≙ rnn_cell.py ZoneoutCell:935 — stochastically preserve prior states."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def reset(self):
        self._prev_output = None

    def forward(self, inputs, states):
        out, new_states = self.base_cell(inputs, states)
        if autograd.is_training():
            def mask(p, like):
                # dropout(ones) is nonzero where the value is KEPT; zoneout
                # keeps the new value there and the previous one elsewhere
                return npx.dropout(mxnp.ones_like(like), p=p)
            if self._zo > 0:
                prev = self._prev_output if self._prev_output is not None \
                    else mxnp.zeros_like(out)
                m = mask(self._zo, out)
                out = mxnp.where(m != 0, out, prev)
            if self._zs > 0:
                new_states = [
                    mxnp.where(mask(self._zs, ns) != 0, ns, s)
                    for ns, s in zip(new_states, states)]
        self._prev_output = out
        return out, new_states


class ResidualCell(_ModifierCell):
    """≙ rnn_cell.py ResidualCell:984."""

    def forward(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        return out + inputs, states


class VariationalDropoutCell(_ModifierCell):
    """≙ rnn_cell.py VariationalDropoutCell:1110 — one dropout mask reused
    across time steps."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self._di, self._ds, self._do = drop_inputs, drop_states, drop_outputs
        self._mask_in = self._mask_out = self._mask_states = None

    def reset(self):
        self._mask_in = self._mask_out = self._mask_states = None

    def _mask(self, cached, p, like):
        if cached is None:
            cached = npx.dropout(mxnp.ones_like(like), p=p)
        return cached

    def forward(self, inputs, states):
        if autograd.is_training():
            if self._di > 0:
                self._mask_in = self._mask(self._mask_in, self._di, inputs)
                inputs = inputs * self._mask_in
            if self._ds > 0:
                self._mask_states = self._mask(self._mask_states, self._ds,
                                               states[0])
                states = [states[0] * self._mask_states] + list(states[1:])
        out, states = self.base_cell(inputs, states)
        if autograd.is_training() and self._do > 0:
            self._mask_out = self._mask(self._mask_out, self._do, out)
            out = out * self._mask_out
        return out, states


class BidirectionalCell(RecurrentCell):
    """≙ rnn_cell.py BidirectionalCell:1029 — unroll-only composite."""

    def __init__(self, l_cell, r_cell):
        super().__init__()
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    def state_info(self, batch_size=0):
        l, r = self._children.values()
        return l.state_info(batch_size) + r.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        l, r = self._children.values()
        return (l.begin_state(batch_size, func, **kwargs)
                + r.begin_state(batch_size, func, **kwargs))

    def forward(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped; use unroll()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        l_cell, r_cell = self._children.values()
        axis = layout.find("T")
        if not isinstance(inputs, (list, tuple)):
            if axis != 0:
                inputs = inputs.swapaxes(0, axis)
            seq = [inputs[t] for t in range(length)]
        else:
            seq = list(inputs)
        batch = seq[0].shape[0]
        states = begin_state if begin_state is not None \
            else self.begin_state(batch)
        nl = len(l_cell.state_info())
        l_states, r_states = states[:nl], states[nl:]
        l_outs, l_states = l_cell.unroll(length, seq, l_states,
                                         merge_outputs=False)
        r_outs, r_states = r_cell.unroll(length, list(reversed(seq)), r_states,
                                         merge_outputs=False)
        outs = [mxnp.concatenate([lo, ro], axis=-1)
                for lo, ro in zip(l_outs, reversed(r_outs))]
        if merge_outputs is None or merge_outputs:
            from ...ndarray import stack
            merged = stack(*outs, axis=axis)
            return merged, l_states + r_states
        return outs, l_states + r_states


# ---------------------------------------------------------------------------
# fused multi-layer recurrent layers (≙ rnn_layer.py → fused rnn op)
# ---------------------------------------------------------------------------
class _FusedRNNLayer(HybridBlock):
    def __init__(self, mode, hidden_size, num_layers=1, layout="TNC",
                 dropout=0.0, bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dtype="float32", **kwargs):
        super().__init__()
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"invalid layout {layout}")
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        ng = {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}[mode]
        self._gates = ng
        for layer in range(num_layers):
            for d in range(self._dir):
                pre = f"{'lr'[0] if d == 0 else 'r'}{layer}_"
                in_sz = input_size if layer == 0 \
                    else hidden_size * self._dir
                suffix = "l" if d == 0 else "r"
                setattr(self, f"{suffix}{layer}_i2h_weight",
                        _cell_param((ng * hidden_size, in_sz if in_sz else 0),
                                    i2h_weight_initializer, "i2h_weight"))
                setattr(self, f"{suffix}{layer}_h2h_weight",
                        _cell_param((ng * hidden_size, hidden_size),
                                    h2h_weight_initializer, "h2h_weight"))
                setattr(self, f"{suffix}{layer}_i2h_bias",
                        _cell_param((ng * hidden_size,),
                                    i2h_bias_initializer, "i2h_bias"))
                setattr(self, f"{suffix}{layer}_h2h_bias",
                        _cell_param((ng * hidden_size,),
                                    h2h_bias_initializer, "h2h_bias"))

    def _layer_params(self):
        out = []
        for layer in range(self._num_layers):
            for d in range(self._dir):
                suffix = "l" if d == 0 else "r"
                out.append(tuple(
                    getattr(self, f"{suffix}{layer}_{n}")
                    for n in ("i2h_weight", "h2h_weight", "i2h_bias",
                              "h2h_bias")))
        return out

    def infer_shape(self, inputs, *args):
        in_sz = inputs.shape[-1]
        for layer in range(self._num_layers):
            for d in range(self._dir):
                suffix = "l" if d == 0 else "r"
                p = getattr(self, f"{suffix}{layer}_i2h_weight")
                layer_in = in_sz if layer == 0 else self._hidden_size * self._dir
                p.shape = (p.shape[0], layer_in)

    def state_info(self, batch_size=0):
        L = self._num_layers * self._dir
        shape = (L, batch_size, self._hidden_size)
        if self._mode == "lstm":
            return [{"shape": shape}, {"shape": shape}]
        return [{"shape": shape}]

    def begin_state(self, batch_size=0, func=None, **kwargs):
        return [mxnp.zeros(i["shape"]) for i in self.state_info(batch_size)]

    def __call__(self, inputs, states=None, **kwargs):
        return super().__call__(inputs, *([states] if states is not None
                                          else []))

    def forward(self, inputs, states=None):
        time_major = self._layout == "TNC"
        explicit_states = states is not None
        batch = inputs.shape[1] if time_major else inputs.shape[0]
        if states is None:
            states = self.begin_state(batch)
        if isinstance(states, NDArray):
            states = [states]
        x = inputs if time_major else inputs.swapaxes(0, 1)

        layer_params = self._layer_params()
        flat = []
        for tup in layer_params:
            flat.extend(p.data() for p in tup)
        mode, L, D = self._mode, self._num_layers, self._dir
        dropout_rate = self._dropout
        training = autograd.is_training()
        key = _random.next_key() if (dropout_rate > 0 and training) else None

        def run(x_raw, *rest):
            ns = len(states)
            st = rest[:ns]
            ws = rest[ns:]
            params = {}
            i = 0
            for layer in range(L):
                for d in range(D):
                    params[(layer, d)] = {"wx": ws[i], "wh": ws[i + 1],
                                          "bx": ws[i + 2], "bh": ws[i + 3]}
                    i += 4
            out, new_state = _nn.rnn(x_raw, params, st, mode=mode,
                                     num_layers=L, bidirectional=(D == 2),
                                     dropout_rate=dropout_rate, key=key,
                                     training=training)
            return (out,) + tuple(new_state)

        res = invoke(run, (x,) + tuple(states) + tuple(flat),
                     name=f"rnn_{mode}", multi_out=True)
        out, new_states = res[0], list(res[1:])
        if not time_major:
            out = out.swapaxes(0, 1)
        if explicit_states:
            return out, new_states
        return out

    def __repr__(self):
        return (f"{type(self).__name__}({self._input_size} -> "
                f"{self._hidden_size}, layers={self._num_layers}, "
                f"bidirectional={self._dir == 2})")


class RNN(_FusedRNNLayer):
    """≙ rnn_layer.py RNN:260."""

    def __init__(self, hidden_size, num_layers=1, activation="tanh", **kwargs):
        mode = "rnn_relu" if activation == "relu" else "rnn_tanh"
        super().__init__(mode, hidden_size, num_layers, **kwargs)


class LSTM(_FusedRNNLayer):
    """≙ rnn_layer.py LSTM:353."""

    def __init__(self, hidden_size, num_layers=1, **kwargs):
        super().__init__("lstm", hidden_size, num_layers, **kwargs)


class GRU(_FusedRNNLayer):
    """≙ rnn_layer.py GRU:480."""

    def __init__(self, hidden_size, num_layers=1, **kwargs):
        super().__init__("gru", hidden_size, num_layers, **kwargs)


# ---------------------------------------------------------------------------
# Convolutional recurrent cells (≙ python/mxnet/gluon/rnn/conv_rnn_cell.py:
# Conv{1D,2D,3D}{RNN,LSTM,GRU}Cell — gates computed by convolutions over
# spatially-structured states). TPU-native: gate convs are lax convs via
# npx.convolution, so a cell step fuses into one XLA program under
# hybridize/unroll.
# ---------------------------------------------------------------------------
class _ConvGateCell(RecurrentCell):
    """Shared conv-gate plumbing. input_shape is (C, *spatial) — required
    up front (the reference also needs it: state shape depends on it)."""

    def __init__(self, input_shape, hidden_channels, num_gates,
                 i2h_kernel=(3, 3), h2h_kernel=(3, 3),
                 i2h_pad=None, conv_layout="NCHW",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 activation="tanh"):
        super().__init__()
        if not conv_layout.startswith("NC"):
            raise MXNetError("conv cells use channel-first layouts")
        self._input_shape = tuple(input_shape)
        self._hc = hidden_channels
        self._ng = num_gates
        nd = len(self._input_shape) - 1
        derived_layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[nd]
        if conv_layout not in ("NCHW", derived_layout):
            # "NCHW" is the reference default arg; anything else must
            # match the rank implied by input_shape
            raise MXNetError(
                f"conv_layout {conv_layout!r} does not match input_shape "
                f"{input_shape} (expected {derived_layout!r})")
        def _t(v):
            return (v,) * nd if isinstance(v, int) else tuple(v)
        self._i2h_kernel = _t(i2h_kernel)
        self._h2h_kernel = _t(h2h_kernel)
        if any(k % 2 == 0 for k in self._i2h_kernel + self._h2h_kernel):
            # even kernels break the recurrence: k//2 padding would grow
            # the state spatially every step (same constraint as the
            # reference's conv cells in practice)
            raise MXNetError(
                "conv cell kernels must be odd so SAME padding keeps "
                f"state spatial dims constant; got i2h={self._i2h_kernel} "
                f"h2h={self._h2h_kernel}")
        # SAME padding so state spatial dims stay constant
        self._i2h_pad = _t(i2h_pad) if i2h_pad is not None else tuple(
            k // 2 for k in self._i2h_kernel)
        self._h2h_pad = tuple(k // 2 for k in self._h2h_kernel)
        self._act = activation
        in_c = self._input_shape[0]
        self._spatial = self._input_shape[1:]
        self._layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[nd]
        self.i2h_weight = _cell_param(
            (num_gates * hidden_channels, in_c) + self._i2h_kernel,
            i2h_weight_initializer, "i2h_weight")
        self.h2h_weight = _cell_param(
            (num_gates * hidden_channels, hidden_channels)
            + self._h2h_kernel,
            h2h_weight_initializer, "h2h_weight")
        self.i2h_bias = _cell_param((num_gates * hidden_channels,),
                                    i2h_bias_initializer, "i2h_bias")
        self.h2h_bias = _cell_param((num_gates * hidden_channels,),
                                    h2h_bias_initializer, "h2h_bias")

    def state_info(self, batch_size=0):
        shape = (batch_size, self._hc) + self._spatial
        n_states = 2 if self._ng == 4 else 1
        return [{"shape": shape, "__layout__": "NC" + "DHW"[-len(
            self._spatial):]} for _ in range(n_states)]

    def _gates(self, inputs, h):
        """Returns (gi, gh) separately — the GRU needs the h-branch
        pre-activation apart; RNN/LSTM callers sum them."""
        gi = npx.convolution(inputs, self.i2h_weight.data(),
                             self.i2h_bias.data(), pad=self._i2h_pad,
                             num_filter=self._ng * self._hc,
                             layout=self._layout)
        gh = npx.convolution(h, self.h2h_weight.data(),
                             self.h2h_bias.data(), pad=self._h2h_pad,
                             num_filter=self._ng * self._hc,
                             layout=self._layout)
        return gi, gh

    def _split_gates(self, g):
        return [g[:, i * self._hc:(i + 1) * self._hc] for i in
                range(self._ng)]


class _ConvRNNCellImpl(_ConvGateCell):
    def __init__(self, input_shape, hidden_channels, **kw):
        super().__init__(input_shape, hidden_channels, 1, **kw)

    def forward(self, inputs, states):
        gi, gh = self._gates(inputs, states[0])
        out = npx.activation(gi + gh, act_type=self._act)
        return out, [out]


class _ConvLSTMCellImpl(_ConvGateCell):
    def __init__(self, input_shape, hidden_channels, **kw):
        super().__init__(input_shape, hidden_channels, 4, **kw)

    def forward(self, inputs, states):
        h0, c0 = states
        gi, gh = self._gates(inputs, h0)
        i, f, g, o = self._split_gates(gi + gh)
        i = npx.activation(i, act_type="sigmoid")
        f = npx.activation(f, act_type="sigmoid")
        g = npx.activation(g, act_type=self._act)
        o = npx.activation(o, act_type="sigmoid")
        c = f * c0 + i * g
        h = o * npx.activation(c, act_type=self._act)
        return h, [h, c]


class _ConvGRUCellImpl(_ConvGateCell):
    def __init__(self, input_shape, hidden_channels, **kw):
        super().__init__(input_shape, hidden_channels, 3, **kw)

    def forward(self, inputs, states):
        h0 = states[0]
        gi, gh = self._gates(inputs, h0)
        ir, iz, inw = [gi[:, k * self._hc:(k + 1) * self._hc]
                       for k in range(3)]
        hr, hz, hnw = [gh[:, k * self._hc:(k + 1) * self._hc]
                       for k in range(3)]
        r = npx.activation(ir + hr, act_type="sigmoid")
        z = npx.activation(iz + hz, act_type="sigmoid")
        n = npx.activation(inw + r * hnw, act_type=self._act)
        out = (1 - z) * n + z * h0
        return out, [out]


def _conv_cell_family(impl, suffix):
    """1D/2D/3D named classes over one implementation (kernel rank comes
    from input_shape; the named classes validate it, reference-style)."""
    classes = {}
    for nd, name in ((1, "Conv1D"), (2, "Conv2D"), (3, "Conv3D")):
        def _make(nd=nd, name=name):
            class Cell(impl):
                def __init__(self, input_shape, hidden_channels,
                             i2h_kernel=3, h2h_kernel=3, **kw):
                    if len(tuple(input_shape)) != nd + 1:
                        raise MXNetError(
                            f"{name}{suffix} needs input_shape of "
                            f"(C, {'x'.join('S' * nd)}), got {input_shape}")
                    if isinstance(i2h_kernel, int):
                        i2h_kernel = (i2h_kernel,) * nd
                    if isinstance(h2h_kernel, int):
                        h2h_kernel = (h2h_kernel,) * nd
                    super().__init__(input_shape, hidden_channels,
                                     i2h_kernel=i2h_kernel,
                                     h2h_kernel=h2h_kernel, **kw)
            Cell.__name__ = f"{name}{suffix}"
            Cell.__qualname__ = Cell.__name__
            Cell.__doc__ = (f"≙ rnn.conv_rnn_cell.{name}{suffix} "
                            "(conv_rnn_cell.py)")
            return Cell
        classes[f"{name}{suffix}"] = _make()
    return classes


for _cls_name, _cls in {**_conv_cell_family(_ConvRNNCellImpl, "RNNCell"),
                        **_conv_cell_family(_ConvLSTMCellImpl, "LSTMCell"),
                        **_conv_cell_family(_ConvGRUCellImpl, "GRUCell")
                        }.items():
    globals()[_cls_name] = _cls
    __all__.append(_cls_name)
