"""gluon.loss — loss functions.

Reference: python/mxnet/gluon/loss.py (L1Loss, L2Loss, SigmoidBCELoss,
SoftmaxCELoss, KLDivLoss, CTCLoss, HuberLoss, HingeLoss, SquaredHingeLoss,
LogisticLoss, TripletLoss, PoissonNLLLoss, CosineEmbeddingLoss, SDMLLoss).
Semantics preserved: per-example loss with `weight` scaling and
`sample_weight` broadcasting (`_apply_weighting`), `batch_axis` mean.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .. import numpy as mxnp
from .. import numpy_extension as npx
from .block import HybridBlock

__all__ = [
    "Loss", "L1Loss", "L2Loss", "SigmoidBinaryCrossEntropyLoss",
    "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss", "KLDivLoss",
    "CTCLoss", "HuberLoss", "HingeLoss", "SquaredHingeLoss", "LogisticLoss",
    "TripletLoss", "PoissonNLLLoss", "CosineEmbeddingLoss",
]


def _apply_weighting(loss, weight=None, sample_weight=None):
    """≙ gluon.loss._apply_weighting."""
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


def _batch_mean(loss, batch_axis):
    axes = tuple(i for i in range(loss.ndim) if i != batch_axis)
    return loss.mean(axis=axes) if axes else loss


class Loss(HybridBlock):
    """Base loss (≙ gluon.loss.Loss)."""

    def __init__(self, weight=None, batch_axis=0):
        super().__init__()
        self._weight = weight
        self._batch_axis = batch_axis

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(batch_axis={self._batch_axis}, w={self._weight})"


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0):
        super().__init__(weight, batch_axis)

    def forward(self, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        loss = mxnp.abs(label - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class L2Loss(Loss):
    """0.5 * (pred - label)^2 (reference keeps the 1/2 factor)."""

    def __init__(self, weight=1.0, batch_axis=0):
        super().__init__(weight, batch_axis)

    def forward(self, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        loss = mxnp.square(label - pred)
        loss = _apply_weighting(loss, self._weight / 2, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0):
        super().__init__(weight, batch_axis)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None, pos_weight=None):
        label = label.reshape(pred.shape)
        if not self._from_sigmoid:
            # log(1+exp(x)) stable form: max(x,0) - x*z + log(1+exp(-|x|))
            relu_x = mxnp.maximum(pred, 0)
            softplus = mxnp.log1p(mxnp.exp(-mxnp.abs(pred)))
            if pos_weight is None:
                loss = relu_x - pred * label + softplus
            else:
                w = (pos_weight - 1) * label + 1
                loss = relu_x - pred * label + w * softplus \
                    + (w - 1) * mxnp.maximum(-pred, 0)
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(mxnp.log(pred + eps) * label
                         + mxnp.log(1 - pred + eps) * (1 - label))
            else:
                loss = -(mxnp.log(pred + eps) * label * pos_weight
                         + mxnp.log(1 - pred + eps) * (1 - label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """≙ gluon.loss.SoftmaxCrossEntropyLoss (sparse_label / from_logits)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0):
        super().__init__(weight, batch_axis)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = npx.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -npx.pick(pred, label, axis=self._axis, keepdims=False)
        else:
            label = label.reshape(pred.shape)
            loss = -(pred * label).sum(axis=self._axis)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0):
        super().__init__(weight, batch_axis)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = npx.log_softmax(pred, axis=self._axis)
        loss = label * (mxnp.log(label + 1e-12) - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class CTCLoss(Loss):
    """Connectionist temporal classification (≙ gluon.loss.CTCLoss;
    kernel src/operator/nn/ctc_loss.cc). TPU-native: vectorized
    alpha-recursion in log space via lax.scan over time."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None):
        if layout not in ("NTC", "TNC"):
            raise MXNetError(f"unsupported layout {layout}")
        super().__init__(weight, 0 if layout.startswith("N") else 1)
        self._layout = layout
        self._label_layout = label_layout

    def forward(self, pred, label, pred_lengths=None, label_lengths=None,
                sample_weight=None):
        from ..ops.registry import invoke
        from ..ndarray import _as_nd
        if self._layout == "TNC":
            pred = pred.swapaxes(0, 1)  # -> NTC
        if self._label_layout == "TN":
            label = label.swapaxes(0, 1)
        args = [_as_nd(pred), _as_nd(label)]
        if pred_lengths is not None:
            args.append(_as_nd(pred_lengths))
        if label_lengths is not None:
            args.append(_as_nd(label_lengths))
        loss = invoke(lambda *a: _ctc_loss_raw(*a), tuple(args), name="ctc_loss")
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss


def _ctc_loss_raw(logits, labels, pred_lengths=None, label_lengths=None,
                  blank=0):
    """log-domain CTC forward algorithm. logits: (N, T, C); labels: (N, L)."""
    import jax
    import jax.numpy as jnp
    N, T, C = logits.shape
    L = labels.shape[1]
    labels = labels.astype(jnp.int32)
    if pred_lengths is None:
        pred_lengths = jnp.full((N,), T, dtype=jnp.int32)
    else:
        pred_lengths = pred_lengths.astype(jnp.int32)
    if label_lengths is None:
        label_lengths = jnp.sum((labels != blank).astype(jnp.int32), axis=1)
    else:
        label_lengths = label_lengths.astype(jnp.int32)

    logp = jax.nn.log_softmax(logits, axis=-1)  # (N,T,C)
    # extended label sequence with blanks: (N, 2L+1)
    S = 2 * L + 1
    ext = jnp.full((N, S), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    neg_inf = jnp.asarray(-1e30, logp.dtype)

    # alpha init
    alpha0 = jnp.full((N, S), neg_inf, logp.dtype)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    first_lab = jnp.take_along_axis(logp[:, 0, :], ext[:, 1:2], axis=1)[:, 0]
    alpha0 = alpha0.at[:, 1].set(jnp.where(label_lengths > 0, first_lab, neg_inf))

    same_as_prev2 = jnp.concatenate(
        [jnp.ones((N, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

    def step(alpha, t):
        lp = jnp.take_along_axis(logp[:, t, :], ext, axis=1)  # (N,S)
        a_shift1 = jnp.concatenate(
            [jnp.full((N, 1), neg_inf, logp.dtype), alpha[:, :-1]], axis=1)
        a_shift2 = jnp.concatenate(
            [jnp.full((N, 2), neg_inf, logp.dtype), alpha[:, :-2]], axis=1)
        a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
        new = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a_shift2) + lp
        # freeze past each sequence's length
        new = jnp.where((t < pred_lengths)[:, None], new, alpha)
        return new, None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    s_last = 2 * label_lengths  # index of final blank
    ll_blank = jnp.take_along_axis(alpha, s_last[:, None], axis=1)[:, 0]
    ll_label = jnp.take_along_axis(
        alpha, jnp.maximum(s_last - 1, 0)[:, None], axis=1)[:, 0]
    ll_label = jnp.where(label_lengths > 0, ll_label, neg_inf)
    return -jnp.logaddexp(ll_blank, ll_label)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0):
        super().__init__(weight, batch_axis)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        err = mxnp.abs(label - pred)
        loss = mxnp.where(err > self._rho,
                          err - 0.5 * self._rho,
                          (0.5 / self._rho) * mxnp.square(err))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0):
        super().__init__(weight, batch_axis)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        loss = mxnp.maximum(self._margin - pred * label, 0)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0):
        super().__init__(weight, batch_axis)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        loss = mxnp.square(mxnp.maximum(self._margin - pred * label, 0))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed"):
        super().__init__(weight, batch_axis)
        if label_format not in ("signed", "binary"):
            raise MXNetError(f"bad label_format {label_format}")
        self._label_format = label_format

    def forward(self, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = (mxnp.maximum(pred, 0) - pred * label
                + mxnp.log1p(mxnp.exp(-mxnp.abs(pred))))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0):
        super().__init__(weight, batch_axis)
        self._margin = margin

    def forward(self, pred, positive, negative, sample_weight=None):
        positive = positive.reshape(pred.shape)
        negative = negative.reshape(pred.shape)
        loss = (mxnp.square(pred - positive)
                - mxnp.square(pred - negative))
        loss = _batch_mean(loss, self._batch_axis)
        loss = mxnp.maximum(loss + self._margin, 0)
        return _apply_weighting(loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False):
        super().__init__(weight, batch_axis)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def forward(self, pred, target, sample_weight=None, epsilon=1e-08):
        target = target.reshape(pred.shape)
        if self._from_logits:
            loss = mxnp.exp(pred) - target * pred
        else:
            loss = pred - target * mxnp.log(pred + epsilon)
        if self._compute_full:
            stirling = (target * mxnp.log(target + epsilon) - target
                        + 0.5 * mxnp.log(2 * _np.pi * (target + epsilon)))
            stirling = stirling * (target > 1)
            loss = loss + stirling
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean()


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0):
        super().__init__(weight, batch_axis)
        self._margin = margin

    def forward(self, input1, input2, label, sample_weight=None):
        input1 = input1.reshape((input1.shape[0], -1))
        input2 = input2.reshape((input2.shape[0], -1))
        eps = 1e-12
        num = (input1 * input2).sum(axis=1)
        den = mxnp.sqrt(mxnp.square(input1).sum(axis=1)
                        * mxnp.square(input2).sum(axis=1) + eps)
        cos = num / den
        label = label.reshape((-1,))
        loss = mxnp.where(label == 1, 1.0 - cos,
                          mxnp.maximum(cos - self._margin, 0))
        return _apply_weighting(loss, self._weight, sample_weight)
