"""Datasets (≙ python/mxnet/gluon/data/dataset.py + the C++ 2.0 datasets
src/io/dataset.cc — random-access only; streaming iterators live in mx.io)."""
from __future__ import annotations

import os

import numpy as _np

from ...base import MXNetError

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    """Random-access dataset (≙ gluon.data.Dataset)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        """≙ Dataset.filter."""
        return SimpleDataset([self[i] for i in range(len(self))
                              if fn(self[i])])

    def shard(self, num_shards, index):
        """≙ Dataset.shard — partition for multi-worker loading."""
        assert 0 <= index < num_shards
        length = len(self)
        shard_len = length // num_shards
        rest = length % num_shards
        start = shard_len * index + min(index, rest)
        end = start + shard_len + (index < rest)
        return SimpleDataset([self[i] for i in range(start, end)])

    def take(self, count):
        count = min(count, len(self))
        return SimpleDataset([self[i] for i in range(count)])

    def transform(self, fn, lazy=True):
        """≙ Dataset.transform."""
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        """≙ Dataset.transform_first."""
        return self.transform(_first_wrapper(fn), lazy)


def _first_wrapper(fn):
    def _f(sample):
        if isinstance(sample, tuple):
            return (fn(sample[0]),) + sample[1:]
        return fn(sample)
    return _f


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple) and _accepts_multi(self._fn, len(item)):
            return self._fn(*item)
        return self._fn(item)


def _accepts_multi(fn, n):
    import inspect
    try:
        sig = inspect.signature(fn)
        params = [p for p in sig.parameters.values()
                  if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
        if any(p.kind == inspect.Parameter.VAR_POSITIONAL
               for p in sig.parameters.values()):
            return True
        return len(params) >= n
    except (TypeError, ValueError):
        return False


class SimpleDataset(Dataset):
    """List wrapper (≙ gluon.data.SimpleDataset)."""

    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class ArrayDataset(Dataset):
    """Zip of arrays/datasets (≙ gluon.data.ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for a in args:
            if len(a) != self._length:
                raise MXNetError("all inputs must have the same length")
            self._data.append(a)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Record file dataset over .rec/.idx (≙ gluon.data.RecordFileDataset,
    C++ fast path src/io/dataset.cc RecordFileDataset)."""

    def __init__(self, filename):
        from ...recordio import MXIndexedRecordIO
        self._filename = filename
        idx_file = os.path.splitext(filename)[0] + ".idx"
        self._record = MXIndexedRecordIO(idx_file, filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
