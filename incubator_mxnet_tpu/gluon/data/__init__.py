"""gluon.data (≙ python/mxnet/gluon/data/): Dataset/Sampler/DataLoader."""
from .dataset import (Dataset, SimpleDataset, ArrayDataset,
                      RecordFileDataset, _LazyTransformDataset)
from .sampler import (Sampler, SequentialSampler, RandomSampler, BatchSampler,
                      FilterSampler)
from .dataloader import DataLoader, default_batchify_fn
from . import batchify
from . import vision
