"""DataLoader (≙ python/mxnet/gluon/data/dataloader.py:307/514).

TPU-native design: the reference forks worker *processes* and ships batches
through POSIX shared memory (CPUSharedStorageManager) because its decode
path is GIL-bound C++ calls. Here batches are numpy work: a thread pool
(decode/augment release the GIL in numpy/PIL) prefetches `prefetch` batches
ahead and the main thread uploads them to the device — double-buffering host
→ HBM copies behind the step (≙ the PrefetcherIter double buffer,
src/io/iter_prefetcher.h). Worker processes are unnecessary and actively
harmful with a live PJRT client (fork-safety), mirroring the reference's own
fork-handler dance (src/initialize.cc) — thread mode is its
`thread_pool=True` path."""
from __future__ import annotations

import itertools
from concurrent.futures import ThreadPoolExecutor

import numpy as _np

from ...base import MXNetError
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (≙ dataloader.default_batchify_fn)."""
    from ...ndarray import NDArray, array
    if isinstance(data[0], NDArray):
        from ...ndarray import stack
        return stack(*data, axis=0)
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn(list(s)) for s in zip(*data))
    arr = _np.asarray(data)
    return array(arr)


default_mp_batchify_fn = default_batchify_fn


class DataLoader:
    """≙ gluon.data.DataLoader(dataset, batch_size, shuffle, sampler,
    last_batch, batch_sampler, batchify_fn, num_workers, pin_memory,
    prefetch, thread_pool)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=True, timeout=120,
                 try_nopython=None):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size required when no batch_sampler")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle conflicts with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError("batch_sampler conflicts with batch_size/"
                             "shuffle/sampler/last_batch")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._pin_memory = pin_memory

    def _make_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            it = iter(self._batch_sampler)
            pending = []
            for indices in itertools.islice(it, self._prefetch + 1):
                pending.append(pool.submit(self._make_batch, indices))
            while pending:
                fut = pending.pop(0)
                nxt = next(it, None)
                if nxt is not None:
                    pending.append(pool.submit(self._make_batch, nxt))
                yield fut.result()

    def __len__(self):
        return len(self._batch_sampler)
