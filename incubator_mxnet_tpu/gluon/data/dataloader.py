"""DataLoader (≙ python/mxnet/gluon/data/dataloader.py:307/514).

TPU-native design: the reference forks worker *processes* and ships batches
through POSIX shared memory (CPUSharedStorageManager) because its decode
path is GIL-bound C++ calls. Here batches are numpy work: a thread pool
(decode/augment release the GIL in numpy/PIL) prefetches `prefetch` batches
ahead and the main thread uploads them to the device — double-buffering host
→ HBM copies behind the step (≙ the PrefetcherIter double buffer,
src/io/iter_prefetcher.h). Worker processes are unnecessary and actively
harmful with a live PJRT client (fork-safety), mirroring the reference's own
fork-handler dance (src/initialize.cc) — thread mode is its
`thread_pool=True` path."""
from __future__ import annotations

import itertools
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeoutError

import numpy as _np

from ...base import MXNetError
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (≙ dataloader.default_batchify_fn)."""
    from ...ndarray import NDArray, array
    if isinstance(data[0], NDArray):
        from ...ndarray import stack
        return stack(*data, axis=0)
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn(list(s)) for s in zip(*data))
    arr = _np.asarray(data)
    return array(arr)


default_mp_batchify_fn = default_batchify_fn


class DataLoader:
    """≙ gluon.data.DataLoader(dataset, batch_size, shuffle, sampler,
    last_batch, batch_sampler, batchify_fn, num_workers, pin_memory,
    prefetch, thread_pool)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=True, timeout=120,
                 try_nopython=None, prefetch_to_device=None):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size required when no batch_sampler")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle conflicts with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError("batch_sampler conflicts with batch_size/"
                             "shuffle/sampler/last_batch")
        self._batch_sampler = batch_sampler
        self._use_processes = (not thread_pool) and num_workers > 0
        if self._use_processes and batchify_fn is None:
            batchify_fn = _host_batchify
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._pin_memory = pin_memory
        self._timeout = timeout
        # transient fetch errors (flaky storage, network FS) retry with
        # backoff instead of killing the epoch; bound via
        # MXNET_DATALOADER_RETRIES (default 3 attempts). Wrapped once here,
        # not per batch — _make_batch is the hot path.
        from ... import fault as _fault
        from ...base import get_env
        self._make_batch = _fault.retrying(
            max_attempts=get_env("MXNET_DATALOADER_RETRIES", 3, typ=int),
            name="dataloader.fetch")(self._fetch_batch)
        # opt-in device prefetch (MXNET_PREFETCH_TO_DEVICE, or the explicit
        # kwarg): batches stage onto the device through io.DeviceFeed so
        # host assembly + H2D overlap the consumer's step
        self._prefetch_to_device = (
            get_env("MXNET_PREFETCH_TO_DEVICE", False, typ=bool)
            if prefetch_to_device is None else bool(prefetch_to_device))
        self._feeds_device = self._prefetch_to_device
        # an EXPLICIT falsy prefetch_to_device is an opt-out that downstream
        # wrappers (estimator.fit's env-driven wrap) must respect
        self._prefetch_opt_out = (prefetch_to_device is not None
                                  and not prefetch_to_device)

    def _fetch_batch(self, indices):
        from ... import fault as _fault
        _fault.inject("dataloader.fetch")
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._prefetch_to_device:
            from ...io.device_feed import DeviceFeed
            feed = DeviceFeed(self._host_iter())
            try:
                yield from feed
            finally:
                feed.close()
            return
        yield from self._host_iter()

    def _host_iter(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        if self._use_processes:
            yield from self._iter_processes()
            return
        pool = ThreadPoolExecutor(max_workers=self._num_workers)
        stalled = False
        try:
            it = iter(self._batch_sampler)
            pending = []
            for indices in itertools.islice(it, self._prefetch + 1):
                pending.append(pool.submit(self._make_batch, indices))
            while pending:
                fut = pending.pop(0)
                nxt = next(it, None)
                if nxt is not None:
                    pending.append(pool.submit(self._make_batch, nxt))
                try:
                    yield fut.result(timeout=self._timeout)
                except FuturesTimeoutError:
                    stalled = True
                    raise MXNetError(
                        f"DataLoader batch fetch exceeded {self._timeout}s "
                        "(worker stalled; raise timeout= or check the "
                        "dataset's I/O)") from None
        finally:
            # on stall, skip the join so the timeout error surfaces to the
            # caller now instead of hanging here; a truly wedged worker is
            # non-daemon and may still delay interpreter exit — the caller
            # gets the chance to report and abort cleanly
            pool.shutdown(wait=not stalled, cancel_futures=True)

    def _iter_processes(self):
        """Spawned process workers + shared-memory batch rebuild
        (≙ reference worker_loop; thread_pool=False, num_workers>0)."""
        import multiprocessing as mp
        import pickle
        from concurrent.futures import ProcessPoolExecutor
        ctx = mp.get_context("spawn")
        payload = pickle.dumps((self._dataset, self._batchify_fn))
        pending = []
        with ProcessPoolExecutor(
                max_workers=self._num_workers, mp_context=ctx,
                initializer=_mp_worker_init,
                initargs=(payload,)) as pool:
            try:
                it = iter(self._batch_sampler)
                for indices in itertools.islice(it, self._prefetch + 1):
                    pending.append(
                        pool.submit(_mp_worker_batch, list(indices)))
                while pending:
                    fut = pending.pop(0)
                    nxt = next(it, None)
                    if nxt is not None:
                        pending.append(
                            pool.submit(_mp_worker_batch, list(nxt)))
                    spec, descs = fut.result(timeout=self._timeout)
                    yield _rebuild_batch(spec, descs)
            finally:
                # the PARENT owns every produced block (workers unregister
                # them): on early exit / error, drain or cancel pending
                # futures and unlink their segments, else up to prefetch+1
                # batches of /dev/shm leak per abandoned epoch
                for fut in pending:
                    if not fut.cancel():
                        try:
                            _, descs = fut.result(timeout=self._timeout)
                            _release_descs(descs)
                        except Exception:
                            pass

    def __len__(self):
        return len(self._batch_sampler)


# ---------------------------------------------------------------------------
# Multiprocessing workers + shared-memory batch rebuild (≙ the reference's
# worker_loop + CPUSharedStorageManager, dataloader.py:47-88,514). For
# GIL-BOUND Python transforms on multi-core hosts; numpy/PIL-heavy
# pipelines usually do as well in thread mode (the default).
#
# Safety model: workers are SPAWNED (never forked — a live PJRT client is
# not fork-safe) and pin JAX_PLATFORMS=cpu before anything imports jax, so
# a dataset that materializes NDArrays cannot grab the accelerator.
# Batches travel as multiprocessing.shared_memory blocks: the worker
# assembles host arrays straight into the block, the parent wraps views
# and uploads to the device — one copy on each side, no pickling of bulk
# data.
# ---------------------------------------------------------------------------

_MP_STATE = {}


def _host_array(x):
    if hasattr(x, "asnumpy"):
        return x.asnumpy()
    return _np.asarray(x)


def _host_batchify(data):
    """Worker-side batchify: numpy in, numpy out (no NDArray creation)."""
    if isinstance(data[0], (tuple, list)):
        return tuple(_host_batchify(list(s)) for s in zip(*data))
    return _np.stack([_host_array(d) for d in data])


def _mp_worker_init(payload):
    # the dataset/batchify travel as PICKLED BYTES: plain bytes deserialize
    # without importing anything, so the platform pin below runs before a
    # dataset containing NDArrays can initialize a jax backend (initargs
    # themselves are unpickled before the initializer executes)
    import os
    import pickle
    os.environ["JAX_PLATFORMS"] = "cpu"
    dataset, batchify_fn = pickle.loads(payload)
    _MP_STATE["dataset"] = dataset
    _MP_STATE["batchify"] = batchify_fn


def _flatten_batch(batch):
    if isinstance(batch, (tuple, list)):
        leaves, subspecs = [], []
        for b in batch:
            sub_leaves, sub_spec = _flatten_batch(b)
            leaves.extend(sub_leaves)
            subspecs.append(sub_spec)
        kind = "tuple" if isinstance(batch, tuple) else "list"
        return leaves, (kind, subspecs)
    if isinstance(batch, dict):
        keys = list(batch)
        leaves, subspecs = [], []
        for k in keys:
            sub_leaves, sub_spec = _flatten_batch(batch[k])
            leaves.extend(sub_leaves)
            subspecs.append(sub_spec)
        return leaves, ("dict", keys, subspecs)
    return [batch], None


def _unflatten_batch(spec, leaves_iter):
    if spec is None:
        return next(leaves_iter)
    if spec[0] == "dict":
        _, keys, subspecs = spec
        return {k: _unflatten_batch(s, leaves_iter)
                for k, s in zip(keys, subspecs)}
    kind, subspecs = spec
    seq = [_unflatten_batch(s, leaves_iter) for s in subspecs]
    return tuple(seq) if kind == "tuple" else seq


def _mp_worker_batch(indices):
    from multiprocessing import resource_tracker, shared_memory
    ds = _MP_STATE["dataset"]
    fn = _MP_STATE["batchify"]
    samples = [ds[i] for i in indices]
    batch = fn(samples)
    leaves, spec = _flatten_batch(batch)
    descs = []
    for a in leaves:
        a = _np.ascontiguousarray(_host_array(a))
        shm = shared_memory.SharedMemory(create=True,
                                         size=max(a.nbytes, 1))
        view = _np.ndarray(a.shape, a.dtype, buffer=shm.buf)
        view[...] = a
        descs.append((shm.name, a.shape, str(a.dtype)))
        shm.close()
        # ownership transfers to the parent (which unlinks after upload);
        # without unregistering, this process's resource tracker would
        # whine about a "leaked" block it no longer owns
        try:
            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:
            pass
    return spec, descs


def _release_descs(descs):
    """Unlink produced-but-unconsumed shared-memory blocks."""
    from multiprocessing import shared_memory
    for name, _shape, _dtype in descs:
        try:
            shm = shared_memory.SharedMemory(name=name)
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass


def _rebuild_batch(spec, descs):
    """Parent side: attach each block, upload to device, release."""
    from multiprocessing import shared_memory

    from ...ndarray import array
    leaves = []
    for name, shape, dtype in descs:
        shm = shared_memory.SharedMemory(name=name)
        try:
            view = _np.ndarray(tuple(shape), _np.dtype(dtype),
                               buffer=shm.buf)
            leaves.append(array(view.copy()))
        finally:
            shm.close()
            shm.unlink()
    return _unflatten_batch(spec, iter(leaves))
