"""Batchify functions (≙ python/mxnet/gluon/data/batchify.py: Stack, Pad,
Group — composable sample→batch assemblers used by DataLoader and exposed
through the MXBatchifyFunction* C ABI group).

TPU-native note: outputs are NDArrays (device arrays); Pad right-pads each
sample to the batch-max length per axis so the batch is rectangular —
static shapes are what XLA wants downstream.
"""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError

__all__ = ["Stack", "Pad", "Group"]


def _as_host(x):
    from ...ndarray import NDArray
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


class Stack:
    """Stack samples along a new batch axis (≙ batchify.Stack)."""

    def __call__(self, data):
        from ... import np as mxnp
        return mxnp.array(_np.stack([_as_host(d) for d in data]))


class Pad:
    """Pad ragged samples to the batch max length per axis, then stack
    (≙ batchify.Pad). `val` fills; `dtype` optionally overrides."""

    def __init__(self, axis=0, val=0, dtype=None):
        self._axis = int(axis)
        self._val = val
        self._dtype = dtype

    def __call__(self, data):
        from ... import np as mxnp
        arrs = [_as_host(d) for d in data]
        ndim = arrs[0].ndim
        if any(a.ndim != ndim for a in arrs):
            raise MXNetError("Pad needs samples of equal rank")
        max_shape = [max(a.shape[i] for a in arrs) for i in range(ndim)]
        dtype = _np.dtype(self._dtype) if self._dtype else arrs[0].dtype
        out = _np.full([len(arrs)] + max_shape, self._val, dtype)
        for i, a in enumerate(arrs):
            out[(i,) + tuple(slice(0, s) for s in a.shape)] = a
        return mxnp.array(out)


class Group:
    """Apply one batchify function per sample component
    (≙ batchify.Group: e.g. Group(Stack(), Pad(val=0)) for (img, caption))."""

    def __init__(self, *fns):
        if len(fns) == 1 and isinstance(fns[0], (list, tuple)):
            fns = tuple(fns[0])
        if not fns:
            raise MXNetError("Group needs at least one batchify function")
        self._fns = fns

    def __call__(self, data):
        parts = list(zip(*data))
        if len(parts) != len(self._fns):
            raise MXNetError(
                f"Group has {len(self._fns)} functions but samples have "
                f"{len(parts)} components")
        return tuple(f(p) for f, p in zip(self._fns, parts))
