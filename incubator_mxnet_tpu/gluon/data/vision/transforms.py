"""Vision transforms (≙ python/mxnet/gluon/data/vision/transforms.py).

Transforms operate on HWC uint8/float NDArrays (the reference convention) and
run host-side through the numpy frontend — batches then upload once. ToTensor
converts HWC [0,255] → CHW [0,1] float32 like the reference.
"""
from __future__ import annotations

import numpy as _np

from ....base import MXNetError
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "CropResize"]


class Compose(Sequential):
    """≙ transforms.Compose."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (≙ transforms.ToTensor)."""

    def forward(self, x):
        x = x.astype("float32") / 255.0
        if x.ndim == 3:
            return x.transpose((2, 0, 1))
        return x.transpose((0, 3, 1, 2))


class Normalize(HybridBlock):
    """(x - mean) / std per channel on CHW input (≙ transforms.Normalize)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = _np.asarray(mean, _np.float32).reshape(-1, 1, 1)
        self._std = _np.asarray(std, _np.float32).reshape(-1, 1, 1)

    def forward(self, x):
        from .... import numpy as mxnp
        return (x - mxnp.array(self._mean)) / mxnp.array(self._std)


def _resize_hwc(x, size, interp="bilinear"):
    """Resize HWC array with jax.image (≙ src/operator/image/resize.cc)."""
    import jax.image
    from ....ops.registry import invoke
    if isinstance(size, int):
        size = (size, size)  # (w, h) in reference order
    w, h = size
    method = {"bilinear": "linear", "nearest": "nearest",
              "bicubic": "cubic"}.get(interp, "linear")

    def f(a):
        shape = (h, w, a.shape[-1]) if a.ndim == 3 else \
            (a.shape[0], h, w, a.shape[-1])
        return jax.image.resize(a.astype("float32"), shape, method)

    return invoke(f, (x,), name="resize")


class Resize(Block):
    """≙ transforms.Resize(size, keep_ratio, interpolation)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interp = "bilinear"

    def forward(self, x):
        size = self._size
        if self._keep and isinstance(size, int):
            h, w = x.shape[-3], x.shape[-2]
            if h < w:
                size = (int(w * size / h), size)
            else:
                size = (size, int(h * size / w))
        return _resize_hwc(x, size, self._interp)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[-3], x.shape[-2]
        y0 = max((H - h) // 2, 0)
        x0 = max((W - w) // 2, 0)
        out = x[..., y0:y0 + h, x0:x0 + w, :]
        if out.shape[-3] != h or out.shape[-2] != w:
            out = _resize_hwc(out, (w, h))
        return out


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._pad = pad

    def forward(self, x):
        from .... import numpy as mxnp
        w, h = self._size
        if self._pad:
            p = self._pad
            x = mxnp.pad(x, ((p, p), (p, p), (0, 0)), mode="constant")
        H, W = x.shape[-3], x.shape[-2]
        y0 = _np.random.randint(0, max(H - h, 0) + 1)
        x0 = _np.random.randint(0, max(W - w, 0) + 1)
        return x[..., y0:y0 + h, x0:x0 + w, :]


class RandomResizedCrop(Block):
    """≙ transforms.RandomResizedCrop (area/ratio jitter then resize)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        H, W = x.shape[-3], x.shape[-2]
        area = H * W
        for _ in range(10):
            target_area = _np.random.uniform(*self._scale) * area
            log_ratio = (_np.log(self._ratio[0]), _np.log(self._ratio[1]))
            aspect = _np.exp(_np.random.uniform(*log_ratio))
            w = int(round(_np.sqrt(target_area * aspect)))
            h = int(round(_np.sqrt(target_area / aspect)))
            if 0 < w <= W and 0 < h <= H:
                y0 = _np.random.randint(0, H - h + 1)
                x0 = _np.random.randint(0, W - w + 1)
                crop = x[..., y0:y0 + h, x0:x0 + w, :]
                return _resize_hwc(crop, self._size)
        return _resize_hwc(x, self._size)  # fallback


class RandomFlipLeftRight(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        from .... import numpy as mxnp
        if _np.random.rand() < self._p:
            return mxnp.flip(x, axis=-2)
        return x


class RandomFlipTopBottom(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        from .... import numpy as mxnp
        if _np.random.rand() < self._p:
            return mxnp.flip(x, axis=-3)
        return x


class CropResize(Block):
    """≙ transforms.CropResize(x, y, w, h, size)."""

    def __init__(self, x, y, width, height, size=None, interpolation=None):
        super().__init__()
        self._x, self._y = x, y
        self._w, self._h = width, height
        self._size = size

    def forward(self, img):
        out = img[..., self._y:self._y + self._h,
                  self._x:self._x + self._w, :]
        if self._size:
            out = _resize_hwc(out, self._size)
        return out
