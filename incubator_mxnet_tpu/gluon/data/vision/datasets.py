"""Vision datasets (≙ python/mxnet/gluon/data/vision/datasets.py).

Zero-egress environment: datasets read the standard on-disk formats from a
local `root` directory (idx-ubyte for MNIST, pickled batches for CIFAR);
`download()` is unavailable by design — no silent network access.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as _np

from ....base import MXNetError
from ..dataset import Dataset


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __len__(self):
        return len(self._label)

    def __getitem__(self, idx):
        from ....ndarray import array
        x = array(self._data[idx])
        y = int(self._label[idx])
        if self._transform is not None:
            return self._transform(x, y)
        return x, y

    def _get_data(self):
        raise NotImplementedError


def _read_idx_images(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise MXNetError(f"bad MNIST image magic in {path}")
        data = _np.frombuffer(f.read(), dtype=_np.uint8)
        return data.reshape(n, rows, cols, 1)


def _read_idx_labels(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise MXNetError(f"bad MNIST label magic in {path}")
        return _np.frombuffer(f.read(), dtype=_np.uint8).astype(_np.int32)


def _find(root, names):
    for n in names:
        p = os.path.join(root, n)
        if os.path.exists(p):
            return p
    raise MXNetError(
        f"dataset files not found under {root} (searched {names}); this "
        "environment has no network egress — place the files locally")


class MNIST(_DownloadedDataset):
    """≙ gluon.data.vision.MNIST (idx-ubyte files under root)."""

    _prefix = ""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        part = "train" if self._train else "t10k"
        img = _find(self._root, [f"{part}-images-idx3-ubyte",
                                 f"{part}-images-idx3-ubyte.gz"])
        lab = _find(self._root, [f"{part}-labels-idx1-ubyte",
                                 f"{part}-labels-idx1-ubyte.gz"])
        self._data = _read_idx_images(img)
        self._label = _read_idx_labels(lab)


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """≙ gluon.data.vision.CIFAR10 (python pickled batches under root)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        super().__init__(root, train, transform)

    def _batches(self):
        if self._train:
            return [f"data_batch_{i}" for i in range(1, 6)]
        return ["test_batch"]

    def _get_data(self):
        datas, labels = [], []
        for name in self._batches():
            p = _find(self._root, [name,
                                   os.path.join("cifar-10-batches-py", name)])
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            datas.append(d[b"data"].reshape(-1, 3, 32, 32)
                         .transpose(0, 2, 3, 1))
            labels.extend(d.get(b"labels", d.get(b"fine_labels")))
        self._data = _np.concatenate(datas, axis=0)
        self._label = _np.asarray(labels, dtype=_np.int32)


class CIFAR100(CIFAR10):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 fine_label=True, train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _batches(self):
        return ["train" if self._train else "test"]

    def _get_data(self):
        name = self._batches()[0]
        p = _find(self._root, [name, os.path.join("cifar-100-python", name)])
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        self._data = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        key = b"fine_labels" if self._fine else b"coarse_labels"
        self._label = _np.asarray(d[key], dtype=_np.int32)


class ImageRecordDataset(Dataset):
    """≙ gluon.data.vision.ImageRecordDataset — .rec of packed images.
    Needs an image codec for decode; raw payload access works without."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset
        self._rec = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._rec)

    def payload(self, idx):
        """Raw IRHeader-packed record bytes — no decode, no NDArray wrap
        (the ImageRecordIter PIL fallback and the shm decode workers parse
        these through `io._imagerec_common.parse_record` instead of paying
        a device round-trip per image)."""
        return self._rec[idx]

    def __getitem__(self, idx):
        from ....recordio import unpack
        header, payload = unpack(self._rec[idx])
        img = _decode_image(payload, self._flag)
        from ....ndarray import array
        x = array(img)
        label = header.label
        if self._transform is not None:
            return self._transform(x, label)
        return x, label


def _decode_image(payload, flag):
    try:
        import io
        from PIL import Image
        img = Image.open(io.BytesIO(payload))
        if flag == 0:
            img = img.convert("L")
        else:
            img = img.convert("RGB")
        arr = _np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr
    except ImportError:
        raise MXNetError("image decode needs PIL, which is unavailable; "
                         "store raw arrays in the record payload instead")


class ImageFolderDataset(Dataset):
    """≙ gluon.data.vision.ImageFolderDataset: root/label/img.jpg layout."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        exts = (".jpg", ".jpeg", ".png", ".bmp")
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith(exts):
                    self.items.append((os.path.join(path, fname), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        path, label = self.items[idx]
        with open(path, "rb") as f:
            img = _decode_image(f.read(), self._flag)
        from ....ndarray import array
        x = array(img)
        if self._transform is not None:
            return self._transform(x, label)
        return x, label
