"""Invertible transformations (≙ python/mxnet/gluon/probability/
transformation/transformation.py: Transformation, ComposeTransform, Exp/
Affine/Power/Sigmoid/Softmax/Abs transforms + TransformedDistribution
support).

TPU-native: each transform is a pair of pure jnp maps plus an analytic
log|det J| — everything traces into the surrounding program; no
per-transform ops."""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError

__all__ = ["Transformation", "ComposeTransform", "ExpTransform",
           "AffineTransform", "PowerTransform", "SigmoidTransform",
           "SoftmaxTransform", "AbsTransform", "TransformedDistribution"]


def _jnp():
    import jax.numpy as jnp
    return jnp


def _raw(x):
    return x._arr if hasattr(x, "_arr") else x


def _wrap(a):
    from ...ndarray import _wrap as w
    return w(a)


class Transformation:
    """y = f(x), invertible; event_dim = rank of the event a single
    transform consumes (0 = elementwise)."""

    bijective = True
    event_dim = 0

    def __call__(self, x):
        return _wrap(self._forward(_raw(x)))

    @property
    def inv(self):
        return _Inverse(self)

    def inverse(self, y):
        return _wrap(self._inverse(_raw(y)))

    def log_det_jacobian(self, x, y=None):
        xr = _raw(x)
        yr = _raw(y) if y is not None else self._forward(xr)
        return _wrap(self._log_det(xr, yr))

    # -- to implement ----------------------------------------------------
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _log_det(self, x, y):
        raise NotImplementedError


class _Inverse(Transformation):
    def __init__(self, base):
        self._base = base
        self.event_dim = base.event_dim

    @property
    def inv(self):
        return self._base

    def _forward(self, x):
        return self._base._inverse(x)

    def _inverse(self, y):
        return self._base._forward(y)

    def _log_det(self, x, y):
        return -self._base._log_det(y, x)


class ComposeTransform(Transformation):
    """f_n ∘ ... ∘ f_1 (applied left to right, reference order)."""

    def __init__(self, parts):
        if not parts:
            raise MXNetError("ComposeTransform needs at least one part")
        self._parts = list(parts)
        self.event_dim = max(p.event_dim for p in parts)

    def _forward(self, x):
        for p in self._parts:
            x = p._forward(x)
        return x

    def _inverse(self, y):
        for p in reversed(self._parts):
            y = p._inverse(y)
        return y

    def _log_det(self, x, y):
        jnp = _jnp()
        total = None
        cur = x
        for p in self._parts:
            nxt = p._forward(cur)
            ld = p._log_det(cur, nxt)
            # reduce finer-grained event dims so parts sum consistently
            while ld.ndim > 0 and p.event_dim < self.event_dim \
                    and ld.ndim > x.ndim - self.event_dim:
                ld = jnp.sum(ld, axis=-1)
            total = ld if total is None else total + ld
            cur = nxt
        return total


class ExpTransform(Transformation):
    def _forward(self, x):
        return _jnp().exp(x)

    def _inverse(self, y):
        return _jnp().log(y)

    def _log_det(self, x, y):
        return x


class AffineTransform(Transformation):
    def __init__(self, loc, scale):
        self._loc = _raw(loc)
        self._scale = _raw(scale)

    def _forward(self, x):
        return self._loc + self._scale * x

    def _inverse(self, y):
        return (y - self._loc) / self._scale

    def _log_det(self, x, y):
        jnp = _jnp()
        return jnp.broadcast_to(jnp.log(jnp.abs(self._scale)), jnp.shape(x))


class PowerTransform(Transformation):
    def __init__(self, exponent):
        self._exp = float(exponent)
        if self._exp == 0:
            raise MXNetError("PowerTransform exponent must be nonzero")

    def _forward(self, x):
        return x ** self._exp

    def _inverse(self, y):
        return y ** (1.0 / self._exp)

    def _log_det(self, x, y):
        jnp = _jnp()
        return jnp.log(jnp.abs(self._exp * y / x))


class SigmoidTransform(Transformation):
    def _forward(self, x):
        import jax
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        jnp = _jnp()
        return jnp.log(y) - jnp.log1p(-y)

    def _log_det(self, x, y):
        import jax
        jnp = _jnp()
        # log sigma'(x) = -softplus(-x) - softplus(x)
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class SoftmaxTransform(Transformation):
    """Not bijective (maps onto the simplex); log_det undefined."""

    bijective = False
    event_dim = 1

    def _forward(self, x):
        import jax
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return _jnp().log(y)

    def _log_det(self, x, y):
        raise MXNetError("SoftmaxTransform is not bijective")


class AbsTransform(Transformation):
    bijective = False

    def _forward(self, x):
        return _jnp().abs(x)

    def _inverse(self, y):
        return y   # a right-inverse (reference semantics)

    def _log_det(self, x, y):
        raise MXNetError("AbsTransform is not bijective")


class TransformedDistribution:
    """Distribution of f(X) for X ~ base (≙ transformed_distribution.py):
    log_prob via the change-of-variables formula, sampling by pushing base
    samples through the transform chain."""

    def __init__(self, base, transforms):
        if isinstance(transforms, Transformation):
            transforms = [transforms]
        self._base = base
        self._chain = ComposeTransform(list(transforms))
        if not self._chain.bijective or any(
                not p.bijective for p in self._chain._parts):
            raise MXNetError(
                "TransformedDistribution needs bijective transforms")

    def sample(self, shape=()):
        x = self._base.sample(shape)
        return self._chain(x)

    def log_prob(self, value):
        jnp = _jnp()
        yr = _raw(value)
        xr = self._chain._inverse(yr)
        base_lp = _raw(self._base.log_prob(_wrap(xr)))
        ld = self._chain._log_det(xr, yr)
        while ld.ndim > base_lp.ndim:
            ld = jnp.sum(ld, axis=-1)
        return _wrap(base_lp - ld)
