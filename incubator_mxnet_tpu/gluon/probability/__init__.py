"""gluon.probability (≙ python/mxnet/gluon/probability/ ~8k LoC).

Distributions with log_prob/sample/entropy/mean/variance, a KL-divergence
registry, and StochasticBlock. Sampling uses the framework's functional PRNG
(mx.random key plumbing), densities lower to jax.scipy — each distribution
is a thin declarative layer instead of the reference's per-distribution
C++ sampler ops (src/operator/random/*).
"""
from .distributions import (Distribution, Normal, Bernoulli, Categorical,
                            Uniform, Exponential, Gamma, Poisson, Laplace,
                            Beta, Dirichlet, StudentT, HalfNormal, Cauchy,
                            Geometric, Binomial, MultivariateNormal,
                            Gumbel, Weibull, Pareto, HalfCauchy, Chi2,
                            FisherSnedecor, NegativeBinomial, Multinomial,
                            OneHotCategorical, RelaxedBernoulli,
                            RelaxedOneHotCategorical, Independent,
                            kl_divergence, register_kl, empirical_kl)
from .stochastic_block import StochasticBlock, StochasticSequential
from .transformation import (Transformation, ComposeTransform, ExpTransform,
                             AffineTransform, PowerTransform,
                             SigmoidTransform, SoftmaxTransform,
                             AbsTransform, TransformedDistribution)
