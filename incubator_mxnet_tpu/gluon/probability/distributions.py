"""Distribution zoo (≙ gluon/probability/distributions/*)."""
from __future__ import annotations

import math

import numpy as _np

from ...base import MXNetError
from ...ndarray import NDArray, _as_nd, _wrap
from ...ops.registry import invoke
from ... import random as _random

__all__ = ["Distribution", "Normal", "Bernoulli", "Categorical", "Uniform",
           "Exponential", "Gamma", "Poisson", "Laplace", "Beta", "Dirichlet",
           "StudentT", "HalfNormal", "Cauchy", "Geometric", "Binomial",
           "MultivariateNormal", "Gumbel", "Weibull", "Pareto", "HalfCauchy",
           "Chi2", "FisherSnedecor", "NegativeBinomial", "Multinomial",
           "OneHotCategorical", "RelaxedBernoulli",
           "RelaxedOneHotCategorical", "Independent",
           "kl_divergence", "register_kl", "empirical_kl"]

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    """Decorator registering a KL(p||q) implementation (≙ register_kl)."""
    def _reg(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return _reg


def kl_divergence(p, q):
    """≙ mx.gluon.probability.kl_divergence."""
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise MXNetError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


def _jx():
    import jax
    return jax


def _call(fn, *arrays):
    return invoke(fn, tuple(_as_nd(a) for a in arrays), name="prob")


def _sample_shape(size, batch_shape):
    if size is None:
        return tuple(batch_shape)
    if isinstance(size, int):
        size = (size,)
    return tuple(size) + tuple(batch_shape)


class Distribution:
    """Base distribution (≙ probability.Distribution)."""

    has_grad = True

    def __init__(self, **params):
        self._params = {k: _as_nd(v) if not isinstance(v, NDArray) else v
                        for k, v in params.items() if v is not None}
        for k, v in self._params.items():
            setattr(self, k, v)

    @property
    def batch_shape(self):
        shapes = [p.shape for p in self._params.values()]
        if not shapes:
            return ()
        return _np.broadcast_shapes(*shapes)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ... import numpy as mxnp
        return mxnp.exp(self.log_prob(value))

    def sample(self, size=None):
        raise NotImplementedError

    def sample_n(self, size=None):
        return self.sample(size)

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        from ... import numpy as mxnp
        return mxnp.sqrt(self.variance)

    def entropy(self):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({list(self._params)})"


class Normal(Distribution):
    def __init__(self, loc=0.0, scale=1.0):
        super().__init__(loc=loc, scale=scale)

    def log_prob(self, value):
        def f(v, loc, scale):
            import jax.numpy as jnp
            var = scale ** 2
            return (-((v - loc) ** 2) / (2 * var)
                    - jnp.log(scale) - 0.5 * math.log(2 * math.pi))
        return _call(f, value, self.loc, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(loc, scale):
            import jax
            return loc + scale * jax.random.normal(key, shape)
        return _call(f, self.loc, self.scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale ** 2

    def entropy(self):
        from ... import numpy as mxnp
        return 0.5 + 0.5 * math.log(2 * math.pi) + mxnp.log(self.scale)


class HalfNormal(Normal):
    def log_prob(self, value):
        def f(v, loc, scale):
            import jax.numpy as jnp
            var = scale ** 2
            lp = (-((v - loc) ** 2) / (2 * var) - jnp.log(scale)
                  - 0.5 * math.log(2 * math.pi) + math.log(2.0))
            return jnp.where(v >= loc, lp, -_np.inf)
        return _call(f, value, self.loc, self.scale)

    def sample(self, size=None):
        from ... import numpy as mxnp
        return self.loc + mxnp.abs(super().sample(size) - self.loc)

    @property
    def mean(self):
        return self.loc + self.scale * math.sqrt(2 / math.pi)

    @property
    def variance(self):
        return (self.scale ** 2) * (1 - 2 / math.pi)


class Laplace(Distribution):
    def __init__(self, loc=0.0, scale=1.0):
        super().__init__(loc=loc, scale=scale)

    def log_prob(self, value):
        def f(v, loc, scale):
            import jax.numpy as jnp
            return -jnp.abs(v - loc) / scale - jnp.log(2 * scale)
        return _call(f, value, self.loc, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(loc, scale):
            import jax
            return loc + scale * jax.random.laplace(key, shape)
        return _call(f, self.loc, self.scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return 2 * self.scale ** 2

    def entropy(self):
        from ... import numpy as mxnp
        return 1 + mxnp.log(2 * self.scale)


class Cauchy(Distribution):
    has_grad = True

    def __init__(self, loc=0.0, scale=1.0):
        super().__init__(loc=loc, scale=scale)

    def log_prob(self, value):
        def f(v, loc, scale):
            import jax.numpy as jnp
            return (-math.log(math.pi) - jnp.log(scale)
                    - jnp.log1p(((v - loc) / scale) ** 2))
        return _call(f, value, self.loc, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(loc, scale):
            import jax
            return loc + scale * jax.random.cauchy(key, shape)
        return _call(f, self.loc, self.scale)


class Uniform(Distribution):
    def __init__(self, low=0.0, high=1.0):
        super().__init__(low=low, high=high)

    def log_prob(self, value):
        def f(v, low, high):
            import jax.numpy as jnp
            inside = (v >= low) & (v <= high)
            return jnp.where(inside, -jnp.log(high - low), -_np.inf)
        return _call(f, value, self.low, self.high)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(low, high):
            import jax
            return low + (high - low) * jax.random.uniform(key, shape)
        return _call(f, self.low, self.high)

    @property
    def mean(self):
        return (self.low + self.high) / 2

    @property
    def variance(self):
        return ((self.high - self.low) ** 2) / 12

    def entropy(self):
        from ... import numpy as mxnp
        return mxnp.log(self.high - self.low)


class Exponential(Distribution):
    def __init__(self, scale=1.0):
        super().__init__(scale=scale)

    def log_prob(self, value):
        def f(v, scale):
            import jax.numpy as jnp
            return jnp.where(v >= 0, -v / scale - jnp.log(scale), -_np.inf)
        return _call(f, value, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(scale):
            import jax
            return scale * jax.random.exponential(key, shape)
        return _call(f, self.scale)

    @property
    def mean(self):
        return self.scale

    @property
    def variance(self):
        return self.scale ** 2

    def entropy(self):
        from ... import numpy as mxnp
        return 1 + mxnp.log(self.scale)


class Gamma(Distribution):
    def __init__(self, shape=1.0, scale=1.0):
        super().__init__(shape_param=shape, scale=scale)

    def log_prob(self, value):
        def f(v, a, s):
            import jax
            import jax.numpy as jnp
            return ((a - 1) * jnp.log(v) - v / s
                    - jax.scipy.special.gammaln(a) - a * jnp.log(s))
        return _call(f, value, self.shape_param, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(a, s):
            import jax
            return s * jax.random.gamma(key, a, shape)
        return _call(f, self.shape_param, self.scale)

    @property
    def mean(self):
        return self.shape_param * self.scale

    @property
    def variance(self):
        return self.shape_param * self.scale ** 2


class Beta(Distribution):
    def __init__(self, alpha=1.0, beta=1.0):
        super().__init__(alpha=alpha, beta=beta)

    def log_prob(self, value):
        def f(v, a, b):
            import jax
            import jax.numpy as jnp
            lbeta = (jax.scipy.special.gammaln(a)
                     + jax.scipy.special.gammaln(b)
                     - jax.scipy.special.gammaln(a + b))
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta
        return _call(f, value, self.alpha, self.beta)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(a, b):
            import jax
            return jax.random.beta(key, a, b, shape)
        return _call(f, self.alpha, self.beta)

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s * s * (s + 1))


class Dirichlet(Distribution):
    def __init__(self, alpha):
        super().__init__(alpha=alpha)

    def log_prob(self, value):
        def f(v, a):
            import jax
            import jax.numpy as jnp
            lnorm = (jnp.sum(jax.scipy.special.gammaln(a), -1)
                     - jax.scipy.special.gammaln(jnp.sum(a, -1)))
            return jnp.sum((a - 1) * jnp.log(v), -1) - lnorm
        return _call(f, value, self.alpha)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.alpha.shape[:-1])

        def f(a):
            import jax
            return jax.random.dirichlet(key, a, shape)
        return _call(f, self.alpha)

    @property
    def mean(self):
        return self.alpha / self.alpha.sum(axis=-1, keepdims=True)


class Poisson(Distribution):
    has_grad = False

    def __init__(self, rate=1.0):
        super().__init__(rate=rate)

    def log_prob(self, value):
        def f(v, rate):
            import jax
            import jax.numpy as jnp
            return v * jnp.log(rate) - rate - jax.scipy.special.gammaln(v + 1)
        return _call(f, value, self.rate)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(rate):
            import jax
            return jax.random.poisson(key, rate, shape).astype("float32")
        return _call(f, self.rate)

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate


class Bernoulli(Distribution):
    has_grad = False

    def __init__(self, prob=None, logit=None):
        if (prob is None) == (logit is None):
            raise MXNetError("pass exactly one of prob/logit")
        if logit is not None:
            from ... import numpy_extension as npx
            prob = npx.sigmoid(_as_nd(logit))
        super().__init__(prob=prob)

    def log_prob(self, value):
        def f(v, p):
            import jax.numpy as jnp
            eps = 1e-12
            return v * jnp.log(p + eps) + (1 - v) * jnp.log1p(-p + eps)
        return _call(f, value, self.prob)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(p):
            import jax
            return jax.random.bernoulli(key, p, shape).astype("float32")
        return _call(f, self.prob)

    @property
    def mean(self):
        return self.prob

    @property
    def variance(self):
        return self.prob * (1 - self.prob)

    def entropy(self):
        from ... import numpy as mxnp
        p = self.prob
        eps = 1e-12
        return -(p * mxnp.log(p + eps) + (1 - p) * mxnp.log1p(-p + eps))


class Geometric(Distribution):
    has_grad = False

    def __init__(self, prob):
        super().__init__(prob=prob)

    def log_prob(self, value):
        def f(v, p):
            import jax.numpy as jnp
            return v * jnp.log1p(-p) + jnp.log(p)
        return _call(f, value, self.prob)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(p):
            import jax
            import jax.numpy as jnp
            u = jax.random.uniform(key, shape, minval=1e-7)
            return jnp.floor(jnp.log(u) / jnp.log1p(-p))
        return _call(f, self.prob)

    @property
    def mean(self):
        return (1 - self.prob) / self.prob


class Binomial(Distribution):
    has_grad = False

    def __init__(self, n=1, prob=0.5):
        self.n = int(n)
        super().__init__(prob=prob)

    def log_prob(self, value):
        n = self.n

        def f(v, p):
            import jax
            import jax.numpy as jnp
            logc = (jax.scipy.special.gammaln(n + 1.0)
                    - jax.scipy.special.gammaln(v + 1.0)
                    - jax.scipy.special.gammaln(n - v + 1.0))
            return logc + v * jnp.log(p) + (n - v) * jnp.log1p(-p)
        return _call(f, value, self.prob)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)
        n = self.n

        def f(p):
            import jax
            return jax.random.binomial(key, n, p, shape=shape)
        return _call(f, self.prob)

    @property
    def mean(self):
        return self.n * self.prob

    @property
    def variance(self):
        return self.n * self.prob * (1 - self.prob)


class Categorical(Distribution):
    has_grad = False

    def __init__(self, num_events=None, prob=None, logit=None):
        if (prob is None) == (logit is None):
            raise MXNetError("pass exactly one of prob/logit")
        if logit is None:
            from ... import numpy as mxnp
            logit = mxnp.log(_as_nd(prob) + 1e-12)
        super().__init__(logit=logit)
        self.num_events = num_events or self.logit.shape[-1]

    @property
    def prob(self):
        from ... import numpy_extension as npx
        return npx.softmax(self.logit, axis=-1)

    def log_prob(self, value):
        def f(v, logit):
            import jax
            import jax.numpy as jnp
            logp = jax.nn.log_softmax(logit, axis=-1)
            return jnp.take_along_axis(
                logp, v.astype(jnp.int32)[..., None], axis=-1)[..., 0]
        return _call(f, value, self.logit)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape[:-1])

        def f(logit):
            import jax
            return jax.random.categorical(key, logit, shape=shape)
        return _call(f, self.logit)

    def entropy(self):
        def f(logit):
            import jax
            import jax.numpy as jnp
            logp = jax.nn.log_softmax(logit, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)
        return _call(f, self.logit)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0):
        super().__init__(df=df, loc=loc, scale=scale)

    def log_prob(self, value):
        def f(v, df, loc, scale):
            import jax
            import jax.numpy as jnp
            z = (v - loc) / scale
            return (jax.scipy.special.gammaln((df + 1) / 2)
                    - jax.scipy.special.gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(scale)
                    - (df + 1) / 2 * jnp.log1p(z * z / df))
        return _call(f, value, self.df, self.loc, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(df, loc, scale):
            import jax
            return loc + scale * jax.random.t(key, df, shape)
        return _call(f, self.df, self.loc, self.scale)


class MultivariateNormal(Distribution):
    def __init__(self, loc, cov=None, scale_tril=None):
        if (cov is None) == (scale_tril is None):
            raise MXNetError("pass exactly one of cov/scale_tril")
        if scale_tril is None:
            def chol(c):
                import jax.numpy as jnp
                return jnp.linalg.cholesky(c)
            scale_tril = _call(chol, cov)
        super().__init__(loc=loc, scale_tril=scale_tril)

    def log_prob(self, value):
        def f(v, loc, L):
            import jax
            import jax.numpy as jnp
            d = loc.shape[-1]
            diff = v - loc
            sol = jax.scipy.linalg.solve_triangular(L, diff[..., None],
                                                    lower=True)[..., 0]
            logdet = jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), -1)
            return (-0.5 * jnp.sum(sol * sol, -1) - logdet
                    - 0.5 * d * math.log(2 * math.pi))
        return _call(f, value, self.loc, self.scale_tril)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(loc, L):
            import jax
            import jax.numpy as jnp
            eps = jax.random.normal(key, shape)
            return loc + jnp.einsum("...ij,...j->...i", L, eps)
        return _call(f, self.loc, self.scale_tril)

    @property
    def mean(self):
        return self.loc


class Gumbel(Distribution):
    """≙ distributions/gumbel.py:48 — Gumbel(loc, scale)."""

    def __init__(self, loc, scale=1.0):
        super().__init__(loc=loc, scale=scale)

    def log_prob(self, value):
        def f(v, loc, scale):
            import jax.numpy as jnp
            z = (v - loc) / scale
            return -jnp.log(scale) - z - jnp.exp(-z)
        return _call(f, value, self.loc, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(loc, scale):
            import jax
            return loc + scale * jax.random.gumbel(key, shape)
        return _call(f, self.loc, self.scale)

    @property
    def mean(self):
        return self.loc + self.scale * _np.euler_gamma

    @property
    def variance(self):
        return (math.pi ** 2 / 6) * self.scale ** 2

    def entropy(self):
        from ... import numpy as mxnp
        return mxnp.log(self.scale) + 1 + _np.euler_gamma


class Weibull(Distribution):
    """≙ distributions/weibull.py:49 — Weibull(concentration, scale)."""

    def __init__(self, concentration, scale=1.0):
        super().__init__(concentration=concentration, scale=scale)

    def log_prob(self, value):
        def f(v, k, s):
            import jax.numpy as jnp
            lp = (jnp.log(k / s) + (k - 1) * (jnp.log(v) - jnp.log(s))
                  - (v / s) ** k)
            return jnp.where(v >= 0, lp, -_np.inf)
        return _call(f, value, self.concentration, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(k, s):
            import jax
            # inverse-CDF: s * (-log U)^(1/k), U~Uniform — exponential
            # draw keeps it numerically clean at U→{0,1}
            e = jax.random.exponential(key, shape)
            return s * e ** (1.0 / k)
        return _call(f, self.concentration, self.scale)

    @property
    def mean(self):
        def f(k, s):
            import jax
            import jax.numpy as jnp
            return s * jnp.exp(jax.scipy.special.gammaln(1 + 1 / k))
        return _call(f, self.concentration, self.scale)

    @property
    def variance(self):
        def f(k, s):
            import jax
            import jax.numpy as jnp
            g1 = jnp.exp(jax.scipy.special.gammaln(1 + 1 / k))
            g2 = jnp.exp(jax.scipy.special.gammaln(1 + 2 / k))
            return s ** 2 * (g2 - g1 ** 2)
        return _call(f, self.concentration, self.scale)

    def entropy(self):
        from ... import numpy as mxnp
        k, s = self.concentration, self.scale
        return _np.euler_gamma * (1 - 1 / k) + mxnp.log(s / k) + 1


class Pareto(Distribution):
    """≙ distributions/pareto.py:47 — Pareto(alpha, scale); support
    [scale, inf)."""

    def __init__(self, alpha, scale=1.0):
        super().__init__(alpha=alpha, scale=scale)

    def log_prob(self, value):
        def f(v, a, s):
            import jax.numpy as jnp
            lp = jnp.log(a) + a * jnp.log(s) - (a + 1) * jnp.log(v)
            return jnp.where(v >= s, lp, -_np.inf)
        return _call(f, value, self.alpha, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(a, s):
            import jax
            import jax.numpy as jnp
            # X = s * e^(E/a), E~Exp(1)  (inverse-CDF of the power law)
            e = jax.random.exponential(key, shape)
            return s * jnp.exp(e / a)
        return _call(f, self.alpha, self.scale)

    @property
    def mean(self):
        def f(a, s):
            import jax.numpy as jnp
            return jnp.where(a > 1, a * s / (a - 1), jnp.inf)
        return _call(f, self.alpha, self.scale)

    @property
    def variance(self):
        def f(a, s):
            import jax.numpy as jnp
            v = s ** 2 * a / ((a - 1) ** 2 * (a - 2))
            return jnp.where(a > 2, v, jnp.inf)
        return _call(f, self.alpha, self.scale)

    def entropy(self):
        from ... import numpy as mxnp
        a, s = self.alpha, self.scale
        return mxnp.log(s / a) + 1 + 1 / a


class HalfCauchy(Distribution):
    """≙ distributions/half_cauchy.py:48 — HalfCauchy(scale), support
    [0, inf)."""

    def __init__(self, scale=1.0):
        super().__init__(scale=scale)

    def log_prob(self, value):
        def f(v, s):
            import jax.numpy as jnp
            lp = (math.log(2.0) - math.log(math.pi) - jnp.log(s)
                  - jnp.log1p((v / s) ** 2))
            return jnp.where(v >= 0, lp, -_np.inf)
        return _call(f, value, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(s):
            import jax
            import jax.numpy as jnp
            return jnp.abs(s * jax.random.cauchy(key, shape))
        return _call(f, self.scale)


class Chi2(Gamma):
    """≙ distributions/chi2.py:40 — Chi2(df) = Gamma(df/2, scale=2)."""

    def __init__(self, df):
        super().__init__(shape=_as_nd(df) / 2, scale=2.0)

    @property
    def df(self):
        return self.shape_param * 2


class FisherSnedecor(Distribution):
    """≙ distributions/fishersnedecor.py:47 — F(df1, df2)."""

    def __init__(self, df1, df2):
        super().__init__(df1=df1, df2=df2)

    def log_prob(self, value):
        def f(v, d1, d2):
            import jax
            import jax.numpy as jnp
            lbeta = (jax.scipy.special.gammaln(d1 / 2)
                     + jax.scipy.special.gammaln(d2 / 2)
                     - jax.scipy.special.gammaln((d1 + d2) / 2))
            return ((d1 / 2) * jnp.log(d1 / d2)
                    + (d1 / 2 - 1) * jnp.log(v)
                    - ((d1 + d2) / 2) * jnp.log1p(d1 * v / d2) - lbeta)
        return _call(f, value, self.df1, self.df2)

    def sample(self, size=None):
        k1, k2 = _random.next_key(), _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(d1, d2):
            import jax
            x1 = jax.random.gamma(k1, d1 / 2, shape) * 2
            x2 = jax.random.gamma(k2, d2 / 2, shape) * 2
            return (x1 / d1) / (x2 / d2)
        return _call(f, self.df1, self.df2)

    @property
    def mean(self):
        def f(d1, d2):
            import jax.numpy as jnp
            return jnp.where(d2 > 2, d2 / (d2 - 2), jnp.nan)
        return _call(f, self.df1, self.df2)

    @property
    def variance(self):
        def f(d1, d2):
            import jax.numpy as jnp
            v = (2 * d2 ** 2 * (d1 + d2 - 2)
                 / (d1 * (d2 - 2) ** 2 * (d2 - 4)))
            return jnp.where(d2 > 4, v, jnp.nan)
        return _call(f, self.df1, self.df2)


class NegativeBinomial(Distribution):
    """≙ distributions/negative_binomial.py:51 — successes-before-n-failures
    count; mean = n * prob / (1 - prob) (reference line 91)."""

    has_grad = False

    def __init__(self, n, prob=None, logit=None):
        if (prob is None) == (logit is None):
            raise MXNetError("pass exactly one of prob/logit")
        if prob is None:
            from ... import numpy_extension as npx
            prob = npx.sigmoid(_as_nd(logit))
        super().__init__(n=n, prob=prob)

    @property
    def logit(self):
        from ... import numpy as mxnp
        return mxnp.log(self.prob) - mxnp.log1p(-self.prob)

    def log_prob(self, value):
        def f(v, n, p):
            import jax
            import jax.numpy as jnp
            coef = (jax.scipy.special.gammaln(v + n)
                    - jax.scipy.special.gammaln(1 + v)
                    - jax.scipy.special.gammaln(n))
            return coef + n * jnp.log1p(-p) + v * jnp.log(p)
        return _call(f, value, self.n, self.prob)

    def sample(self, size=None):
        # Poisson-Gamma mixture (≙ negative_binomial.py:121)
        kg, kp = _random.next_key(), _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(n, p):
            import jax
            rate = jax.random.gamma(kg, n, shape) * (p / (1 - p))
            return jax.random.poisson(kp, rate).astype("float32")
        return _call(f, self.n, self.prob)

    @property
    def mean(self):
        return self.n * self.prob / (1 - self.prob)

    @property
    def variance(self):
        return self.n * self.prob / (1 - self.prob) ** 2


class Multinomial(Distribution):
    """≙ distributions/multinomial.py:48 — Multinomial(num_events,
    prob/logit, total_count)."""

    has_grad = False

    def __init__(self, num_events, prob=None, logit=None, total_count=1):
        if not isinstance(total_count, int):
            raise MXNetError("total_count must be a scalar int")
        if (prob is None) == (logit is None):
            raise MXNetError("pass exactly one of prob/logit")
        if prob is None:
            from ... import numpy_extension as npx
            prob = npx.softmax(_as_nd(logit), axis=-1)
        super().__init__(prob=prob)
        self.num_events = num_events
        self.total_count = total_count

    def log_prob(self, value):
        n = float(self.total_count)

        def f(v, p):
            import jax
            import jax.numpy as jnp
            coef = (jax.scipy.special.gammaln(n + 1.0)
                    - jnp.sum(jax.scipy.special.gammaln(v + 1.0), -1))
            # xlogy: a zero count against a zero probability contributes 0
            return coef + jnp.sum(jax.scipy.special.xlogy(v, p), -1)
        return _call(f, value, self.prob)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape[:-1])
        n, k = self.total_count, self.num_events

        def f(p):
            import jax
            import jax.numpy as jnp
            draws = jax.random.categorical(
                key, jnp.log(p), shape=(n,) + shape)
            return jnp.sum(jax.nn.one_hot(draws, k, dtype=p.dtype), 0)
        return _call(f, self.prob)

    @property
    def mean(self):
        return self.prob * self.total_count

    @property
    def variance(self):
        return self.total_count * self.prob * (1 - self.prob)


class OneHotCategorical(Categorical):
    """≙ distributions/one_hot_categorical.py:46 — samples are one-hot;
    log_prob consumes one-hot values."""

    def log_prob(self, value):
        def f(v, logit):
            import jax
            import jax.numpy as jnp
            logp = jax.nn.log_softmax(logit, axis=-1)
            return jnp.sum(v * logp, -1)
        return _call(f, value, self.logit)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape[:-1])
        k = self.num_events

        def f(logit):
            import jax
            draws = jax.random.categorical(key, logit, shape=shape)
            return jax.nn.one_hot(draws, k, dtype=logit.dtype)
        return _call(f, self.logit)

    @property
    def mean(self):
        return self.prob

    @property
    def variance(self):
        return self.prob * (1 - self.prob)


class RelaxedBernoulli(Distribution):
    """≙ distributions/relaxed_bernoulli.py:50 — binary Concrete with
    temperature T; reparameterized (gradients flow through sample)."""

    def __init__(self, T, prob=None, logit=None):
        if (prob is None) == (logit is None):
            raise MXNetError("pass exactly one of prob/logit")
        if logit is None:
            from ... import numpy as mxnp
            p = _as_nd(prob)
            logit = mxnp.log(p) - mxnp.log1p(-p)
        super().__init__(T=T, logit=logit)

    @property
    def prob(self):
        from ... import numpy_extension as npx
        return npx.sigmoid(self.logit)

    def log_prob(self, value):
        def f(v, t, logit):
            import jax.numpy as jnp
            # binary Concrete density (Maddison et al. 2017, eq. 24)
            diff = logit - t * (jnp.log(v) - jnp.log1p(-v))
            return (jnp.log(t) + diff - jnp.log(v) - jnp.log1p(-v)
                    - 2 * jnp.logaddexp(0.0, diff))
        return _call(f, value, self.T, self.logit)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(t, logit):
            import jax
            import jax.numpy as jnp
            u = jax.random.uniform(key, shape, minval=1e-7, maxval=1 - 1e-7)
            logistic = jnp.log(u) - jnp.log1p(-u)
            return jax.nn.sigmoid((logit + logistic) / t)
        return _call(f, self.T, self.logit)


class RelaxedOneHotCategorical(Distribution):
    """≙ distributions/relaxed_one_hot_categorical.py:53 — Concrete
    distribution on the simplex with temperature T."""

    def __init__(self, T, num_events, prob=None, logit=None):
        if (prob is None) == (logit is None):
            raise MXNetError("pass exactly one of prob/logit")
        if logit is None:
            from ... import numpy as mxnp
            logit = mxnp.log(_as_nd(prob) + 1e-12)
        super().__init__(T=T, logit=logit)
        self.num_events = num_events

    def log_prob(self, value):
        k = self.num_events

        def f(v, t, logit):
            import jax
            import jax.numpy as jnp
            # Concrete density (Maddison et al. 2017, eq. 22)
            score = logit - t * jnp.log(v)
            return (jax.scipy.special.gammaln(float(k))
                    + (k - 1) * jnp.log(t)
                    + jnp.sum(score - jnp.log(v), -1)
                    - k * jax.scipy.special.logsumexp(score, -1))
        return _call(f, value, self.T, self.logit)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(t, logit):
            import jax
            g = jax.random.gumbel(key, shape)
            return jax.nn.softmax((logit + g) / t, axis=-1)
        return _call(f, self.T, self.logit)


class Independent(Distribution):
    """≙ distributions/independent.py:28 — reinterpret the rightmost
    `reinterpreted_batch_ndims` batch dims of `base` as event dims (sums
    them out of log_prob/entropy)."""

    def __init__(self, base_distribution, reinterpreted_batch_ndims):
        self.base_dist = base_distribution
        self.reinterpreted_batch_ndims = int(reinterpreted_batch_ndims)
        self._params = {}

    @property
    def has_grad(self):
        return self.base_dist.has_grad

    @property
    def batch_shape(self):
        b = self.base_dist.batch_shape
        return b[:len(b) - self.reinterpreted_batch_ndims]

    def _sum_rightmost(self, x):
        from ... import numpy as mxnp
        n = self.reinterpreted_batch_ndims
        if n == 0:
            return x
        return mxnp.sum(x.reshape(x.shape[:x.ndim - n] + (-1,)), axis=-1)

    def log_prob(self, value):
        return self._sum_rightmost(self.base_dist.log_prob(value))

    def sample(self, size=None):
        return self.base_dist.sample(size)

    def sample_n(self, size=None):
        return self.base_dist.sample_n(size)

    def entropy(self):
        return self._sum_rightmost(self.base_dist.entropy())

    @property
    def mean(self):
        return self.base_dist.mean

    @property
    def variance(self):
        return self.base_dist.variance

    def __repr__(self):
        return (f"Independent({self.base_dist!r}, "
                f"{self.reinterpreted_batch_ndims})")


# ---------------------------------------------------------------------------
# KL divergences (≙ probability KL registry)
# ---------------------------------------------------------------------------
@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    from ... import numpy as mxnp
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1 - mxnp.log(var_ratio))


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p, q):
    from ... import numpy as mxnp
    eps = 1e-12
    a, b = p.prob, q.prob
    return (a * (mxnp.log(a + eps) - mxnp.log(b + eps))
            + (1 - a) * (mxnp.log1p(-a + eps) - mxnp.log1p(-b + eps)))


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    def f(lp, lq):
        import jax
        import jax.numpy as jnp
        a = jax.nn.log_softmax(lp, -1)
        b = jax.nn.log_softmax(lq, -1)
        return jnp.sum(jnp.exp(a) * (a - b), -1)
    return _call(f, p.logit, q.logit)


@register_kl(Exponential, Exponential)
def _kl_exp_exp(p, q):
    from ... import numpy as mxnp
    rate_ratio = q.scale / p.scale
    return mxnp.log(rate_ratio) + 1.0 / rate_ratio - 1.0


@register_kl(Uniform, Uniform)
def _kl_unif_unif(p, q):
    from ... import numpy as mxnp
    return mxnp.log((q.high - q.low) / (p.high - p.low))


@register_kl(OneHotCategorical, OneHotCategorical)
def _kl_onehot_onehot(p, q):
    return _kl_cat_cat(p, q)


@register_kl(Cauchy, Cauchy)
def _kl_cauchy_cauchy(p, q):
    from ... import numpy as mxnp
    return mxnp.log(((p.scale + q.scale) ** 2 + (p.loc - q.loc) ** 2)
                    / (4 * p.scale * q.scale))


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    from ... import numpy as mxnp
    d = mxnp.abs(p.loc - q.loc)
    return (mxnp.log(q.scale / p.scale) - 1
            + (p.scale * mxnp.exp(-d / p.scale) + d) / q.scale)


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p, q):
    from ... import numpy as mxnp
    return p.rate * (mxnp.log(p.rate) - mxnp.log(q.rate)) + q.rate - p.rate


@register_kl(Geometric, Geometric)
def _kl_geom_geom(p, q):
    from ... import numpy as mxnp
    a, b = p.prob, q.prob
    return (mxnp.log(a) - mxnp.log(b)
            + (1 - a) / a * (mxnp.log1p(-a) - mxnp.log1p(-b)))


@register_kl(Pareto, Pareto)
def _kl_pareto_pareto(p, q):
    """Finite only when p's support lies inside q's (scale_p >= scale_q)."""
    def f(a1, s1, a2, s2):
        import jax.numpy as jnp
        kl = (a2 * (jnp.log(s1) - jnp.log(s2))
              + jnp.log(a1 / a2) + (a2 - a1) / a1)
        return jnp.where(s1 >= s2, kl, jnp.inf)
    return _call(f, p.alpha, p.scale, q.alpha, q.scale)


@register_kl(Gumbel, Gumbel)
def _kl_gumbel_gumbel(p, q):
    def f(l1, s1, l2, s2):
        import jax
        import jax.numpy as jnp
        ratio = s1 / s2
        dz = (l1 - l2) / s2
        return (jnp.log(s2 / s1) + _np.euler_gamma * (ratio - 1) + dz
                + jnp.exp(-dz + jax.scipy.special.gammaln(ratio + 1)) - 1)
    return _call(f, p.loc, p.scale, q.loc, q.scale)


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    def f(a1, s1, a2, s2):
        import jax
        import jax.numpy as jnp
        dig = jax.scipy.special.digamma(a1)
        return ((a1 - a2) * dig - jax.scipy.special.gammaln(a1)
                + jax.scipy.special.gammaln(a2)
                + a2 * (jnp.log(s2) - jnp.log(s1)) + a1 * (s1 / s2 - 1))
    return _call(f, p.shape_param, p.scale, q.shape_param, q.scale)


@register_kl(Chi2, Chi2)
def _kl_chi2_chi2(p, q):
    return _kl_gamma_gamma(p, q)


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    def f(a1, b1, a2, b2):
        import jax
        sp = jax.scipy.special
        lbeta = lambda a, b: sp.gammaln(a) + sp.gammaln(b) - sp.gammaln(a + b)
        return (lbeta(a2, b2) - lbeta(a1, b1)
                + (a1 - a2) * sp.digamma(a1) + (b1 - b2) * sp.digamma(b1)
                + (a2 - a1 + b2 - b1) * sp.digamma(a1 + b1))
    return _call(f, p.alpha, p.beta, q.alpha, q.beta)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    def f(a1, a2):
        import jax
        import jax.numpy as jnp
        sp = jax.scipy.special
        s1 = jnp.sum(a1, -1)
        return (sp.gammaln(s1) - jnp.sum(sp.gammaln(a1), -1)
                - sp.gammaln(jnp.sum(a2, -1)) + jnp.sum(sp.gammaln(a2), -1)
                + jnp.sum((a1 - a2)
                          * (sp.digamma(a1) - sp.digamma(s1)[..., None]),
                          -1))
    return _call(f, p.alpha, q.alpha)


@register_kl(HalfNormal, HalfNormal)
def _kl_halfnormal_halfnormal(p, q):
    from ... import numpy as mxnp
    var_ratio = (p.scale / q.scale) ** 2
    return 0.5 * (var_ratio - 1 - mxnp.log(var_ratio))


@register_kl(Binomial, Binomial)
def _kl_binom_binom(p, q):
    if p.n != q.n:
        raise MXNetError("KL(Binomial||Binomial) needs equal trial counts")
    return p.n * _kl_bern_bern(p, q)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    def f(l1, L1, l2, L2):
        import jax
        import jax.numpy as jnp
        d = l1.shape[-1]
        # tr(S2^-1 S1) = ||L2^-1 L1||_F^2 ; Mahalanobis via a solve
        M = jax.scipy.linalg.solve_triangular(L2, L1, lower=True)
        tr = jnp.sum(M * M, (-2, -1))
        diff = jax.scipy.linalg.solve_triangular(
            L2, (l2 - l1)[..., None], lower=True)[..., 0]
        maha = jnp.sum(diff * diff, -1)
        ld1 = jnp.sum(jnp.log(jnp.diagonal(L1, axis1=-2, axis2=-1)), -1)
        ld2 = jnp.sum(jnp.log(jnp.diagonal(L2, axis1=-2, axis2=-1)), -1)
        return 0.5 * (tr + maha - d) + ld2 - ld1
    return _call(f, p.loc, p.scale_tril, q.loc, q.scale_tril)


@register_kl(Uniform, Normal)
def _kl_unif_normal(p, q):
    def f(lo, hi, loc, scale):
        import jax.numpy as jnp
        w = hi - lo
        # E[(x-mu)^2] for x~U[lo,hi]
        ex2 = ((hi - loc) ** 3 - (lo - loc) ** 3) / (3 * w)
        return (-jnp.log(w) + 0.5 * math.log(2 * math.pi)
                + jnp.log(scale) + ex2 / (2 * scale ** 2))
    return _call(f, p.low, p.high, q.loc, q.scale)


@register_kl(Uniform, Gumbel)
def _kl_unif_gumbel(p, q):
    def f(lo, hi, loc, scale):
        import jax.numpy as jnp
        w = hi - lo
        ez = ((hi + lo) / 2 - loc) / scale       # E[(x-loc)/scale]
        # E[exp(-(x-loc)/scale)] in closed form over the box
        eexp = (scale / w) * (jnp.exp(-(lo - loc) / scale)
                              - jnp.exp(-(hi - loc) / scale))
        return -jnp.log(w) + jnp.log(scale) + ez + eexp
    return _call(f, p.low, p.high, q.loc, q.scale)


@register_kl(Exponential, Gamma)
def _kl_exp_gamma(p, q):
    def f(s, a, th):
        import jax
        import jax.numpy as jnp
        # E_p[ln x] = ln s - gamma for x~Exp(scale=s)
        return (-jnp.log(s) - 1
                - (a - 1) * (jnp.log(s) - _np.euler_gamma)
                + jax.scipy.special.gammaln(a) + a * jnp.log(th) + s / th)
    return _call(f, p.scale, q.shape_param, q.scale)


def empirical_kl(p, q, n_samples=10000):
    """Monte-Carlo KL(p||q) ≙ divergence.py `empirical_kl`."""
    from ... import numpy as mxnp
    x = p.sample((n_samples,))
    return mxnp.mean(p.log_prob(x) - q.log_prob(x), axis=0)
