"""Distribution zoo (≙ gluon/probability/distributions/*)."""
from __future__ import annotations

import math

import numpy as _np

from ...base import MXNetError
from ...ndarray import NDArray, _as_nd, _wrap
from ...ops.registry import invoke
from ... import random as _random

__all__ = ["Distribution", "Normal", "Bernoulli", "Categorical", "Uniform",
           "Exponential", "Gamma", "Poisson", "Laplace", "Beta", "Dirichlet",
           "StudentT", "HalfNormal", "Cauchy", "Geometric", "Binomial",
           "MultivariateNormal", "kl_divergence", "register_kl"]

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    """Decorator registering a KL(p||q) implementation (≙ register_kl)."""
    def _reg(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return _reg


def kl_divergence(p, q):
    """≙ mx.gluon.probability.kl_divergence."""
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise MXNetError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


def _jx():
    import jax
    return jax


def _call(fn, *arrays):
    return invoke(fn, tuple(_as_nd(a) for a in arrays), name="prob")


def _sample_shape(size, batch_shape):
    if size is None:
        return tuple(batch_shape)
    if isinstance(size, int):
        size = (size,)
    return tuple(size) + tuple(batch_shape)


class Distribution:
    """Base distribution (≙ probability.Distribution)."""

    has_grad = True

    def __init__(self, **params):
        self._params = {k: _as_nd(v) if not isinstance(v, NDArray) else v
                        for k, v in params.items() if v is not None}
        for k, v in self._params.items():
            setattr(self, k, v)

    @property
    def batch_shape(self):
        shapes = [p.shape for p in self._params.values()]
        if not shapes:
            return ()
        return _np.broadcast_shapes(*shapes)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ... import numpy as mxnp
        return mxnp.exp(self.log_prob(value))

    def sample(self, size=None):
        raise NotImplementedError

    def sample_n(self, size=None):
        return self.sample(size)

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        from ... import numpy as mxnp
        return mxnp.sqrt(self.variance)

    def entropy(self):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({list(self._params)})"


class Normal(Distribution):
    def __init__(self, loc=0.0, scale=1.0):
        super().__init__(loc=loc, scale=scale)

    def log_prob(self, value):
        def f(v, loc, scale):
            import jax.numpy as jnp
            var = scale ** 2
            return (-((v - loc) ** 2) / (2 * var)
                    - jnp.log(scale) - 0.5 * math.log(2 * math.pi))
        return _call(f, value, self.loc, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(loc, scale):
            import jax
            return loc + scale * jax.random.normal(key, shape)
        return _call(f, self.loc, self.scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale ** 2

    def entropy(self):
        from ... import numpy as mxnp
        return 0.5 + 0.5 * math.log(2 * math.pi) + mxnp.log(self.scale)


class HalfNormal(Normal):
    def log_prob(self, value):
        def f(v, loc, scale):
            import jax.numpy as jnp
            var = scale ** 2
            lp = (-((v - loc) ** 2) / (2 * var) - jnp.log(scale)
                  - 0.5 * math.log(2 * math.pi) + math.log(2.0))
            return jnp.where(v >= loc, lp, -_np.inf)
        return _call(f, value, self.loc, self.scale)

    def sample(self, size=None):
        from ... import numpy as mxnp
        return self.loc + mxnp.abs(super().sample(size) - self.loc)

    @property
    def mean(self):
        return self.loc + self.scale * math.sqrt(2 / math.pi)

    @property
    def variance(self):
        return (self.scale ** 2) * (1 - 2 / math.pi)


class Laplace(Distribution):
    def __init__(self, loc=0.0, scale=1.0):
        super().__init__(loc=loc, scale=scale)

    def log_prob(self, value):
        def f(v, loc, scale):
            import jax.numpy as jnp
            return -jnp.abs(v - loc) / scale - jnp.log(2 * scale)
        return _call(f, value, self.loc, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(loc, scale):
            import jax
            return loc + scale * jax.random.laplace(key, shape)
        return _call(f, self.loc, self.scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return 2 * self.scale ** 2

    def entropy(self):
        from ... import numpy as mxnp
        return 1 + mxnp.log(2 * self.scale)


class Cauchy(Distribution):
    has_grad = True

    def __init__(self, loc=0.0, scale=1.0):
        super().__init__(loc=loc, scale=scale)

    def log_prob(self, value):
        def f(v, loc, scale):
            import jax.numpy as jnp
            return (-math.log(math.pi) - jnp.log(scale)
                    - jnp.log1p(((v - loc) / scale) ** 2))
        return _call(f, value, self.loc, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(loc, scale):
            import jax
            return loc + scale * jax.random.cauchy(key, shape)
        return _call(f, self.loc, self.scale)


class Uniform(Distribution):
    def __init__(self, low=0.0, high=1.0):
        super().__init__(low=low, high=high)

    def log_prob(self, value):
        def f(v, low, high):
            import jax.numpy as jnp
            inside = (v >= low) & (v <= high)
            return jnp.where(inside, -jnp.log(high - low), -_np.inf)
        return _call(f, value, self.low, self.high)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(low, high):
            import jax
            return low + (high - low) * jax.random.uniform(key, shape)
        return _call(f, self.low, self.high)

    @property
    def mean(self):
        return (self.low + self.high) / 2

    @property
    def variance(self):
        return ((self.high - self.low) ** 2) / 12

    def entropy(self):
        from ... import numpy as mxnp
        return mxnp.log(self.high - self.low)


class Exponential(Distribution):
    def __init__(self, scale=1.0):
        super().__init__(scale=scale)

    def log_prob(self, value):
        def f(v, scale):
            import jax.numpy as jnp
            return jnp.where(v >= 0, -v / scale - jnp.log(scale), -_np.inf)
        return _call(f, value, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(scale):
            import jax
            return scale * jax.random.exponential(key, shape)
        return _call(f, self.scale)

    @property
    def mean(self):
        return self.scale

    @property
    def variance(self):
        return self.scale ** 2

    def entropy(self):
        from ... import numpy as mxnp
        return 1 + mxnp.log(self.scale)


class Gamma(Distribution):
    def __init__(self, shape=1.0, scale=1.0):
        super().__init__(shape_param=shape, scale=scale)

    def log_prob(self, value):
        def f(v, a, s):
            import jax
            import jax.numpy as jnp
            return ((a - 1) * jnp.log(v) - v / s
                    - jax.scipy.special.gammaln(a) - a * jnp.log(s))
        return _call(f, value, self.shape_param, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(a, s):
            import jax
            return s * jax.random.gamma(key, a, shape)
        return _call(f, self.shape_param, self.scale)

    @property
    def mean(self):
        return self.shape_param * self.scale

    @property
    def variance(self):
        return self.shape_param * self.scale ** 2


class Beta(Distribution):
    def __init__(self, alpha=1.0, beta=1.0):
        super().__init__(alpha=alpha, beta=beta)

    def log_prob(self, value):
        def f(v, a, b):
            import jax
            import jax.numpy as jnp
            lbeta = (jax.scipy.special.gammaln(a)
                     + jax.scipy.special.gammaln(b)
                     - jax.scipy.special.gammaln(a + b))
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta
        return _call(f, value, self.alpha, self.beta)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(a, b):
            import jax
            return jax.random.beta(key, a, b, shape)
        return _call(f, self.alpha, self.beta)

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s * s * (s + 1))


class Dirichlet(Distribution):
    def __init__(self, alpha):
        super().__init__(alpha=alpha)

    def log_prob(self, value):
        def f(v, a):
            import jax
            import jax.numpy as jnp
            lnorm = (jnp.sum(jax.scipy.special.gammaln(a), -1)
                     - jax.scipy.special.gammaln(jnp.sum(a, -1)))
            return jnp.sum((a - 1) * jnp.log(v), -1) - lnorm
        return _call(f, value, self.alpha)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.alpha.shape[:-1])

        def f(a):
            import jax
            return jax.random.dirichlet(key, a, shape)
        return _call(f, self.alpha)

    @property
    def mean(self):
        return self.alpha / self.alpha.sum(axis=-1, keepdims=True)


class Poisson(Distribution):
    has_grad = False

    def __init__(self, rate=1.0):
        super().__init__(rate=rate)

    def log_prob(self, value):
        def f(v, rate):
            import jax
            import jax.numpy as jnp
            return v * jnp.log(rate) - rate - jax.scipy.special.gammaln(v + 1)
        return _call(f, value, self.rate)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(rate):
            import jax
            return jax.random.poisson(key, rate, shape).astype("float32")
        return _call(f, self.rate)

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate


class Bernoulli(Distribution):
    has_grad = False

    def __init__(self, prob=None, logit=None):
        if (prob is None) == (logit is None):
            raise MXNetError("pass exactly one of prob/logit")
        if logit is not None:
            from ... import numpy_extension as npx
            prob = npx.sigmoid(_as_nd(logit))
        super().__init__(prob=prob)

    def log_prob(self, value):
        def f(v, p):
            import jax.numpy as jnp
            eps = 1e-12
            return v * jnp.log(p + eps) + (1 - v) * jnp.log1p(-p + eps)
        return _call(f, value, self.prob)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(p):
            import jax
            return jax.random.bernoulli(key, p, shape).astype("float32")
        return _call(f, self.prob)

    @property
    def mean(self):
        return self.prob

    @property
    def variance(self):
        return self.prob * (1 - self.prob)

    def entropy(self):
        from ... import numpy as mxnp
        p = self.prob
        eps = 1e-12
        return -(p * mxnp.log(p + eps) + (1 - p) * mxnp.log1p(-p + eps))


class Geometric(Distribution):
    has_grad = False

    def __init__(self, prob):
        super().__init__(prob=prob)

    def log_prob(self, value):
        def f(v, p):
            import jax.numpy as jnp
            return v * jnp.log1p(-p) + jnp.log(p)
        return _call(f, value, self.prob)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(p):
            import jax
            import jax.numpy as jnp
            u = jax.random.uniform(key, shape, minval=1e-7)
            return jnp.floor(jnp.log(u) / jnp.log1p(-p))
        return _call(f, self.prob)

    @property
    def mean(self):
        return (1 - self.prob) / self.prob


class Binomial(Distribution):
    has_grad = False

    def __init__(self, n=1, prob=0.5):
        self.n = int(n)
        super().__init__(prob=prob)

    def log_prob(self, value):
        n = self.n

        def f(v, p):
            import jax
            import jax.numpy as jnp
            logc = (jax.scipy.special.gammaln(n + 1.0)
                    - jax.scipy.special.gammaln(v + 1.0)
                    - jax.scipy.special.gammaln(n - v + 1.0))
            return logc + v * jnp.log(p) + (n - v) * jnp.log1p(-p)
        return _call(f, value, self.prob)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)
        n = self.n

        def f(p):
            import jax
            return jax.random.binomial(key, n, p, shape=shape)
        return _call(f, self.prob)

    @property
    def mean(self):
        return self.n * self.prob

    @property
    def variance(self):
        return self.n * self.prob * (1 - self.prob)


class Categorical(Distribution):
    has_grad = False

    def __init__(self, num_events=None, prob=None, logit=None):
        if (prob is None) == (logit is None):
            raise MXNetError("pass exactly one of prob/logit")
        if logit is None:
            from ... import numpy as mxnp
            logit = mxnp.log(_as_nd(prob) + 1e-12)
        super().__init__(logit=logit)
        self.num_events = num_events or self.logit.shape[-1]

    @property
    def prob(self):
        from ... import numpy_extension as npx
        return npx.softmax(self.logit, axis=-1)

    def log_prob(self, value):
        def f(v, logit):
            import jax
            import jax.numpy as jnp
            logp = jax.nn.log_softmax(logit, axis=-1)
            return jnp.take_along_axis(
                logp, v.astype(jnp.int32)[..., None], axis=-1)[..., 0]
        return _call(f, value, self.logit)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape[:-1])

        def f(logit):
            import jax
            return jax.random.categorical(key, logit, shape=shape)
        return _call(f, self.logit)

    def entropy(self):
        def f(logit):
            import jax
            import jax.numpy as jnp
            logp = jax.nn.log_softmax(logit, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)
        return _call(f, self.logit)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0):
        super().__init__(df=df, loc=loc, scale=scale)

    def log_prob(self, value):
        def f(v, df, loc, scale):
            import jax
            import jax.numpy as jnp
            z = (v - loc) / scale
            return (jax.scipy.special.gammaln((df + 1) / 2)
                    - jax.scipy.special.gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(scale)
                    - (df + 1) / 2 * jnp.log1p(z * z / df))
        return _call(f, value, self.df, self.loc, self.scale)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(df, loc, scale):
            import jax
            return loc + scale * jax.random.t(key, df, shape)
        return _call(f, self.df, self.loc, self.scale)


class MultivariateNormal(Distribution):
    def __init__(self, loc, cov=None, scale_tril=None):
        if (cov is None) == (scale_tril is None):
            raise MXNetError("pass exactly one of cov/scale_tril")
        if scale_tril is None:
            def chol(c):
                import jax.numpy as jnp
                return jnp.linalg.cholesky(c)
            scale_tril = _call(chol, cov)
        super().__init__(loc=loc, scale_tril=scale_tril)

    def log_prob(self, value):
        def f(v, loc, L):
            import jax
            import jax.numpy as jnp
            d = loc.shape[-1]
            diff = v - loc
            sol = jax.scipy.linalg.solve_triangular(L, diff[..., None],
                                                    lower=True)[..., 0]
            logdet = jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), -1)
            return (-0.5 * jnp.sum(sol * sol, -1) - logdet
                    - 0.5 * d * math.log(2 * math.pi))
        return _call(f, value, self.loc, self.scale_tril)

    def sample(self, size=None):
        key = _random.next_key()
        shape = _sample_shape(size, self.batch_shape)

        def f(loc, L):
            import jax
            import jax.numpy as jnp
            eps = jax.random.normal(key, shape)
            return loc + jnp.einsum("...ij,...j->...i", L, eps)
        return _call(f, self.loc, self.scale_tril)

    @property
    def mean(self):
        return self.loc


# ---------------------------------------------------------------------------
# KL divergences (≙ probability KL registry)
# ---------------------------------------------------------------------------
@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    from ... import numpy as mxnp
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1 - mxnp.log(var_ratio))


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p, q):
    from ... import numpy as mxnp
    eps = 1e-12
    a, b = p.prob, q.prob
    return (a * (mxnp.log(a + eps) - mxnp.log(b + eps))
            + (1 - a) * (mxnp.log1p(-a + eps) - mxnp.log1p(-b + eps)))


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    def f(lp, lq):
        import jax
        import jax.numpy as jnp
        a = jax.nn.log_softmax(lp, -1)
        b = jax.nn.log_softmax(lq, -1)
        return jnp.sum(jnp.exp(a) * (a - b), -1)
    return _call(f, p.logit, q.logit)


@register_kl(Exponential, Exponential)
def _kl_exp_exp(p, q):
    from ... import numpy as mxnp
    rate_ratio = q.scale / p.scale
    return mxnp.log(rate_ratio) + 1.0 / rate_ratio - 1.0


@register_kl(Uniform, Uniform)
def _kl_unif_unif(p, q):
    from ... import numpy as mxnp
    return mxnp.log((q.high - q.low) / (p.high - p.low))
