"""StochasticBlock (≙ gluon/probability/block/stochastic_block.py):
a HybridBlock that collects auxiliary losses (e.g. KL terms) added during
forward via add_loss."""
from __future__ import annotations

from ..block import HybridBlock
from ..nn import HybridSequential

__all__ = ["StochasticBlock", "StochasticSequential"]


class StochasticBlock(HybridBlock):
    def __init__(self):
        super().__init__()
        self._losses = []
        self._flag = False

    def add_loss(self, loss):
        self._flag = True
        self._losses.append(loss)

    @property
    def losses(self):
        return self._losses

    def __call__(self, *args, **kwargs):
        self._losses = []
        return super().__call__(*args, **kwargs)


class StochasticSequential(StochasticBlock):
    """≙ probability.StochasticSequential."""

    def __init__(self, *blocks):
        super().__init__()
        for b in blocks:
            self.add(b)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
            if isinstance(block, StochasticBlock):
                self._losses.extend(block.losses)
        return x
