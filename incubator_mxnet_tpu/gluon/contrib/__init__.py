"""gluon.contrib (≙ python/mxnet/gluon/contrib): estimator + extras."""
from . import estimator
from .fused import FusedTrainStep, FusedInferStep
