"""Estimator — Keras-like fit loop with event handlers.

Reference: python/mxnet/gluon/contrib/estimator/{estimator,event_handler}.py
(~1.5k LoC): Estimator.fit with train/val dataflow, EventHandler taxonomy
(TrainBegin/EpochBegin/BatchBegin/BatchEnd/EpochEnd/TrainEnd), built-ins:
StoppingHandler, MetricHandler, ValidationHandler, LoggingHandler,
CheckpointHandler, EarlyStoppingHandler.
"""
from __future__ import annotations

import logging
import os
import time

import numpy as _np

from ...base import MXNetError
from .. import metric as metric_mod
from ..trainer import Trainer

__all__ = ["Estimator", "EventHandler", "TrainBegin", "TrainEnd",
           "EpochBegin", "EpochEnd", "BatchBegin", "BatchEnd",
           "StoppingHandler", "MetricHandler", "ValidationHandler",
           "LoggingHandler", "CheckpointHandler", "EarlyStoppingHandler",
           "StepTimelineHandler"]


class EventHandler:
    pass


class TrainBegin(EventHandler):
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd(EventHandler):
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin(EventHandler):
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd(EventHandler):
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin(EventHandler):
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd(EventHandler):
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop at max_epoch/max_batch (≙ event_handler.StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        # a CheckpointHandler resume fast-forwards the epoch budget so a
        # 10-epoch fit interrupted after 7 runs 3 more, not 10
        self.current_epoch = getattr(estimator, "_resume_epoch", 0)
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            estimator.stop_training = True

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            estimator.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            estimator.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Reset per epoch, update per batch (≙ event_handler.MetricHandler)."""

    def __init__(self, metrics, priority=-1000):
        self.metrics = metrics
        self.priority = priority

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, pred=None, label=None, loss=None,
                  **kwargs):
        for m in self.metrics:
            if isinstance(m, metric_mod.Loss):
                m.update(None, loss)
            else:
                m.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run validation on a cadence (≙ event_handler.ValidationHandler)."""

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.priority = priority
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchEnd):
    """≙ event_handler.LoggingHandler."""

    def __init__(self, log_interval="epoch", metrics=None, priority=-3000):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.priority = priority
        self.batch_index = 0
        self.current_epoch = 0
        self.logger = logging.getLogger("estimator")

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        self.logger.info("Training done in %.1fs",
                         time.time() - self.train_start)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.batch_index = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.batch_index += 1
        if self.log_interval != "epoch" and \
                self.batch_index % int(self.log_interval) == 0:
            self._log()

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        self._log()

    def _log(self):
        msgs = [f"[epoch {self.current_epoch} batch {self.batch_index}]"]
        for m in self.metrics:
            name, value = m.get()
            msgs.append(f"{name}={value:.4f}"
                        if isinstance(value, float) else f"{name}={value}")
        self.logger.info(" ".join(msgs))


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Periodic + best-only checkpointing (≙ event_handler.CheckpointHandler)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5,
                 resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_epoch = 0
        self.current_batch = 0
        if mode == "auto":
            mode = "min" if monitor is not None and \
                "loss" in monitor.get()[0] else "max"
        self.mode = mode
        self.best = _np.inf if mode == "min" else -_np.inf
        self.resume_from_checkpoint = resume_from_checkpoint

    def train_begin(self, estimator, *args, **kwargs):
        os.makedirs(self.model_dir, exist_ok=True)
        if self.resume_from_checkpoint:
            self._resume(estimator)

    def _resume(self, estimator):
        """Load the newest epoch checkpoint in model_dir (params + trainer
        states) so an interrupted fit continues instead of restarting."""
        import re
        pat = re.compile(
            re.escape(self.model_prefix) + r"-epoch(\d+)\.params\.npz$")
        found = [(int(m.group(1)), m.group(0))
                 for m in map(pat.match, sorted(os.listdir(self.model_dir)))
                 if m]
        if not found:
            return
        epoch, name = max(found)
        path = os.path.join(self.model_dir, name)
        estimator.net.load_parameters(path)
        if estimator.trainer is not None and os.path.exists(path + ".states"):
            estimator.trainer.load_states(path + ".states")
        self.current_epoch = epoch
        estimator._resume_epoch = epoch  # StoppingHandler shortens the run
        best_meta = os.path.join(self.model_dir,
                                 f"{self.model_prefix}-best.json")
        if self.save_best and os.path.exists(best_meta):
            import json
            with open(best_meta) as f:
                self.best = json.load(f)["value"]
        estimator.logger.info("resumed from checkpoint %s (epoch %d)",
                              path, epoch)

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save(estimator, f"batch{self.current_batch}")

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self._save(estimator, f"epoch{self.current_epoch}")

    def _save(self, estimator, tag):
        # retried: a transient I/O failure must not kill a long fit, and the
        # atomic writes underneath guarantee no torn checkpoint either way
        from ... import fault as _fault

        @_fault.retrying(max_attempts=3, name="estimator.checkpoint")
        def _write():
            _fault.inject("estimator.checkpoint")
            if self.save_best and self.monitor is not None:
                _, value = self.monitor.get()
                improved = (value < self.best if self.mode == "min"
                            else value > self.best)
                if improved:
                    estimator.net.save_parameters(os.path.join(
                        self.model_dir,
                        f"{self.model_prefix}-best.params.npz"))
                    # persist the best value so a resumed fit does not
                    # clobber the best file with a worse model
                    import json
                    with _fault.atomic_output(
                            os.path.join(self.model_dir,
                                         f"{self.model_prefix}-best.json"),
                            mode="w") as f:
                        json.dump({"value": float(value),
                                   "mode": self.mode}, f)
                    # only after the write lands: a failed save must retry
                    # as still-improved, not silently skip the best file
                    self.best = value
            path = os.path.join(self.model_dir,
                                f"{self.model_prefix}-{tag}.params.npz")
            estimator.net.save_parameters(path)
            if estimator.trainer is not None:
                estimator.trainer.save_states(path + ".states")
        _write()


class StepTimelineHandler(TrainBegin, BatchBegin, BatchEnd, TrainEnd):
    """Per-step time attribution for a fit loop (telemetry.StepTimeline).

    Every batch runs inside a `telemetry.span("train.step")`, diffing the
    DeviceFeed stall clock and the kvstore allreduce clock around it, so
    after (and during) the run `estimator.step_timeline` answers "where
    did step time go" — data-stall vs compute vs (overlapped) H2D staging
    vs allreduce — plus a live-counter MFU when FLOPs are known.

    `flops_per_batch`: FLOPs of one train step. Default: on the first
    batch, XLA-count the forward via `telemetry.block_fwd_flops` and use
    the conventional 3x (fwd + 2x bwd) — the same numerator bench.py
    uses. Pass `flops_per_batch=None, auto_flops=False` to skip MFU.
    `peak_flops`: denominator; default `telemetry.device_peak_flops()`
    (None on CPU — MFU is then omitted rather than wrong).

    Attached automatically by `Estimator.fit` when `MXNET_TELEMETRY` is on
    (the default) unless the caller already passed one."""

    def __init__(self, flops_per_batch=None, peak_flops=None,
                 auto_flops=True, priority=-2000):
        self.flops_per_batch = flops_per_batch
        self.peak_flops = peak_flops
        self.auto_flops = auto_flops
        self.priority = priority
        self._tl = None
        self._step_cm = None
        # deferred-shape nets resolve on the FIRST forward, so the first
        # batch_begin can't cost-count yet — retry a few batches before
        # giving up on MFU for the run
        self._flops_tries = 3

    def train_begin(self, estimator, *args, **kwargs):
        from ... import telemetry
        self._close_step()       # a prior fit's exception-leaked step
        self._tl = telemetry.StepTimeline(
            flops_per_step=self.flops_per_batch,
            peak_flops=self.peak_flops)
        estimator.step_timeline = None

    def _close_step(self):
        if self._step_cm is not None:
            self._step_cm.__exit__(None, None, None)
            self._step_cm = None

    def batch_begin(self, estimator, batch=None, **kwargs):
        if self._tl is None:
            return
        # a step left open by an exception mid-batch (fit propagates, so
        # batch_end never fired) is closed here — the failed batch's time
        # is attributed and the span stack stays balanced
        self._close_step()
        if self.auto_flops and self._tl.flops_per_step is None \
                and batch is not None:
            # one XLA cost analysis per fit: the forward's compile lands
            # in jax's jit cache, so the real loop does not re-pay it
            try:
                from ... import telemetry
                x = batch[0] if isinstance(batch, (tuple, list)) else batch
                self._tl.flops_per_step = 3.0 * telemetry.block_fwd_flops(
                    estimator.net, x)
            except Exception:
                self._flops_tries -= 1
                if self._flops_tries <= 0:
                    self.auto_flops = False   # bounded: stop retrying
        self._step_cm = self._tl.step()
        self._step_cm.__enter__()

    def batch_end(self, estimator, *args, **kwargs):
        self._close_step()
        estimator.step_timeline = self._tl.report()

    def train_end(self, estimator, *args, **kwargs):
        self._close_step()
        if self._tl is not None and self._tl.steps:
            estimator.step_timeline = self._tl.report()
            estimator.logger.info("step timeline: %s",
                                  estimator.step_timeline)


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """≙ event_handler.EarlyStoppingHandler."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        if mode == "auto":
            mode = "min" if "loss" in monitor.get()[0] else "max"
        self.mode = mode
        self.baseline = baseline
        self.wait = 0
        self.best = _np.inf if mode == "min" else -_np.inf
        self.stopped_epoch = 0
        self.current_epoch = 0

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        _, value = self.monitor.get()
        if not isinstance(value, (int, float)) or _np.isnan(value):
            return
        improved = (value < self.best - self.min_delta if self.mode == "min"
                    else value > self.best + self.min_delta)
        if improved:
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self.current_epoch
                estimator.stop_training = True


class Estimator:
    """≙ gluon.contrib.estimator.Estimator."""

    def __init__(self, net, loss, train_metrics=None, val_metrics=None,
                 trainer=None, context=None, device=None,
                 evaluation_loss=None):
        self.net = net
        self.loss = loss
        self.train_metrics = train_metrics or [metric_mod.Accuracy()]
        if not isinstance(self.train_metrics, (list, tuple)):
            self.train_metrics = [self.train_metrics]
        self.train_metrics = list(self.train_metrics)
        self.train_metrics.append(metric_mod.Loss("train_loss"))
        self.val_metrics = val_metrics or [
            metric_mod.create(type(m).__name__.lower())
            for m in self.train_metrics[:-1]]
        self.trainer = trainer or Trainer(net.collect_params(), "adam",
                                          {"learning_rate": 1e-3})
        self.evaluation_loss = evaluation_loss or loss
        self.stop_training = False
        self.logger = logging.getLogger("mxnet.estimator")
        self._resume_epoch = 0
        # written by StepTimelineHandler: per-step time attribution +
        # (when FLOPs are known) live-counter MFU for the last fit()
        self.step_timeline = None

    # ------------------------------------------------------------------
    def evaluate(self, val_data):
        from ... import autograd
        for m in self.val_metrics:
            m.reset()
        for batch in val_data:
            x, y = batch[0], batch[1]
            with autograd.predict_mode():
                pred = self.net(x)
            for m in self.val_metrics:
                m.update(y, pred)
        return {m.get()[0]: m.get()[1] for m in self.val_metrics}

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None, batch_axis=0):
        from ... import autograd
        from ...base import get_env
        if epochs is None and batches is None:
            epochs = 1
        # MXNET_PREFETCH_TO_DEVICE: route batches through io.DeviceFeed so
        # host data prep + H2D for batch N+1 overlap batch N's step (the
        # feed re-iterates per epoch like any loader); skip when the loader
        # already feeds device batches (DeviceFeed, opted-in DataLoader) or
        # EXPLICITLY opted out (DataLoader(prefetch_to_device=False))
        if get_env("MXNET_PREFETCH_TO_DEVICE", False, typ=bool) and \
                not getattr(train_data, "_feeds_device", False) and \
                not getattr(train_data, "_prefetch_opt_out", False):
            from ...io.device_feed import DeviceFeed
            train_data = DeviceFeed(train_data, batch_axis=batch_axis)
        handlers = list(event_handlers or [])
        handlers.append(StoppingHandler(epochs, batches))
        handlers.append(MetricHandler(self.train_metrics))
        # step-timeline attribution (MXNET_TELEMETRY, default on): spans +
        # stall/compute split are near-free; MFU needs FLOPs, so the
        # auto-attached handler skips the extra cost-analysis compile —
        # pass StepTimelineHandler(auto_flops=True) (or flops_per_batch=)
        # to get mfu in estimator.step_timeline
        if get_env("MXNET_TELEMETRY", True, typ=bool) and \
                not any(isinstance(h, StepTimelineHandler)
                        for h in handlers):
            handlers.append(StepTimelineHandler(auto_flops=False))
        if val_data is not None:
            handlers.append(ValidationHandler(
                val_data, self.evaluate))
        handlers.sort(key=lambda h: getattr(h, "priority", 0))
        self.stop_training = False
        # stale resume state from a previous fit() must not shorten this
        # one; a CheckpointHandler resume re-sets it during train_begin
        self._resume_epoch = 0

        def emit(kind, **kw):
            for h in handlers:
                fn = getattr(h, kind, None)
                if fn is not None:
                    fn(self, **kw)

        emit("train_begin")
        while not self.stop_training:
            emit("epoch_begin")
            for batch in train_data:
                if self.stop_training:
                    break
                x, y = batch[0], batch[1]
                emit("batch_begin", batch=batch)
                with autograd.record():
                    pred = self.net(x)
                    loss = self.loss(pred, y)
                    loss_scalar = loss.mean()
                loss_scalar.backward()
                batch_size = x.shape[batch_axis]
                self.trainer.step(batch_size)
                emit("batch_end", pred=pred, label=y, loss=loss_scalar)
            emit("epoch_end")
        emit("train_end")
        return self
