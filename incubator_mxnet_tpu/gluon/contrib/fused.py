"""FusedTrainStep — one XLA program for fwd + loss + bwd + clip + update.

TPU-native counterpart of the reference's fused-RNN training capability
(src/operator/rnn.cc: the whole BPTT step as one kernel) generalized to ANY
HybridBlock: the forward, the loss, the backward, global-norm clipping and
the optimizer update all compile into a single jitted computation with
donated parameter/state buffers. No per-op dispatch, no per-step tape, no
host round-trips inside the step.

    step = FusedTrainStep(net, fn, optimizer)        # fn(net, *inputs)
    loss, *extras = step(x, y, ...)                  # one XLA execution

`fn` receives the live net and the step inputs and returns a scalar loss
NDArray (or a tuple (loss, *extras) — extras pass through untouched, e.g.
recurrent states). Optimizers whose `step_one` kernels are pure traceable
functions work (the same eligibility as the multi-tensor fused update
path); host-stateful rules (SGLD, Nadam) and multi_precision are
rejected at construction — use gluon.Trainer for those.
"""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError

__all__ = ["FusedTrainStep"]


class FusedTrainStep:
    def __init__(self, net, fn, optimizer, clip_global_norm=None):
        from ... import optimizer as opt_mod
        optimizer = opt_mod.create(optimizer)
        # same eligibility rules as the multi-tensor fused path
        # (optimizer/__init__.py fused_update_all): host-stateful rules
        # (SGLD's per-step noise key, Nadam's m_schedule) would be baked
        # in as trace-time constants, multi-precision needs the
        # update_multi_precision flow, and subclasses overriding update()
        # expect to be called per-param on the host.
        if not getattr(optimizer, "_fused_safe", True):
            raise MXNetError(
                f"{type(optimizer).__name__} keeps per-step host state and "
                "cannot be traced into one program; use gluon.Trainer")
        if optimizer.multi_precision:
            raise MXNetError(
                "multi_precision optimizers are not supported by "
                "FusedTrainStep yet; use gluon.Trainer")
        if (type(optimizer).update is not opt_mod.Optimizer.update
                or type(optimizer).update_multi_precision
                is not opt_mod.Optimizer.update_multi_precision):
            raise MXNetError(
                f"{type(optimizer).__name__} overrides update(); the "
                "extension point runs per-param on the host — use "
                "gluon.Trainer")
        self._net = net
        self._fn = fn
        self._opt = optimizer
        self._clip = clip_global_norm
        params = [p for _, p in sorted(net.collect_params().items())]
        for p in params:
            if p._data is None:
                raise MXNetError(
                    "FusedTrainStep needs a fully initialized net: run one "
                    "forward pass first (deferred shapes must be resolved)")
        self._params = params
        # same per-parameter lr_mult/wd_mult plumbing as gluon.Trainer
        # (trainer.py:48-57): _get_lr/_get_wd resolve multipliers through
        # optimizer.param_dict
        optimizer.param_dict = {i: p for i, p in enumerate(params)}
        self._train_idx = [i for i, p in enumerate(params)
                           if p.grad_req != "null"]
        self._frozen_idx = [i for i, p in enumerate(params)
                            if p.grad_req == "null"]
        self._states = None
        self._jit = None
        self._meta = {"aux_idx": None}  # frozen params mutated in forward

    # ------------------------------------------------------------------
    def _ensure_states(self):
        if self._states is None:
            self._states = [
                self._opt.create_state_multi_precision(
                    i, self._params[i].data())
                for i in self._train_idx]

    def _build(self):
        import jax
        import jax.numpy as jnp
        from ... import autograd, random as _random
        from ...ndarray import _wrap
        from ...optimizer import _state_bufs, _wrap_state

        params = self._params
        train_idx, frozen_idx = self._train_idx, self._frozen_idx
        net, fn, opt, clip = self._net, self._fn, self._opt, self._clip
        takes_t = type(opt)._step_takes_t()
        meta = self._meta

        def step(train_bufs, sbufs, frozen_bufs, key, lrs, wds, rescale, ts,
                 *in_raw):
            def loss_of(tbufs):
                full = [None] * len(params)
                for k, i in enumerate(train_idx):
                    full[i] = tbufs[k]
                for k, i in enumerate(frozen_idx):
                    full[i] = frozen_bufs[k]
                saved = []
                for p, buf in zip(params, full):
                    nd = p.data()
                    saved.append(nd._data)
                    nd._data = buf
                    nd._version += 1
                try:
                    with autograd._Scope(recording=False, training=True), \
                            _random.trace_key_scope(key):
                        out = fn(net, *[_wrap(r) for r in in_raw])
                    if isinstance(out, (tuple, list)):
                        loss, extras = out[0], tuple(out[1:])
                    else:
                        loss, extras = out, ()
                    loss_raw = loss._arr
                    extras_raw = tuple(e._arr for e in extras)
                    # aux state written during forward (BN running stats
                    # live on grad_req='null' params); which indices mutate
                    # is a trace-time constant, recorded once in meta
                    mutated = {}
                    for i, (p, buf) in enumerate(zip(params, full)):
                        cur = p.data()._data
                        if cur is not buf:
                            mutated[i] = cur
                    if meta["aux_idx"] is None:
                        meta["aux_idx"] = tuple(sorted(mutated))
                    aux_bufs = tuple(mutated[i] for i in sorted(mutated))
                finally:
                    for p, old in zip(params, saved):
                        p.data()._data = old
                return loss_raw, (extras_raw, aux_bufs)

            (loss, (extras, aux_bufs)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(list(train_bufs))

            if clip is not None:
                total = jnp.zeros((), jnp.float32)
                for g in grads:
                    total = total + jnp.sum(jnp.square(g.astype(jnp.float32)))
                norm = jnp.sqrt(total)
                scale = jnp.minimum(
                    1.0, clip / jnp.maximum(norm, 1e-12))
                grads = [g * scale.astype(g.dtype) for g in grads]

            prev = opt.rescale_grad
            opt.rescale_grad = rescale  # traced; inner kernels key on it
            try:
                new_w, new_s = [], []
                for k, i in enumerate(train_idx):
                    w = _wrap(train_bufs[k])
                    g = _wrap(grads[k])
                    st = _wrap_state(sbufs[k])
                    if takes_t:
                        opt.step_one(i, w, g, st, lrs[k], wds[k], t=ts[k])
                    else:
                        opt.step_one(i, w, g, st, lrs[k], wds[k])
                    new_w.append(w._arr)
                    new_s.append(_state_bufs(st))
            finally:
                opt.rescale_grad = prev
            return new_w, new_s, loss, extras, aux_bufs

        # donate only the trainable weight + optimizer-state buffers; frozen
        # params keep their buffers live across calls
        return jax.jit(step, donate_argnums=(0, 1))

    # ------------------------------------------------------------------
    def __call__(self, *inputs):
        from ... import random as _random
        from ...ndarray import NDArray, _wrap
        from ...optimizer import _state_bufs, _state_restore

        self._ensure_states()
        if self._jit is None:
            self._jit = self._build()
        opt = self._opt
        for i in self._train_idx:
            opt._update_count(i)
        lrs = _np.asarray([opt._get_lr(i) for i in self._train_idx],
                          _np.float32)
        wds = _np.asarray([opt._get_wd(i) for i in self._train_idx],
                          _np.float32)
        ts = (_np.asarray([opt._index_update_count[i]
                           for i in self._train_idx], _np.float32)
              if type(opt)._step_takes_t() else None)
        key = _random.next_key()
        train_bufs = [self._params[i].data()._arr for i in self._train_idx]
        frozen_bufs = [self._params[i].data()._arr for i in self._frozen_idx]
        sbufs = [_state_bufs(s) for s in self._states]
        in_raw = tuple(a._arr if isinstance(a, NDArray) else a
                       for a in inputs)

        new_w, new_s, loss, extras, aux_bufs = self._jit(
            train_bufs, sbufs, frozen_bufs, key, lrs, wds,
            _np.float32(opt.rescale_grad), ts, *in_raw)

        for k, i in enumerate(self._train_idx):
            self._params[i].data()._set_arr(new_w[k])
            _state_restore(self._states[k], new_s[k])
        for i, buf in zip(self._meta["aux_idx"], aux_bufs):
            self._params[i].data()._set_arr(buf)
        out = (_wrap(loss),) + tuple(_wrap(e) for e in extras)
        return out if len(out) > 1 else out[0]
