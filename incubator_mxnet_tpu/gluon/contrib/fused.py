"""FusedTrainStep — one XLA program for fwd + loss + bwd + clip + update.

TPU-native counterpart of the reference's fused-RNN training capability
(src/operator/rnn.cc: the whole BPTT step as one kernel) generalized to ANY
HybridBlock: the forward, the loss, the backward, global-norm clipping and
the optimizer update all compile into a single jitted computation with
donated parameter/state buffers. No per-op dispatch, no per-step tape, no
host round-trips inside the step.

    step = FusedTrainStep(net, fn, optimizer)        # fn(net, *inputs)
    loss, *extras = step(x, y, ...)                  # one XLA execution

`fn` receives the live net and the step inputs and returns a scalar loss
NDArray (or a tuple (loss, *extras) — extras pass through untouched, e.g.
recurrent states). Optimizers whose `step_one` kernels are pure traceable
functions work (the same eligibility as the multi-tensor fused update
path); host-stateful rules (SGLD, Nadam) and multi_precision are
rejected at construction — use gluon.Trainer for those.

Input staging: host-array inputs start an ASYNC device transfer before the
dispatch; inputs that are already committed device arrays with the right
placement (an `io.DeviceFeed`-staged batch) skip the transfer — feed the
step through `io.prefetch_to_device(loader)` and batch N+1's host prep +
H2D overlaps batch N's compute (`max(data, step)` instead of their sum).
"""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from ...ops import fused as _fused_mod

# "argument not given" marker for knobs whose default is a real value
# (None = XLA-default remat, True = donate) — lets the mx.tune profile
# tier slot in UNDER an explicit argument but OVER the built-in default
_TUNE_UNSET = object()

__all__ = ["FusedTrainStep", "FusedInferStep"]

_staging = None   # (jax.Array, maybe_device_put), resolved on first step


def _stage_raw(r):
    """Per-input staging for the step hot path: async H2D for host arrays,
    skip for committed device arrays (io.DeviceFeed batches), raw scalars
    untouched. Imports resolve ONCE — this runs per input per step."""
    global _staging
    if _staging is None:
        import jax
        from ...io.device_feed import maybe_device_put
        _staging = (jax.Array, maybe_device_put)
    arr_t, put = _staging
    return put(r) if isinstance(r, (arr_t, _np.ndarray)) else r


class FusedInferStep:
    """One jitted XLA program per inference step, chained elision-proof.

    The compiled step maps ``x -> (logits, x_next)`` where ``x_next`` is the
    donated input perturbed by a scalar derived from the logits. The data
    dependence means step N+1 cannot begin before step N produced its
    output and no step can be elided by a transport layer, while the host
    never blocks between dispatches — per-dispatch latency overlaps with
    device compute exactly like the fused training chain.

        step = FusedInferStep(net)
        out = step(x0)          # seed the chain
        for _ in range(n - 1):
            out = step()        # continue the chain, one dispatch each
        out.asnumpy()           # sync: forces the whole chain

    Reference counterpart: scoring-mode CachedOp dispatch
    (src/imperative/cached_op.cc Forward) — here the entire net is one XLA
    executable and consecutive calls pipeline through donated buffers.
    """

    def __init__(self, net, perturb=1e-6, steps_per_call=1,
                 use_fusion=None):
        params = [p for _, p in sorted(net.collect_params().items())]
        for p in params:
            if p._data is None:
                raise MXNetError(
                    "FusedInferStep needs a fully initialized net: run one "
                    "forward pass first")
        self._net = net
        self._params = params
        self._perturb = perturb
        self._K = int(steps_per_call)   # K chained forwards per dispatch
        # fused kernel tier (ops/fused.py): default on for the fused steps
        # per MXNET_USE_FUSION; the scope engages at trace time
        self._use_fusion = _fused_mod._env_use_fusion() \
            if use_fusion is None else bool(use_fusion)
        self._jit = None
        self._x = None
        self._pnds = None

    def _build(self):
        import jax
        import jax.numpy as jnp
        from ... import autograd, random as _random
        from ...ndarray import _wrap

        net, params, eps, n_steps = (self._net, self._params, self._perturb,
                                     self._K)
        use_fusion = self._use_fusion

        def one(pbufs, x):
            saved = []
            for p, buf in zip(params, pbufs):
                nd = p.data()
                saved.append(nd._data)
                nd._data = buf
                nd._version += 1
            try:
                key = jax.random.PRNGKey(0)  # inference: dropout inactive
                with autograd._Scope(recording=False, training=False), \
                        _random.trace_key_scope(key), \
                        _fused_mod.fusion_scope(use_fusion):
                    out = net(_wrap(x))
                logits = out._arr
            finally:
                for p, old in zip(params, saved):
                    # deliberate trace-time buffer swap: params point at the
                    # jit args during net(x), restored before tracing ends
                    p.data()._data = old  # mxlint: disable=trace-closure-mutation
            x_next = x + (eps * jnp.mean(logits)).astype(x.dtype)
            return logits, x_next

        def step(pbufs, x):
            if n_steps == 1:
                return one(pbufs, x)

            def body(carry, _):
                logits, x_next = one(pbufs, carry)
                return x_next, logits

            x_final, logits_all = jax.lax.scan(body, x, None,
                                               length=n_steps)
            return logits_all[-1], x_final

        from ... import sanitize as _sanitize
        return _sanitize.maybe_wrap_donated(
            jax.jit(step, donate_argnums=(1,)), (1,),
            "fused.chain_step")

    def lowered(self, x=None):
        """The chained-inference program lowered for inspection
        (`mx.inspect.inspect_step(step, x0)`) without executing or
        consuming the chain state. `x` may be omitted once the chain is
        seeded."""
        from ...ndarray import NDArray
        if self._jit is None:
            self._jit = self._build()
            self._pnds = [p.data() for p in self._params]
        if x is not None:
            raw = x._arr if isinstance(x, NDArray) else x
        elif self._x is not None:
            raw = self._x
        else:
            raise MXNetError("FusedInferStep.lowered needs an input: "
                             "pass x or seed the chain with step(x0)")
        pbufs = [nd._arr for nd in self._pnds]
        return self._jit.lower(pbufs, raw)

    def __call__(self, x=None):
        import jax.numpy as jnp
        from ...ndarray import NDArray, _wrap
        if self._jit is None:
            self._jit = self._build()
            self._pnds = [p.data() for p in self._params]
        if x is not None:
            raw = x._arr if isinstance(x, NDArray) else x
            # the chain buffer is donated every step — seed with a COPY so
            # the caller's array stays valid (and re-seeding works)
            self._x = jnp.array(raw, copy=True)
        if self._x is None:
            raise MXNetError("seed the chain: step(x0) before step()")
        pbufs = [nd._arr for nd in self._pnds]
        logits, self._x = self._jit(pbufs, self._x)
        return _wrap(logits)


class FusedTrainStep:
    """One XLA program per call; with ``steps_per_call=K`` the program runs K
    full train steps via ``lax.scan`` (weights/optimizer-state/BN-stats carry
    on device) — the standard TPU host-loop-elimination pattern: per-dispatch
    transport latency amortizes K-fold, which is what bounds small-batch
    throughput on remote-attached chips. Inputs then take a leading (K, ...)
    axis. The learning rate is resolved once per call (per-step schedules
    advance by optimizer update count as usual; within one call the lr is a
    trace constant, like the reference's update_on_kvstore batching)."""

    def __init__(self, net, fn, optimizer, clip_global_norm=None,
                 steps_per_call=1, remat=_TUNE_UNSET, donate=_TUNE_UNSET,
                 use_fusion=None):
        from ... import optimizer as opt_mod
        from ...tune.profile import resolve as _tune_resolve
        # knob precedence: explicit arg > deployment profile > default.
        # `None` is a meaningful remat policy (XLA default), so "caller
        # said nothing" needs its own sentinel for the profile tier.
        if remat is _TUNE_UNSET:
            remat = _tune_resolve("train.remat")
        if donate is _TUNE_UNSET:
            donate = _tune_resolve("train.donate", True)
        optimizer = opt_mod.create(optimizer)
        # same eligibility rules as the multi-tensor fused path
        # (optimizer/__init__.py fused_update_all): host-stateful rules
        # (SGLD's per-step noise key, Nadam's m_schedule) would be baked
        # in as trace-time constants, multi-precision needs the
        # update_multi_precision flow, and subclasses overriding update()
        # expect to be called per-param on the host.
        if not getattr(optimizer, "_fused_safe", True):
            raise MXNetError(
                f"{type(optimizer).__name__} keeps per-step host state and "
                "cannot be traced into one program; use gluon.Trainer")
        if optimizer.multi_precision:
            raise MXNetError(
                "multi_precision optimizers are not supported by "
                "FusedTrainStep yet; use gluon.Trainer")
        if (type(optimizer).update is not opt_mod.Optimizer.update
                or type(optimizer).update_multi_precision
                is not opt_mod.Optimizer.update_multi_precision):
            raise MXNetError(
                f"{type(optimizer).__name__} overrides update(); the "
                "extension point runs per-param on the host — use "
                "gluon.Trainer")
        self._net = net
        self._fn = fn
        self._opt = optimizer
        self._clip = clip_global_norm
        # remat: trade FLOPs for HBM traffic on the backward's saved
        # residuals — None (XLA default), "full" (recompute the whole
        # forward; near-zero residual traffic), "dots" (save matmul
        # outputs, recompute elementwise/conv chains). Which wins is
        # hardware-bound: bench.py A/Bs them on the attached chip.
        if remat not in (None, "full", "dots"):
            raise MXNetError(f"unknown remat policy {remat!r}")
        self._remat = remat
        # donate: hand the trainable weight + optimizer-state buffers to
        # XLA (in-place update, halves the peak weight footprint). The
        # off switch is the other arm of the bench policy sweep — some
        # program shapes schedule better without donation aliasing.
        self._donate = bool(donate)
        # fused kernel tier (ops/fused.py) — default ON for the fused
        # step per MXNET_USE_FUSION; the scope engages around the
        # forward trace so gluon blocks route through the fused ops
        self._use_fusion = _fused_mod._env_use_fusion() \
            if use_fusion is None else bool(use_fusion)
        self._K = int(steps_per_call)
        if self._K < 1:
            raise MXNetError("steps_per_call must be >= 1")
        params = [p for _, p in sorted(net.collect_params().items())]
        for p in params:
            if p._data is None:
                raise MXNetError(
                    "FusedTrainStep needs a fully initialized net: run one "
                    "forward pass first (deferred shapes must be resolved)")
        self._params = params
        # same per-parameter lr_mult/wd_mult plumbing as gluon.Trainer
        # (trainer.py:48-57): _get_lr/_get_wd resolve multipliers through
        # optimizer.param_dict
        optimizer.param_dict = {i: p for i, p in enumerate(params)}
        self._train_idx = [i for i, p in enumerate(params)
                           if p.grad_req != "null"]
        self._frozen_idx = [i for i, p in enumerate(params)
                            if p.grad_req == "null"]
        self._states = None
        self._jit = None
        self._meta = {"aux_idx": None}  # frozen params mutated in forward

    # ------------------------------------------------------------------
    def _ensure_states(self):
        if self._states is None:
            self._states = [
                self._opt.create_state_multi_precision(
                    i, self._params[i].data())
                for i in self._train_idx]

    def _build(self):
        import jax
        import jax.numpy as jnp
        from ... import autograd, random as _random
        from ...ndarray import _wrap
        from ...optimizer import _state_bufs, _wrap_state

        params = self._params
        train_idx, frozen_idx = self._train_idx, self._frozen_idx
        net, fn, opt, clip = self._net, self._fn, self._opt, self._clip
        takes_t = type(opt)._step_takes_t()
        meta = self._meta

        n_steps = self._K
        frozen_pos = {i: k for k, i in enumerate(frozen_idx)}
        use_fusion = self._use_fusion

        def one_step(train_bufs, sbufs, frozen_bufs, key, lrs, wds, rescale,
                     ts, in_raw):
            def loss_of(tbufs):
                full = [None] * len(params)
                for k, i in enumerate(train_idx):
                    full[i] = tbufs[k]
                for k, i in enumerate(frozen_idx):
                    full[i] = frozen_bufs[k]
                saved = []
                for p, buf in zip(params, full):
                    nd = p.data()
                    saved.append(nd._data)
                    nd._data = buf
                    nd._version += 1
                try:
                    with autograd._Scope(recording=False, training=True), \
                            _random.trace_key_scope(key), \
                            _fused_mod.fusion_scope(use_fusion):
                        out = fn(net, *[_wrap(r) for r in in_raw])
                    if isinstance(out, (tuple, list)):
                        loss, extras = out[0], tuple(out[1:])
                    else:
                        loss, extras = out, ()
                    loss_raw = loss._arr
                    extras_raw = tuple(e._arr for e in extras)
                    # aux state written during forward (BN running stats
                    # live on grad_req='null' params); which indices mutate
                    # is a trace-time constant, recorded once in meta
                    mutated = {}
                    for i, (p, buf) in enumerate(zip(params, full)):
                        cur = p.data()._data
                        if cur is not buf:
                            mutated[i] = cur
                    if meta["aux_idx"] is None:
                        # trace-time memo by design (see comment above)
                        meta["aux_idx"] = tuple(sorted(mutated))  # mxlint: disable=trace-closure-mutation
                    aux_bufs = tuple(mutated[i] for i in sorted(mutated))
                finally:
                    for p, old in zip(params, saved):
                        # deliberate trace-time buffer swap (see ChainStep)
                        p.data()._data = old  # mxlint: disable=trace-closure-mutation
                return loss_raw, (extras_raw, aux_bufs)

            # prevent_cse=False: we are always under jit (and under scan
            # for K>1), where the CSE-prevention barriers are unnecessary
            # and would slow the remat'd program (jax.checkpoint docs)
            if self._remat == "full":
                loss_of = jax.checkpoint(
                    loss_of, policy=jax.checkpoint_policies.nothing_saveable,
                    prevent_cse=False)
            elif self._remat == "dots":
                loss_of = jax.checkpoint(
                    loss_of, policy=jax.checkpoint_policies.dots_saveable,
                    prevent_cse=False)
            (loss, (extras, aux_bufs)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(list(train_bufs))

            if clip is not None:
                total = jnp.zeros((), jnp.float32)
                for g in grads:
                    total = total + jnp.sum(jnp.square(g.astype(jnp.float32)))
                norm = jnp.sqrt(total)
                scale = jnp.minimum(
                    1.0, clip / jnp.maximum(norm, 1e-12))
                grads = [g * scale.astype(g.dtype) for g in grads]

            prev = opt.rescale_grad
            # deliberate trace-time swap (inner kernels key on the traced
            # rescale), restored in finally below
            opt.rescale_grad = rescale  # mxlint: disable=trace-closure-mutation
            try:
                new_w, new_s = [], []
                for k, i in enumerate(train_idx):
                    w = _wrap(train_bufs[k])
                    g = _wrap(grads[k])
                    st = _wrap_state(sbufs[k])
                    if takes_t:
                        opt.step_one(i, w, g, st, lrs[k], wds[k], t=ts[k])
                    else:
                        opt.step_one(i, w, g, st, lrs[k], wds[k])
                    new_w.append(w._arr)
                    new_s.append(_state_bufs(st))
            finally:
                opt.rescale_grad = prev  # mxlint: disable=trace-closure-mutation -- restore of the trace-time swap
            # fold BN-stat updates back into the frozen set so a scanned
            # call carries them step to step
            new_frozen = list(frozen_bufs)
            for pos, i in enumerate(meta["aux_idx"]):
                new_frozen[frozen_pos[i]] = aux_bufs[pos]
            return new_w, new_s, new_frozen, loss, extras

        def step(train_bufs, sbufs, frozen_bufs, key, lrs, wds, rescale, ts,
                 *in_raw):
            if n_steps == 1:
                new_w, new_s, new_f, loss, extras = one_step(
                    train_bufs, sbufs, frozen_bufs, key, lrs, wds, rescale,
                    ts, in_raw)
                aux = tuple(new_f[frozen_pos[i]] for i in meta["aux_idx"])
                return new_w, new_s, loss, extras, aux

            # K train steps in ONE XLA program: weights/opt-state/BN-stats
            # carry on device, inputs have a leading (K, ...) axis
            keys = jax.random.split(key, n_steps)

            def body(carry, per):
                tb, sb, fb, t_off = carry
                key_k = per[0]
                in_k = per[1:]
                ts_k = None if ts is None else [t + t_off for t in ts]
                nw, ns, nf, loss, extras = one_step(
                    tuple(tb), tuple(sb), tuple(fb), key_k, lrs, wds,
                    rescale, ts_k, in_k)
                return ((tuple(nw), tuple(ns), tuple(nf), t_off + 1.0),
                        (loss, extras))

            carry0 = (tuple(train_bufs), tuple(sbufs), tuple(frozen_bufs),
                      jnp.float32(0.0))
            (new_w, new_s, new_f, _), (losses, extras) = jax.lax.scan(
                body, carry0, (keys,) + tuple(in_raw))
            aux = tuple(new_f[frozen_pos[i]] for i in meta["aux_idx"])
            return list(new_w), list(new_s), losses, extras, aux

        # donate only the trainable weight + optimizer-state buffers; frozen
        # params keep their buffers live across calls. donate=False is the
        # other arm of the bench policy sweep (docs/PERF.md "Kernel tier").
        from ... import sanitize as _sanitize
        donate = (0, 1) if self._donate else ()
        return _sanitize.maybe_wrap_donated(
            jax.jit(step, donate_argnums=donate), donate,
            "fused.train_step")

    # ------------------------------------------------------------------
    def lowered(self, *inputs):
        """The fused step lowered for these input shapes WITHOUT running
        it: a `jax.stages.Lowered` whose `.compile()` yields the exact
        program `step(*inputs)` would execute. This is the inspection
        surface — `mx.inspect.inspect_step(step, x, y)` walks its
        compiled HLO for fusion-level offender attribution, and
        `flops_per_call` cost-counts it. The lowering lands in jax's jit
        cache, so a subsequent real `step(...)` with the same shapes does
        not re-pay compilation."""
        import jax
        from ...ndarray import NDArray
        from ...optimizer import _state_bufs

        self._ensure_states()
        if self._jit is None:
            self._jit = self._build()
        opt = self._opt
        lrs = _np.asarray([opt._get_lr(i) for i in self._train_idx],
                          _np.float32)
        wds = _np.asarray([opt._get_wd(i) for i in self._train_idx],
                          _np.float32)
        ts = (_np.asarray([1.0] * len(self._train_idx), _np.float32)
              if type(opt)._step_takes_t() else None)
        # fixed key: only shapes matter for lowering, and consuming the
        # global RNG stream here would silently change training
        # reproducibility for callers that cost-count before training
        key = jax.random.PRNGKey(0)
        train_bufs = [self._params[i].data()._arr for i in self._train_idx]
        frozen_bufs = [self._params[i].data()._arr
                       for i in self._frozen_idx]
        sbufs = [_state_bufs(s) for s in self._states]
        in_raw = tuple(
            _stage_raw(a._arr if isinstance(a, NDArray) else a)
            for a in inputs)
        return self._jit.lower(
            train_bufs, sbufs, frozen_bufs, key, lrs, wds,
            _np.float32(opt.rescale_grad), ts, *in_raw)

    def flops_per_call(self, *inputs):
        """XLA-counted FLOPs of ONE compiled step call (cost analysis of
        the lowered fwd+loss+bwd+update program, MAC=2 — the same
        convention as chip peak specs). With `steps_per_call=K` this is
        the K-step program's total; divide by K for per-step. This is the
        MFU numerator `telemetry.StepTimeline(flops_per_step=...)` wants —
        live-counter MFU instead of hand-math."""
        from ...telemetry import cost_flops
        return cost_flops(self.lowered(*inputs), what="the fused step")

    def __call__(self, *inputs):
        from ... import random as _random
        from ...ndarray import NDArray, _wrap
        from ...optimizer import _state_bufs, _state_restore

        self._ensure_states()
        if self._jit is None:
            self._jit = self._build()
        opt = self._opt
        for _ in range(self._K):
            for i in self._train_idx:
                opt._update_count(i)
        lrs = _np.asarray([opt._get_lr(i) for i in self._train_idx],
                          _np.float32)
        wds = _np.asarray([opt._get_wd(i) for i in self._train_idx],
                          _np.float32)
        # takes_t rules see t = count at that inner step: base + scan offset
        ts = (_np.asarray([opt._index_update_count[i] - self._K + 1
                           for i in self._train_idx], _np.float32)
              if type(opt)._step_takes_t() else None)
        key = _random.next_key()
        train_bufs = [self._params[i].data()._arr for i in self._train_idx]
        frozen_bufs = [self._params[i].data()._arr for i in self._frozen_idx]
        sbufs = [_state_bufs(s) for s in self._states]
        # stage inputs asynchronously: host arrays start their H2D transfer
        # now (overlapping this prologue), while batches that are already
        # committed device arrays with the right placement — e.g. from
        # io.DeviceFeed — skip the redundant transfer entirely (counted in
        # profiler.feed_stats()["device_put_skipped"]). Raw python scalars
        # pass through untouched to keep weak-typed promotion semantics.
        in_raw = tuple(
            _stage_raw(a._arr if isinstance(a, NDArray) else a)
            for a in inputs)

        new_w, new_s, loss, extras, aux_bufs = self._jit(
            train_bufs, sbufs, frozen_bufs, key, lrs, wds,
            _np.float32(opt.rescale_grad), ts, *in_raw)

        for k, i in enumerate(self._train_idx):
            self._params[i].data()._set_arr(new_w[k])
            _state_restore(self._states[k], new_s[k])
        for i, buf in zip(self._meta["aux_idx"], aux_bufs):
            self._params[i].data()._set_arr(buf)
        # census attribution (mx.inspect.memory): the donated update
        # produced FRESH weight/state buffers — re-attribute them so a
        # live-buffer census names the training state (a weakref-dict
        # write per buffer; must never break the step)
        try:
            from ...inspect import memory as _mem
            _mem.register((new_w, new_s, list(aux_bufs)),
                          owner="train_step")
        except Exception:
            pass
        out = (_wrap(loss),) + tuple(_wrap(e) for e in extras)
        return out if len(out) > 1 else out[0]
