"""Subgraph backend plug-in point (≙ the reference's subgraph property
framework, src/operator/subgraph/subgraph_property.h:88-211 + the
`optimize_for(backend)` partitioning API, block.py:1272).

TPU-native redesign: the reference partitions an nnvm graph and hands
subgraphs to a backend's C++ operators; here the graph IS the traced
jaxpr, so a backend is a REWRITER over jaxpr equations. When a block is
`optimize_for`'d with a registered backend, its hybridize cache re-traces
the forward and re-evaluates it equation by equation, letting the backend
substitute any primitive application with its own (traceable) computation
— the whole result still compiles into ONE XLA program, so a backend
rewrite composes with jit/grad like native code.

    class MyBackend(SubgraphBackend):
        def rewrite_eqn(self, eqn, invals):
            if eqn.primitive.name == "tanh":
                return [my_fast_tanh(invals[0])]
            return None                      # default lowering

    register_subgraph_backend("mine", MyBackend())
    net.optimize_for(x, backend="mine")
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["SubgraphBackend", "register_subgraph_backend",
           "list_subgraph_backends", "get_subgraph_backend",
           "rewrite_callable"]

_BACKENDS = {}


class SubgraphBackend:
    """Override one (or both) hooks.

    rewrite_eqn(eqn, invals) -> list-of-outputs | None
        Called per traced equation with concrete (traced) input values;
        return replacement outputs, or None to keep the default lowering.

    transform_callable(fn) -> fn
        Whole-function hook: wrap/replace the pure callable before jit
        (e.g. to pre/post-process or re-trace with custom logic)."""

    def rewrite_eqn(self, eqn, invals):
        return None

    def transform_callable(self, fn):
        return rewrite_callable(fn, self)


def register_subgraph_backend(name, backend):
    """≙ MXNET_REGISTER_SUBGRAPH_BACKEND."""
    if not isinstance(backend, SubgraphBackend):
        raise MXNetError("backend must be a SubgraphBackend")
    _BACKENDS[name] = backend
    return backend


def list_subgraph_backends():
    return sorted(_BACKENDS)


def get_subgraph_backend(name):
    b = _BACKENDS.get(name)
    if b is None:
        raise MXNetError(
            f"subgraph backend {name!r} is not registered "
            f"(registered: {list_subgraph_backends() or 'none'})")
    return b


def _eval_with_rewrites(closed, backend, *args):
    """Evaluate a closed jaxpr, offering every equation to the backend."""
    import jax.extend.core as jcore

    jaxpr, consts = closed.jaxpr, closed.consts
    env = {}

    def read(v):
        if isinstance(v, jcore.Literal):
            return v.val
        return env[v]

    for cv, c in zip(jaxpr.constvars, consts):
        env[cv] = c
    for iv, a in zip(jaxpr.invars, args):
        env[iv] = a
    for eqn in jaxpr.eqns:
        invals = [read(v) for v in eqn.invars]
        outs = backend.rewrite_eqn(eqn, invals)
        if outs is None:
            subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
            res = eqn.primitive.bind(*subfuns, *invals, **bind_params)
            outs = res if eqn.primitive.multiple_results else [res]
        elif not isinstance(outs, (list, tuple)):
            outs = [outs]
        if len(outs) != len(eqn.outvars):
            raise MXNetError(
                f"backend rewrite of {eqn.primitive.name!r} returned "
                f"{len(outs)} outputs, expected {len(eqn.outvars)}")
        for ov, o in zip(eqn.outvars, outs):
            env[ov] = o
    return [read(v) for v in jaxpr.outvars]


def rewrite_callable(fn, backend):
    """fn -> fn with the backend's equation rewrites applied. The wrapper
    traces `fn` (abstractly) per call and re-evaluates with substitutions
    — safe under an outer jit (everything stays traceable, and tracing
    inside the outer trace costs nothing at runtime)."""
    import jax

    def wrapped(*args):
        flat, treedef = jax.tree_util.tree_flatten(args)
        out_tree = {}

        def flat_fn(*xs):
            out = fn(*jax.tree_util.tree_unflatten(treedef, xs))
            out_flat, tree = jax.tree_util.tree_flatten(out)
            out_tree["tree"] = tree
            return out_flat

        closed = jax.make_jaxpr(flat_fn)(*flat)
        out_flat = _eval_with_rewrites(closed, backend, *flat)
        return jax.tree_util.tree_unflatten(out_tree["tree"], out_flat)

    return wrapped
