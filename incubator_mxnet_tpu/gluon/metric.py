"""gluon.metric — evaluation metrics.

Reference: python/mxnet/gluon/metric.py (1.9k LoC: EvalMetric base with
update/reset/get, CompositeEvalMetric, Accuracy, TopKAccuracy, F1, MCC,
Perplexity, MAE, MSE, RMSE, CrossEntropy, NegativeLogLikelihood, PearsonCorrelation,
PCC, Loss, TorchMetric...). Host-side numpy computation — metrics are
bookkeeping, not accelerator work.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError

__all__ = [
    "MeanAveragePrecision", "VOC07MApMetric",
    "EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy", "F1",
    "BinaryAccuracy", "MCC", "MAE", "MSE", "RMSE", "CrossEntropy",
    "Perplexity", "NegativeLogLikelihood", "PearsonCorrelation", "Loss",
    "create",
]

_REGISTRY = {}


_ALIASES = {
    "accuracy": ["acc"],
    "topkaccuracy": ["top_k_accuracy", "top_k_acc"],
    "crossentropy": ["ce", "cross-entropy"],
    "negativeloglikelihood": ["nll_loss", "nll-loss"],
    "pearsoncorrelation": ["pearsonr"],
    "meanaverageprecision": ["map", "voc07mapmetric"],
}


def register(klass):
    key = klass.__name__.lower()
    _REGISTRY[key] = klass
    for alias in _ALIASES.get(key, []):
        _REGISTRY[alias] = klass
    return klass


def create(metric, *args, **kwargs):
    """≙ mx.gluon.metric.create."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    name = str(metric).lower()
    if name not in _REGISTRY:
        raise MXNetError(f"unknown metric {metric!r}: {sorted(_REGISTRY)}")
    return _REGISTRY[name](*args, **kwargs)


def _to_numpy(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else _np.asarray(x)


class EvalMetric:
    """Base metric (≙ gluon/metric.py EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name, value = [name], [value]
        return list(zip(name, value))

    def update_dict(self, labels, preds):
        self.update(list(labels.values()), list(preds.values()))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values


def _as_lists(labels, preds):
    if not isinstance(labels, (list, tuple)):
        labels = [labels]
    if not isinstance(preds, (list, tuple)):
        preds = [preds]
    return labels, preds


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            pred = _to_numpy(pred)
            label = _to_numpy(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(_np.int64).ravel()
            label = label.astype(_np.int64).ravel()
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype(_np.int64).ravel()
            topk = _np.argsort(-pred, axis=-1)[..., :self.top_k]
            hits = (topk == label[:, None]).any(axis=-1)
            self.sum_metric += float(hits.sum())
            self.num_inst += len(label)


@register
class BinaryAccuracy(EvalMetric):
    def __init__(self, name="binary_accuracy", threshold=0.5, **kwargs):
        super().__init__(name, **kwargs)
        self.threshold = threshold

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            pred = (_to_numpy(pred).ravel() > self.threshold)
            label = _to_numpy(label).ravel() > 0.5
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


class _BinaryClassBase(EvalMetric):
    def reset(self):
        super().reset()
        self.tp = self.fp = self.tn = self.fn = 0.0

    def _accumulate(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            pred = _to_numpy(pred)
            label = _to_numpy(label).ravel().astype(_np.int64)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(axis=-1).ravel()
            else:
                pred = (pred.ravel() > 0.5).astype(_np.int64)
            self.tp += float(((pred == 1) & (label == 1)).sum())
            self.fp += float(((pred == 1) & (label == 0)).sum())
            self.tn += float(((pred == 0) & (label == 0)).sum())
            self.fn += float(((pred == 0) & (label == 1)).sum())
            self.num_inst += len(label)


@register
class F1(_BinaryClassBase):
    def __init__(self, name="f1", average="macro", **kwargs):
        self.average = average
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        self._accumulate(labels, preds)

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        precision = self.tp / max(self.tp + self.fp, 1e-12)
        recall = self.tp / max(self.tp + self.fn, 1e-12)
        f1 = 2 * precision * recall / max(precision + recall, 1e-12)
        return self.name, f1


@register
class MCC(_BinaryClassBase):
    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        self._accumulate(labels, preds)

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        num = self.tp * self.tn - self.fp * self.fn
        den = _np.sqrt((self.tp + self.fp) * (self.tp + self.fn)
                       * (self.tn + self.fp) * (self.tn + self.fn))
        return self.name, float(num / den) if den else 0.0


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_numpy(label)
            pred = _to_numpy(pred).reshape(label.shape)
            self.sum_metric += float(_np.abs(label - pred).mean()) * len(label)
            self.num_inst += len(label)


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_numpy(label)
            pred = _to_numpy(pred).reshape(label.shape)
            self.sum_metric += float(((label - pred) ** 2).mean()) * len(label)
            self.num_inst += len(label)


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def get(self):
        name, value = super().get()
        return name, float(_np.sqrt(value))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_numpy(label).ravel().astype(_np.int64)
            pred = _to_numpy(pred)
            prob = pred[_np.arange(label.shape[0]), label]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += len(label)


@register
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):
        super().__init__(name=name, **kwargs)
        self.ignore_label = ignore_label

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_numpy(label).ravel().astype(_np.int64)
            pred = _to_numpy(pred).reshape(-1, _to_numpy(pred).shape[-1])
            prob = pred[_np.arange(label.shape[0]), label]
            ce = -_np.log(prob + self.eps)
            if self.ignore_label is not None:
                keep = label != self.ignore_label
                ce = ce[keep]
                self.num_inst += int(keep.sum())
            else:
                self.num_inst += len(label)
            self.sum_metric += float(ce.sum())

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, float(_np.exp(self.sum_metric / self.num_inst))


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(eps=eps, name=name, **kwargs)


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def reset(self):
        super().reset()
        self._labels, self._preds = [], []

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            self._labels.append(_to_numpy(label).ravel())
            self._preds.append(_to_numpy(pred).ravel())
            self.num_inst += len(self._labels[-1])

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        x = _np.concatenate(self._labels)
        y = _np.concatenate(self._preds)
        return self.name, float(_np.corrcoef(x, y)[0, 1])


@register
class Loss(EvalMetric):
    """Running mean of a loss output (≙ gluon.metric.Loss)."""

    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
        for pred in preds:
            p = _to_numpy(pred)
            self.sum_metric += float(p.sum())
            self.num_inst += p.size


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False,
                 **kwargs):
        super().__init__(f"custom({name})", **kwargs)
        self._feval = feval

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            val = self._feval(_to_numpy(label), _to_numpy(pred))
            if isinstance(val, tuple):
                s, n = val
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += val
                self.num_inst += 1


np = _np  # reference module exposes numpy as mx.gluon.metric.numpy


@register
class MeanAveragePrecision(EvalMetric):
    """Detection mAP (≙ gluon-cv VOCMApMetric / the reference SSD eval):
    per-class average precision over an IoU threshold, averaged.

    update(labels, preds):
      labels: (B, M, 5) ground truth [cls, x1, y1, x2, y2] (cls -1 pads)
      preds:  (B, N, 6) detections  [cls, score, x1, y1, x2, y2]
              (cls -1 entries ignored — multibox_detection's pad rows)

    `get()` computes integral AP per class (precision envelope, the
    VOC2010+ convention) unless voc07=True (11-point interpolation).
    """

    def __init__(self, iou_thresh=0.5, class_names=None, voc07=False,
                 name="mAP", **kwargs):
        self._iou = float(iou_thresh)
        self._voc07 = bool(voc07)
        self._class_names = class_names
        super().__init__(name, **kwargs)

    def reset(self):
        super().reset()
        self._records = {}   # cls -> list of (score, is_tp)
        self._npos = {}      # cls -> #ground-truth boxes

    @staticmethod
    def _iou_matrix(a, b):
        # a: (n,4), b: (m,4) corner boxes
        lt = _np.maximum(a[:, None, :2], b[None, :, :2])
        rb = _np.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = _np.clip(rb - lt, 0, None)
        inter = wh[..., 0] * wh[..., 1]
        area_a = _np.clip(a[:, 2] - a[:, 0], 0, None) \
            * _np.clip(a[:, 3] - a[:, 1], 0, None)
        area_b = _np.clip(b[:, 2] - b[:, 0], 0, None) \
            * _np.clip(b[:, 3] - b[:, 1], 0, None)
        union = area_a[:, None] + area_b[None, :] - inter
        return inter / _np.maximum(union, 1e-12)

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for lab_x, det_x in zip(labels, preds):
            lab = _to_numpy(lab_x)
            det = _to_numpy(det_x)
            for b in range(lab.shape[0]):
                self._update_one(lab[b], det[b])
        self.num_inst = 1   # get() computes from the records

    def _update_one(self, lab, det):
        gts = lab[lab[:, 0] >= 0]
        dets = det[det[:, 0] >= 0]
        classes = set(gts[:, 0].astype(int)) | set(dets[:, 0].astype(int))
        for c in classes:
            g = gts[gts[:, 0].astype(int) == c][:, 1:5]
            d = dets[dets[:, 0].astype(int) == c]
            self._npos[c] = self._npos.get(c, 0) + len(g)
            rec = self._records.setdefault(c, [])
            if len(d) == 0:
                continue
            d = d[_np.argsort(-d[:, 1])]
            if len(g) == 0:
                rec.extend((float(s), False) for s in d[:, 1])
                continue
            ious = self._iou_matrix(d[:, 2:6], g)   # one (N, M) matrix
            matched = _np.zeros(len(g), bool)
            for i in range(len(d)):
                j = int(_np.argmax(ious[i]))
                if ious[i, j] >= self._iou and not matched[j]:
                    matched[j] = True
                    rec.append((float(d[i, 1]), True))
                else:
                    rec.append((float(d[i, 1]), False))

    def _class_ap(self, c):
        rec = self._records.get(c, [])
        npos = self._npos.get(c, 0)
        if npos == 0:
            return None
        if not rec:
            return 0.0
        arr = _np.array(sorted(rec, key=lambda r: -r[0]), dtype=_np.float64)
        tp = _np.cumsum(arr[:, 1])
        fp = _np.cumsum(1.0 - arr[:, 1])
        recall = tp / npos
        precision = tp / _np.maximum(tp + fp, 1e-12)
        if self._voc07:
            ap = 0.0
            for t in _np.linspace(0, 1, 11):
                p = precision[recall >= t]
                ap += (p.max() if len(p) else 0.0) / 11.0
            return float(ap)
        # integral AP with the precision envelope
        mrec = _np.concatenate([[0.0], recall, [1.0]])
        mpre = _np.concatenate([[0.0], precision, [0.0]])
        for i in range(len(mpre) - 2, -1, -1):
            mpre[i] = max(mpre[i], mpre[i + 1])
        idx = _np.where(mrec[1:] != mrec[:-1])[0]
        return float(_np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))

    def get(self):
        aps = [ap for ap in (self._class_ap(c) for c in
                             sorted(self._npos)) if ap is not None]
        if not aps:
            return self.name, float("nan")
        return self.name, float(_np.mean(aps))

    def get_class_aps(self):
        """Per-class APs, keyed by class id (or class_names entry)."""
        out = {}
        for c in sorted(self._npos):
            ap = self._class_ap(c)
            if ap is None:
                continue
            key = (self._class_names[c]
                   if self._class_names and c < len(self._class_names)
                   else c)
            out[key] = ap
        return out


@register
class VOC07MApMetric(MeanAveragePrecision):
    """The 11-point interpolated VOC-2007 convention (≙ gluon-cv
    VOC07MApMetric): same accumulation, voc07 AP by default."""

    def __init__(self, iou_thresh=0.5, class_names=None, name="mAP_voc07",
                 **kwargs):
        kwargs.pop("voc07", None)
        super().__init__(iou_thresh=iou_thresh, class_names=class_names,
                         voc07=True, name=name, **kwargs)
