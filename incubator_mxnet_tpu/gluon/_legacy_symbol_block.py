"""LegacySymbolBlock — run a parsed reference `*-symbol.json` graph as a
HybridBlock (≙ gluon.SymbolBlock over an nnvm symbol, block.py:1638 +
symbol loading in legacy_json_util.cc:226).

The symbol executor (`Symbol.bind_fn`) is a pure jax function, so the block
hybridizes/jits like any native net; parameters come from a reference
`.params` checkpoint (arg:/aux: prefixes) or are freshly initialized from
`infer_shape`.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError


def build_legacy_block(symbol_file, input_names=None, param_file=None):
    from .. import symbol as sym_mod
    from ..ndarray import NDArray, _wrap, array
    from .block import HybridBlock
    from .parameter import Parameter

    s = sym_mod.load(symbol_file)
    input_names = list(input_names or ["data"])
    args = s.list_arguments()
    aux = s.list_auxiliary_states()
    param_names = [a for a in args if a not in input_names]
    label_like = {n for n in param_names
                  if n.endswith("_label") or n.endswith("label")}
    param_names = [n for n in param_names if n not in label_like]

    loaded = {}
    if param_file:
        from .model_zoo.model_store import load_params_file
        loaded = load_params_file(param_file)

    class LegacySymbolBlock(HybridBlock):
        def __init__(self):
            super().__init__()
            self._symbol = s
            self._run = s.bind_fn()
            self._input_names = input_names
            self._n_outputs = s.num_outputs
            for nm in param_names + aux:
                grad_req = "write" if nm in param_names else "null"
                p = Parameter(name=nm, grad_req=grad_req,
                              allow_deferred_init=True)
                self._reg_params[nm] = p

        def infer_and_initialize(self, **input_shapes):
            """Resolve every parameter shape from the graph and initialize
            (loaded checkpoint values win; the rest Xavier-ish random)."""
            arg_shapes, _, aux_shapes = self._symbol.infer_shape(
                **input_shapes)
            shp = dict(zip(args, arg_shapes))
            shp.update(dict(zip(aux, aux_shapes)))
            rng = _np.random.RandomState(0)
            for nm, p in self._reg_params.items():
                if nm in loaded:
                    val = loaded[nm]
                elif shp.get(nm) is not None:
                    shape = shp[nm]
                    fan = max(int(_np.prod(shape[1:])), 1)
                    scale = float(_np.sqrt(2.0 / fan))
                    if nm in aux and ("var" in nm):
                        val = _np.ones(shape, _np.float32)
                    elif nm in aux or nm.endswith("_bias") \
                            or nm.endswith("_beta"):
                        val = _np.zeros(shape, _np.float32)
                    elif nm.endswith("_gamma"):
                        val = _np.ones(shape, _np.float32)
                    else:
                        val = (rng.randn(*shape) * scale).astype(_np.float32)
                else:
                    raise MXNetError(
                        f"cannot infer shape for parameter {nm!r}; pass "
                        "input shapes covering it")
                p.set_data(array(val))
            return self

        def forward(self, *inputs):
            vals = {}
            for nm, x in zip(self._input_names, inputs):
                vals[nm] = x._arr if isinstance(x, NDArray) else x
            for nm, p in self._reg_params.items():
                vals[nm] = p.data()._arr
            outs = [_wrap(o) for o in self._run(vals)]
            return outs[0] if self._n_outputs == 1 else tuple(outs)

    block = LegacySymbolBlock()
    if loaded:
        # shapes are known from the checkpoint: initialize immediately,
        # missing entries resolved on the first infer_and_initialize
        ready = all(nm in loaded for nm in list(param_names) + list(aux))
        if ready:
            for nm, p in block._reg_params.items():
                p.set_data(array(loaded[nm]))
    return block
