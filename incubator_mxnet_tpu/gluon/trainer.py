"""gluon.Trainer — bridges autograd grads ↔ KVStore ↔ Optimizer.

Reference: python/mxnet/gluon/trainer.py:31 (`_init_kvstore`:188 decides
update_on_kvstore, `step`:334, `allreduce_grads`:363, `update`:411,
save/load_states:468-530). Grad aggregation priority ordering (engine
priority = -param_index overlapping comm with backprop) is unnecessary on
TPU: XLA schedules the update computation asynchronously after the grads
materialize, and in SPMD mode the psum is fused into the step.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import optimizer as opt_mod
from ..kvstore import KVStore, create as kv_create
from .parameter import Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore=None,
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict,)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError(
                "params must be the dict from net.collect_params() or a list")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p!r}")
            self._params.append(p)
            self._param2idx[id(p)] = i
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = optimizer_params.get("rescale_grad", 1.0)
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_arg = kvstore
        self._update_on_kvstore_arg = update_on_kvstore
        self._kvstore = None
        self._update_on_kvstore = None
        self._kv_initialized = False
        self._states = [None] * len(self._params)
        self._states_created = [False] * len(self._params)

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params and set(optimizer_params) != {"rescale_grad"}:
                raise MXNetError(
                    "optimizer_params must be None when optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
        else:
            self._optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._optimizer.param_dict = param_dict

    # ------------------------------------------------------------------
    def _init_kvstore(self):
        """≙ trainer.py:188 — decide kvstore & update placement."""
        arg = self._kvstore_arg
        if arg is None or arg is False:
            if self._compression_params:
                raise MXNetError(
                    "compression_params requires a kvstore (e.g. "
                    "kvstore='device'); without one gradients would silently "
                    "flow uncompressed")
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            kv = kv_create(arg) if isinstance(arg, str) else arg
            self._kvstore = kv
            if self._compression_params:
                # unconditional (≙ reference trainer.py:266): a store without
                # compression support must fail loudly, not silently train
                # uncompressed
                kv.set_gradient_compression(self._compression_params)
            u = self._update_on_kvstore_arg
            if u is None:
                u = kv.type.startswith("dist") if hasattr(kv, "type") else False
            self._update_on_kvstore = u
            if u:
                self._kvstore.set_updater(opt_mod.get_updater(self._optimizer))
            for i, p in enumerate(self._params):
                if p._data is not None:
                    self._kvstore.init(i, p.data())
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # ------------------------------------------------------------------
    def step(self, batch_size, ignore_stale_grad=False):
        """grad-normalize by batch_size, allreduce, update (≙ step:334)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        if self._kvstore is not None:
            self._allreduce_grads()
            if self._update_on_kvstore:
                for i, p in enumerate(self._params):
                    if p.grad_req != "null":
                        self._kvstore.pull(i, p.data())
                self._mark_consumed()
                return
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is None:
            return
        self._allreduce_grads()

    def _allreduce_grads(self):
        for i, p in enumerate(self._params):
            if p.grad_req != "null":
                if self._update_on_kvstore:
                    self._kvstore.push(i, p.list_grad())
                else:
                    g = p.grad()
                    self._kvstore.pushpull(i, p.list_grad(), out=g)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "update() is not supported when update_on_kvstore; use step()")
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        items = []
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or p._data is None:
                continue
            var = p._data._var
            if var is not None and not var.fresh:
                if ignore_stale_grad:
                    # a skipped sparse param must not carry its token
                    # batches into a later step (stale-row update + leak)
                    if getattr(p, "_last_tokens", None) is not None:
                        p._last_tokens = None
                    continue  # skip params whose grad was not refreshed
                raise MXNetError(
                    f"gradient of parameter {p.name} has not been updated by "
                    "backward since the last step; set ignore_stale_grad=True "
                    "to skip such parameters (≙ trainer.py stale-grad check)")
            if not self._states_created[i]:
                self._states[i] = \
                    self._optimizer.create_state_multi_precision(i, p.data())
                self._states_created[i] = True
            if getattr(p, "_sparse_grad", False) \
                    and getattr(p, "_last_tokens", None) is not None:
                if self._kvstore is not None and getattr(
                        self._kvstore, "type", "").startswith("dist"):
                    # dist: the allreduced dense grad carries rows touched
                    # ONLY on other workers; updating just the locally
                    # touched rows would drop those contributions and let
                    # replicas diverge — fall through to the dense update
                    p._last_tokens = None
                else:
                    # row-sparse path (≙ trainer.py:325 row-sparse pull +
                    # lazy_update): only rows touched since the last step
                    self._row_sparse_update(i, p, self._states[i])
                    if p.data()._var is not None:
                        p.data()._var.fresh = False
                    continue
            items.append((i, p.data(), p.grad(), self._states[i]))
        # one fused XLA computation for all params when the rule supports
        # it (≙ multi_sgd_update etc.). Under engine op-bulking the update
        # joins the deferred segment, so the WHOLE iteration (fwd+bwd+
        # update) is one self-feeding donated program; otherwise a
        # standalone jitted multi-tensor update; else per-param kernels.
        if not self._optimizer.fused_update_all_bulked(items):
            if not self._optimizer.fused_update_all(items):
                for i, w, g, s in items:
                    self._optimizer.update_multi_precision(i, w, g, s)
        # only mark grads consumed once the updates have been issued
        for i, w, g, s in items:
            if w._var is not None:
                w._var.fresh = False

    def _row_sparse_update(self, i, p, state):
        """Touched-rows optimizer update for sparse_grad parameters.

        TPU-native ≙ the reference's row_sparse gradient + row-sparse
        kvstore pull (trainer.py:325) with lazy_update semantics: gather
        the unique touched rows of weight/grad/state, run the optimizer's
        own step_one on the row block, scatter back. Cost scales with
        rows touched, not the vocabulary."""
        import numpy as _onp

        from ..ndarray import _wrap

        token_batches = p._last_tokens
        p._last_tokens = None
        idx = _onp.unique(_onp.concatenate(
            [_onp.asarray(t).ravel() for t in token_batches])
        ).astype(_onp.int32)
        w_nd, g_nd = p.data(), p.grad()
        w, g = w_nd._arr, g_nd._arr

        def gather_state(s):
            if s is None:
                return None
            if isinstance(s, tuple):
                return tuple(gather_state(x) for x in s)
            return _wrap(s._arr[idx])

        def scatter_state(s, rows):
            if s is None:
                return
            if isinstance(s, tuple):
                for sub, r in zip(s, rows):
                    scatter_state(sub, r)
                return
            s._set_arr(s._arr.at[idx].set(rows._arr))

        w_rows = _wrap(w[idx])
        g_rows = _wrap(g[idx])
        s_rows = gather_state(state)
        # the optimizer's own rule on the row block (lr/wd resolved as
        # usual, multi-precision state handled); mutates the row copies
        self._optimizer.update_multi_precision(i, w_rows, g_rows, s_rows)
        w_nd._set_arr(w.at[idx].set(w_rows._arr))
        scatter_state(state, s_rows)

    def _mark_consumed(self):
        for p in self._params:
            if p._data is not None and p._data._var is not None:
                p._data._var.fresh = False
            # update happened on the kvstore: the sparse-row path never
            # runs here, so token batches must be dropped or they pile up
            # without bound (and would stale-update rows much later)
            if getattr(p, "_last_tokens", None) is not None:
                p._last_tokens = None

    # ------------------------------------------------------------------
    def save_states(self, fname):
        """≙ trainer.py:468. Crash-consistent: temp write + atomic rename,
        so a crash mid-save never corrupts the previous state file."""
        import pickle
        from .. import fault as _fault
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
            return
        payload = {
            "num_update": self._optimizer.num_update,
            "index_count": self._optimizer._index_update_count,
            "states": {i: opt_mod._state_to_numpy(s)
                       for i, s in enumerate(self._states)
                       if self._states_created[i]},
        }
        with _fault.atomic_output(fname) as f:
            pickle.dump(payload, f)

    def load_states(self, fname):
        """≙ trainer.py:500."""
        import pickle
        with open(fname, "rb") as f:
            payload = pickle.load(f)
        self._optimizer.num_update = payload["num_update"]
        self._optimizer._index_update_count = payload["index_count"]
        for i, s in payload["states"].items():
            self._states[int(i)] = opt_mod._state_from_numpy(s)
            self._states_created[int(i)] = True
