"""gluon.utils (≙ python/mxnet/gluon/utils.py): split_and_load,
clip_global_norm, shape checking helpers."""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError

__all__ = ["split_data", "split_and_load", "clip_global_norm",
           "check_sha1", "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """≙ gluon.utils.split_data."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}")
    step = size // num_slice
    if batch_axis == 0:
        return [data[i * step:(i + 1) * step] for i in range(num_slice)]
    from .. import numpy as mxnp
    return mxnp.split(data, num_slice, axis=batch_axis)


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """≙ gluon.utils.split_and_load. With one device (the common TPU-SPMD
    case — sharding replaces device lists) returns [data]."""
    from ..ndarray import _as_nd
    data = _as_nd(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """≙ gluon.utils.clip_global_norm."""
    from .. import numpy_extension as npx
    if not arrays:
        raise MXNetError("arrays must not be empty")
    return npx.clip_by_global_norm(arrays, max_norm)


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """≙ gluon.utils.download. Zero-egress environments raise at call time."""
    raise MXNetError(
        "download() requires network egress, which this environment does not "
        "provide; place files locally and load them directly")
