"""Transformer layers for gluon (MultiHeadAttention, encoder/decoder cells).

Reference: the fused attention kernels GluonNLP's BERT rides on —
`_contrib_interleaved_matmul_selfatt_qk/valatt` (src/operator/contrib/
transformer.cc:676-869) and sliding-window attention (:888-1096). TPU-native:
one `scaled_dot_product_attention` composition that XLA fuses onto the MXU;
long sequences swap in the Pallas flash kernel (use_flash=True) and
sequence-parallel meshes use parallel.ring_attention.
"""
from __future__ import annotations

import math

import numpy as _np

from ...base import MXNetError
from ... import numpy as mxnp
from ... import numpy_extension as npx
from ..block import HybridBlock
from ..parameter import Parameter
from . import Dense, Dropout, LayerNorm

__all__ = ["MultiHeadAttention", "TransformerEncoderCell",
           "TransformerDecoderCell", "PositionalEmbedding"]


class MultiHeadAttention(HybridBlock):
    """Multi-head attention (≙ the interleaved_matmul_selfatt op pair).

    Inputs (batch, seq, units); separate q/k/v projections + output proj.
    """

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 use_flash=False):
        super().__init__()
        if units % num_heads:
            raise MXNetError(f"units {units} not divisible by heads "
                             f"{num_heads}")
        self._units = units
        self._heads = num_heads
        self._use_flash = use_flash
        self.query_proj = Dense(units, use_bias=use_bias, flatten=False,
                                in_units=units)
        self.key_proj = Dense(units, use_bias=use_bias, flatten=False,
                              in_units=units)
        self.value_proj = Dense(units, use_bias=use_bias, flatten=False,
                                in_units=units)
        self.out_proj = Dense(units, use_bias=use_bias, flatten=False,
                              in_units=units)
        self.dropout = Dropout(dropout) if dropout > 0 else None

    def _split(self, x):
        b, t, _ = x.shape
        return x.reshape((b, t, self._heads, -1)).transpose((0, 2, 1, 3))

    def forward(self, query, key=None, value=None, mask=None, causal=False):
        key = query if key is None else key
        value = key if value is None else value
        q = self._split(self.query_proj(query))
        k = self._split(self.key_proj(key))
        v = self._split(self.value_proj(value))
        if self._use_flash and mask is None:
            from ...ops.pallas_attention import flash_attention
            from ...ops.registry import invoke
            b, h, t, d = q.shape
            causal_ = causal

            def f(qr, kr, vr):
                o = flash_attention(qr.reshape(b * h, t, d),
                                    kr.reshape(b * h, -1, d),
                                    vr.reshape(b * h, -1, d), causal=causal_)
                return o.reshape(b, h, t, d)
            out = invoke(f, (q, k, v), name="flash_attention")
        else:
            out = npx.scaled_dot_product_attention(q, k, v, mask=mask,
                                                   causal=causal)
        b, h, t, d = out.shape
        out = out.transpose((0, 2, 1, 3)).reshape((b, t, self._units))
        out = self.out_proj(out)
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class TransformerEncoderCell(HybridBlock):
    """Pre-norm transformer encoder layer (attention + FFN)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.1,
                 activation="gelu", use_flash=False):
        super().__init__()
        self.attention = MultiHeadAttention(units, num_heads, dropout,
                                            use_flash=use_flash)
        self.ln1 = LayerNorm(in_channels=units)
        self.ln2 = LayerNorm(in_channels=units)
        if activation not in ("relu", "gelu"):
            raise MXNetError(f"unsupported activation {activation!r} "
                             "(relu|gelu)")
        self.ffn1 = Dense(hidden_size, flatten=False, in_units=units)
        self.ffn2 = Dense(units, flatten=False, in_units=hidden_size)
        self._act = activation
        self.dropout = Dropout(dropout) if dropout > 0 else None

    def forward(self, x, mask=None):
        h = self.ln1(x)
        x = x + self.attention(h, mask=mask)
        h = self.ln2(x)
        h = npx.activation(self.ffn1(h), act_type="relu") \
            if self._act == "relu" else npx.gelu(self.ffn1(h))
        h = self.ffn2(h)
        if self.dropout is not None:
            h = self.dropout(h)
        return x + h


class TransformerDecoderCell(HybridBlock):
    """Pre-norm decoder layer: causal self-attn + cross-attn + FFN."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.1,
                 use_flash=False):
        super().__init__()
        self.self_attention = MultiHeadAttention(units, num_heads, dropout,
                                                 use_flash=use_flash)
        self.cross_attention = MultiHeadAttention(units, num_heads, dropout)
        self.ln1 = LayerNorm(in_channels=units)
        self.ln2 = LayerNorm(in_channels=units)
        self.ln3 = LayerNorm(in_channels=units)
        self.ffn1 = Dense(hidden_size, flatten=False, in_units=units)
        self.ffn2 = Dense(units, flatten=False, in_units=hidden_size)
        self.dropout = Dropout(dropout) if dropout > 0 else None

    def forward(self, x, memory, mem_mask=None, self_mask=None):
        # self_mask excludes padded target positions (combined with causal)
        x = x + self.self_attention(self.ln1(x), mask=self_mask, causal=True)
        x = x + self.cross_attention(self.ln2(x), memory, memory,
                                     mask=mem_mask)
        h = npx.gelu(self.ffn1(self.ln3(x)))
        h = self.ffn2(h)
        if self.dropout is not None:
            h = self.dropout(h)
        return x + h


class PositionalEmbedding(HybridBlock):
    """Learned positional embedding (BERT-style)."""

    def __init__(self, max_length, units):
        super().__init__()
        self._max_length = max_length
        self.weight = Parameter(shape=(max_length, units), init="normal",
                                name="weight")

    def forward(self, x):
        t = x.shape[1]
        if t > self._max_length:
            raise MXNetError(f"sequence length {t} exceeds max_length "
                             f"{self._max_length}")
        return x + self.weight.data()[:t].reshape((1, t, -1))
