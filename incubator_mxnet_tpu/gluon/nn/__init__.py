"""gluon.nn — neural network layers.

Reference: python/mxnet/gluon/nn/{basic_layers,conv_layers,activations}.py
(catalog in SURVEY.md Appendix B). Each layer is a HybridBlock whose forward
is written against mx.npx functional ops, so it runs eagerly op-by-op or
compiles to one XLA computation under hybridize().

Layout note: layers default to the reference's NCHW/`channels-first`
convention for API parity; `layout='NHWC'` is the TPU-preferred fast path
(XLA convs tile NHWC onto the MXU without transposes).
"""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError, name_to_dtype
from ... import numpy_extension as npx
from ... import numpy as mxnp
from ...ops import fused as _fused
from ..block import Block, HybridBlock
from ..parameter import Parameter

# activation strings the fused-tier GATE may engage on: the intersection
# of the kernel contract (ops/fused.py FUSABLE_ACTS) and what
# npx.activation serves — the tier must be a pure optimization, so a
# block built with one of these must also run with fusion OFF
# (MXNET_USE_FUSION=0 A/B). silu/gelu are fusable by the kernels but
# have no unfused npx.activation, so the gate skips them.
_NPX_ACTS = frozenset(("relu", "sigmoid", "tanh", "softrelu", "softsign",
                       "log_sigmoid", "mish"))
_FUSABLE_ACTS = frozenset(a for a in _fused.FUSABLE_ACTS if a) & _NPX_ACTS


def _fusion_on():
    """Route this forward through the fused kernel tier? True inside a
    `fused.fusion_scope(True)` (FusedTrainStep/FusedInferStep enter one
    automatically) or after `fused.set_fusion_default(True)`, unless
    MXNET_USE_FUSION kills the tier. See docs/PERF.md 'Kernel tier'."""
    return _fused.fusion_enabled()

__all__ = [
    "Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
    "BatchNormReLU", "SyncBatchNorm", "Embedding", "Flatten", "InstanceNorm",
    "LayerNorm", "GroupNorm", "RMSNorm", "Lambda", "HybridLambda",
    "Concatenate", "HybridConcatenate", "Identity",
    "Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "Swish", "SiLU", "GELU",
    "Conv1D", "Conv2D", "Conv3D",
    "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose",
    "MaxPool1D", "MaxPool2D", "MaxPool3D",
    "AvgPool1D", "AvgPool2D", "AvgPool3D",
    "GlobalMaxPool1D", "GlobalMaxPool2D", "GlobalMaxPool3D",
    "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D",
    "ReflectionPad2D",
]


# ---------------------------------------------------------------------------
# containers (≙ basic_layers.py Sequential:36 / HybridSequential:104)
# ---------------------------------------------------------------------------
class Sequential(Block):
    """Stack of blocks executed sequentially."""

    def __init__(self, *blocks):
        super().__init__()
        for b in blocks:
            self.add(b)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        children = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*children[key])
            return net
        return children[key]

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(Sequential, HybridBlock):
    """Hybridizable Sequential (≙ basic_layers.py:104)."""

    def __init__(self, *blocks):
        HybridBlock.__init__(self)
        for b in blocks:
            self.add(b)


# ---------------------------------------------------------------------------
# Dense (≙ basic_layers.py Dense:156; kernel: fully_connected.cc:252)
# ---------------------------------------------------------------------------
class Dense(HybridBlock):
    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0):
        super().__init__()
        self._units = units
        self._flatten = flatten
        self._act_type = activation
        self.weight = Parameter(shape=(units, in_units), dtype=dtype,
                                init=weight_initializer,
                                allow_deferred_init=True, name="weight")
        self.bias = (Parameter(shape=(units,), dtype=dtype,
                               init=bias_initializer,
                               allow_deferred_init=True, name="bias")
                     if use_bias else None)

    def infer_shape(self, x, *args):
        in_units = int(_np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)
        if self.bias is not None:
            self.bias.shape = (self._units,)

    def forward(self, x):
        if (self._act_type in _FUSABLE_ACTS and self.bias is not None
                and _fusion_on()):
            # kernel tier: bias + activation fold into one fused pass
            y = npx.fully_connected(x, self.weight.data(), None,
                                    no_bias=True, flatten=self._flatten)
            return npx.fused_bias_act(y, self.bias.data(),
                                      act_type=self._act_type, axis=-1)
        y = npx.fully_connected(
            x, self.weight.data(),
            None if self.bias is None else self.bias.data(),
            no_bias=self.bias is None, flatten=self._flatten)
        if self._act_type:
            y = npx.activation(y, act_type=self._act_type)
        return y

    def __repr__(self):
        shape = self.weight.shape
        return (f"Dense({shape[1] if shape and len(shape) > 1 else None} -> "
                f"{self._units}, "
                f"{self._act_type if self._act_type else 'linear'})")


# ---------------------------------------------------------------------------
# Dropout (≙ basic_layers.py Dropout:253; src/operator/nn/dropout*)
# ---------------------------------------------------------------------------
class Dropout(HybridBlock):
    def __init__(self, rate, axes=()):
        super().__init__()
        self._rate = rate
        self._axes = axes

    def forward(self, x):
        return npx.dropout(x, p=self._rate, axes=self._axes or None)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


# ---------------------------------------------------------------------------
# Norm layers (≙ basic_layers.py BatchNorm:414, LayerNorm:717, GroupNorm:808,
# InstanceNorm:616; kernels src/operator/nn/{batch_norm,layer_norm,group_norm}*)
# ---------------------------------------------------------------------------
class BatchNorm(HybridBlock):
    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__()
        self._axis = axis
        self._momentum = momentum
        self._eps = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        ch = in_channels if in_channels > 0 else 0
        self.gamma = Parameter(shape=(ch,), init=gamma_initializer,
                               grad_req="write" if scale else "null",
                               allow_deferred_init=True, name="gamma")
        self.beta = Parameter(shape=(ch,), init=beta_initializer,
                              grad_req="write" if center else "null",
                              allow_deferred_init=True, name="beta")
        self.running_mean = Parameter(shape=(ch,),
                                      init=running_mean_initializer,
                                      grad_req="null",
                                      allow_deferred_init=True,
                                      name="running_mean")
        self.running_var = Parameter(shape=(ch,),
                                     init=running_variance_initializer,
                                     grad_req="null",
                                     allow_deferred_init=True,
                                     name="running_var")

    def infer_shape(self, x, *args):
        ch = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (ch,)

    def fused_forward(self, x, act_type=None, residual=None):
        """BN + optional activation + optional pre-activation residual
        add as ONE fused-tier op (npx.fused_batch_norm): the apply stage
        runs as a single Pallas pass on TPU instead of the memory-bound
        fusion chain. Numerics match forward() (+ activation, + add)
        within float association; running stats update identically."""
        return npx.fused_batch_norm(
            x, self.gamma.data(), self.beta.data(),
            self.running_mean.data(), self.running_var.data(),
            eps=self._eps, momentum=self._momentum, axis=self._axis,
            use_global_stats=self._use_global_stats, act_type=act_type,
            residual=residual)

    def forward(self, x):
        if _fusion_on():
            return self.fused_forward(x)
        return npx.batch_norm(
            x, self.gamma.data(), self.beta.data(),
            self.running_mean.data(), self.running_var.data(),
            eps=self._eps, momentum=self._momentum, axis=self._axis,
            use_global_stats=self._use_global_stats)

    def __repr__(self):
        return (f"BatchNorm(axis={self._axis}, momentum={self._momentum}, "
                f"eps={self._eps})")


class BatchNormReLU(BatchNorm):
    """Fused BN+ReLU (≙ basic_layers.py:478). On the kernel tier
    (`_fusion_on()`) the whole normalize+scale/shift+relu chain is one
    fused pass; otherwise BN + relu as before (XLA fuses pointwise)."""

    def forward(self, x):
        if _fusion_on():
            return self.fused_forward(x, act_type="relu")
        return npx.relu(BatchNorm.forward(self, x))


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm (≙ basic_layers.py SyncBatchNorm:1087,
    kernel src/operator/contrib/sync_batch_norm-inl.h:78-173).

    TPU-native: inside a pjit/shard_map over a mesh, batch stats reduce with
    `lax.pmean` over the data-parallel axis (`sync_axis_name`) instead of the
    reference's per-process SharedND barrier."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones",
                 sync_axis_name="dp", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels)
        self._sync_axis_name = sync_axis_name

    def forward(self, x):
        from ... import parallel
        axis_name = (self._sync_axis_name
                     if parallel.axis_is_bound(self._sync_axis_name) else None)
        return npx.batch_norm(
            x, self.gamma.data(), self.beta.data(),
            self.running_mean.data(), self.running_var.data(),
            eps=self._eps, momentum=self._momentum, axis=self._axis,
            use_global_stats=self._use_global_stats,
            sync_axis_name=axis_name)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0):
        super().__init__()
        self._axis = axis
        self._eps = epsilon
        ch = in_channels if in_channels > 0 else 0
        self.gamma = Parameter(shape=(ch,), init=gamma_initializer,
                               grad_req="write" if scale else "null",
                               allow_deferred_init=True, name="gamma")
        self.beta = Parameter(shape=(ch,), init=beta_initializer,
                              grad_req="write" if center else "null",
                              allow_deferred_init=True, name="beta")

    def infer_shape(self, x, *args):
        ch = x.shape[self._axis]
        self.gamma.shape = (ch,)
        self.beta.shape = (ch,)

    def forward(self, x):
        return npx.layer_norm(x, self.gamma.data(), self.beta.data(),
                              axis=self._axis, eps=self._eps)

    def __repr__(self):
        return f"LayerNorm(axis={self._axis}, eps={self._eps})"


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0):
        super().__init__()
        self._num_groups = num_groups
        self._eps = epsilon
        ch = in_channels if in_channels > 0 else 0
        self.gamma = Parameter(shape=(ch,), init=gamma_initializer,
                               grad_req="write" if scale else "null",
                               allow_deferred_init=True, name="gamma")
        self.beta = Parameter(shape=(ch,), init=beta_initializer,
                              grad_req="write" if center else "null",
                              allow_deferred_init=True, name="beta")

    def infer_shape(self, x, *args):
        ch = x.shape[1]
        self.gamma.shape = (ch,)
        self.beta.shape = (ch,)

    def forward(self, x):
        return npx.group_norm(x, self.gamma.data(), self.beta.data(),
                              num_groups=self._num_groups, eps=self._eps)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0):
        super().__init__()
        self._axis = axis
        self._eps = epsilon
        ch = in_channels if in_channels > 0 else 0
        self.gamma = Parameter(shape=(ch,), init=gamma_initializer,
                               grad_req="write" if scale else "null",
                               allow_deferred_init=True, name="gamma")
        self.beta = Parameter(shape=(ch,), init=beta_initializer,
                              grad_req="write" if center else "null",
                              allow_deferred_init=True, name="beta")

    def infer_shape(self, x, *args):
        ch = x.shape[self._axis]
        self.gamma.shape = (ch,)
        self.beta.shape = (ch,)

    def forward(self, x):
        return npx.instance_norm(x, self.gamma.data(), self.beta.data(),
                                 eps=self._eps)


class RMSNorm(HybridBlock):
    """RMS normalization — modern-transformer extension beyond the reference
    (used by the flagship transformer; no MXNet equivalent)."""

    def __init__(self, in_channels=0, epsilon=1e-6, gamma_initializer="ones"):
        super().__init__()
        self._eps = epsilon
        ch = in_channels if in_channels > 0 else 0
        self.gamma = Parameter(shape=(ch,), init=gamma_initializer,
                               allow_deferred_init=True, name="gamma")

    def infer_shape(self, x, *args):
        self.gamma.shape = (x.shape[-1],)

    def forward(self, x):
        return npx.rms_norm(x, self.gamma.data(), eps=self._eps)


# ---------------------------------------------------------------------------
# Embedding / Flatten / glue (≙ basic_layers.py Embedding:543, Flatten:596,
# Lambda:904, Concatenate:1002, Identity:1066)
# ---------------------------------------------------------------------------
class Embedding(HybridBlock):
    """≙ gluon.nn.Embedding. `sparse_grad=True` enables the TPU-native
    counterpart of the reference's row_sparse gradient path
    (python/mxnet/gluon/trainer.py:325 row-sparse pulls): the backward is
    XLA's scatter-add (cost scales with tokens touched), and gluon.Trainer
    applies a TOUCHED-ROWS optimizer update — only rows referenced since
    the last step are updated (the reference's lazy_update semantics:
    untouched rows receive no decay/momentum aging). Promoted from
    tests/nightly/test_large_vocab_embedding.py viability evidence."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False):
        super().__init__()
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = bool(sparse_grad)
        self.weight = Parameter(shape=(input_dim, output_dim), dtype=dtype,
                                init=weight_initializer, name="weight")
        if self._sparse_grad:
            self.weight._sparse_grad = True

    def forward(self, x):
        if self._sparse_grad:
            import jax
            from ... import autograd as _ag
            raw = x._arr if hasattr(x, "_arr") else x
            if isinstance(raw, jax.core.Tracer):
                # symbolic indices (hybridize/jit): the trainer falls back
                # to the dense update
                self.weight._last_tokens = None
            elif _ag.is_recording():
                # ACCUMULATE recorded batches (grad_req='add' / multiple
                # calls per iteration touch the union of their rows);
                # inference forwards between backward and step must not
                # disturb the recorded set
                prev = getattr(self.weight, "_last_tokens", None)
                self.weight._last_tokens = (list(prev) if prev else []) \
                    + [raw]
        return npx.embedding(x, self.weight.data())

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    def forward(self, x):
        return x.reshape((x.shape[0], -1))

    def __repr__(self):
        return "Flatten"


class Identity(HybridBlock):
    def forward(self, x):
        return x


class Lambda(Block):
    def __init__(self, function):
        super().__init__()
        if isinstance(function, str):
            function = getattr(mxnp, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function):
        super().__init__()
        if isinstance(function, str):
            function = getattr(mxnp, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class Concatenate(Sequential):
    """Run children on the same input, concat outputs (≙ basic_layers.py:1002)."""

    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        outs = [block(x) for block in self._children.values()]
        return mxnp.concatenate(outs, axis=self._axis)


class HybridConcatenate(HybridSequential):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        outs = [block(x) for block in self._children.values()]
        return mxnp.concatenate(outs, axis=self._axis)


# ---------------------------------------------------------------------------
# activations (≙ gluon/nn/activations.py)
# ---------------------------------------------------------------------------
class Activation(HybridBlock):
    def __init__(self, activation):
        super().__init__()
        self._act_type = activation

    def forward(self, x):
        return npx.activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return npx.leaky_relu(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, in_channels=1):
        super().__init__()
        from ... import initializer as init_mod
        self.alpha = Parameter(shape=(in_channels,),
                               init=alpha_initializer or
                               init_mod.Constant(0.25), name="alpha")

    def forward(self, x):
        return npx.leaky_relu(x, gamma=self.alpha.data(), act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return npx.elu(x, alpha=self._alpha)


class SELU(HybridBlock):
    def forward(self, x):
        return npx.selu(x)


class GELU(HybridBlock):
    def __init__(self, approximation="erf"):
        super().__init__()
        self._approx = approximation != "erf"

    def forward(self, x):
        return npx.gelu(x, approximate=self._approx)


class Swish(HybridBlock):
    def __init__(self, beta=1.0):
        super().__init__()
        self._beta = beta

    def forward(self, x):
        if self._beta == 1.0:
            return npx.silu(x)
        return x * npx.sigmoid(self._beta * x)


SiLU = Swish


# ---------------------------------------------------------------------------
# conv / pool layers (≙ gluon/nn/conv_layers.py:219-1204)
# ---------------------------------------------------------------------------
class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="convolution", adj=None, dtype="float32"):
        super().__init__()
        self._channels = channels
        self._in_channels = in_channels
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * (len(layout) - 2)
        self._kernel = tuple(kernel_size)
        self._strides = strides
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._layout = layout
        self._act_type = activation
        self._op_name = op_name
        self._adj = adj
        wshape = self._weight_shape(in_channels if in_channels else 0)
        self.weight = Parameter(shape=wshape, dtype=dtype,
                                init=weight_initializer,
                                allow_deferred_init=True, name="weight")
        self.bias = (Parameter(shape=(channels,), dtype=dtype,
                               init=bias_initializer,
                               allow_deferred_init=True, name="bias")
                     if use_bias else None)

    def _channel_axis(self):
        return 1 if self._layout.startswith("NC") else len(self._layout) - 1

    def _weight_shape(self, in_ch):
        # layouts: NCHW→OIHW weights; NHWC→HWIO (ops/nn.py conv contract)
        if self._op_name == "deconvolution":
            # reference deconv weight: (in, out/groups, *k) for NCHW
            if self._layout.startswith("NC"):
                return (in_ch, self._channels // self._groups) + self._kernel
            return self._kernel + (self._channels // self._groups, in_ch)
        if self._layout.startswith("NC"):
            return (self._channels,
                    (in_ch // self._groups) if in_ch else 0) + self._kernel
        return self._kernel + ((in_ch // self._groups) if in_ch else 0,
                               self._channels)

    def infer_shape(self, x, *args):
        in_ch = x.shape[self._channel_axis()]
        self.weight.shape = self._weight_shape(in_ch)
        if self.bias is not None:
            self.bias.shape = (self._channels,)

    def forward(self, x):
        bias = None if self.bias is None else self.bias.data()
        fuse_ba = (self._act_type in _FUSABLE_ACTS and bias is not None
                   and _fusion_on())
        if fuse_ba:
            bias_arr, bias = bias, None   # bias folds into the fused act
        if self._op_name == "convolution":
            y = npx.convolution(x, self.weight.data(), bias,
                                stride=self._strides, dilate=self._dilation,
                                pad=self._padding, num_group=self._groups,
                                no_bias=bias is None, layout=self._layout)
        else:
            y = npx.deconvolution(x, self.weight.data(), bias,
                                  stride=self._strides, dilate=self._dilation,
                                  pad=self._padding, adj=self._adj or 0,
                                  num_group=self._groups,
                                  no_bias=bias is None, layout=self._layout)
        if fuse_ba:
            return npx.fused_bias_act(y, bias_arr, act_type=self._act_type,
                                      axis=self._channel_axis())
        if self._act_type:
            y = npx.activation(y, act_type=self._act_type)
        return y

    def __repr__(self):
        return (f"{type(self).__name__}({self._channels}, "
                f"kernel_size={self._kernel}, stride={self._strides})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="deconvolution", adj=output_padding, **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="deconvolution", adj=output_padding, **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="deconvolution", adj=output_padding, **kwargs)


class _Pool(HybridBlock):
    def __init__(self, pool_size, strides, padding, global_pool, pool_type,
                 layout, ceil_mode=False, count_include_pad=True):
        super().__init__()
        self._kernel = pool_size
        self._stride = strides if strides is not None else pool_size
        self._pad = padding
        self._global = global_pool
        self._type = pool_type
        self._layout = layout
        self._count_include_pad = count_include_pad
        self._ceil_mode = ceil_mode

    def _fused_pool_size(self, x):
        """(ph, pw) when this pool can take the fused non-overlapping
        NHWC kernel (VMEM-tiled Pallas backward), else None: avg type,
        NHWC 2-D, zero padding, kernel == stride dividing the spatial
        dims — which covers AvgPool2D(k, k) and GlobalAvgPool2D."""
        if self._type != "avg" or self._layout != "NHWC" or x.ndim != 4:
            return None
        h, w = x.shape[1], x.shape[2]
        if self._global:
            return (h, w)
        k = (self._kernel,) * 2 if isinstance(self._kernel, int) \
            else tuple(self._kernel)
        s = (self._stride,) * 2 if isinstance(self._stride, int) \
            else tuple(self._stride)
        p = (self._pad,) * 2 if isinstance(self._pad, int) \
            else tuple(self._pad)
        if len(k) == 2 and k == s and p == (0, 0) \
                and h % k[0] == 0 and w % k[1] == 0:
            return k
        return None

    def forward(self, x):
        if _fusion_on():
            ps = self._fused_pool_size(x)
            if ps is not None:
                return npx.fused_avg_pool2d(x, ps, layout="NHWC")
        return npx.pooling(x, kernel=self._kernel, pool_type=self._type,
                           stride=self._stride, pad=self._pad,
                           global_pool=self._global,
                           count_include_pad=self._count_include_pad,
                           layout=self._layout, ceil_mode=self._ceil_mode)

    def __repr__(self):
        return (f"{type(self).__name__}(size={self._kernel}, "
                f"stride={self._stride}, padding={self._pad})")


class MaxPool1D(_Pool):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, False, "max", layout,
                         ceil_mode)


class MaxPool2D(_Pool):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, False, "max", layout,
                         ceil_mode)


class MaxPool3D(_Pool):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, False, "max", layout,
                         ceil_mode)


class AvgPool1D(_Pool):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(pool_size, strides, padding, False, "avg", layout,
                         ceil_mode, count_include_pad)


class AvgPool2D(_Pool):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(pool_size, strides, padding, False, "avg", layout,
                         ceil_mode, count_include_pad)


class AvgPool3D(_Pool):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(pool_size, strides, padding, False, "avg", layout,
                         ceil_mode, count_include_pad)


class GlobalMaxPool1D(_Pool):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__(1, None, 0, True, "max", layout)


class GlobalMaxPool2D(_Pool):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, True, "max", layout)


class GlobalMaxPool3D(_Pool):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, True, "max", layout)


class GlobalAvgPool1D(_Pool):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__(1, None, 0, True, "avg", layout)


class GlobalAvgPool2D(_Pool):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, True, "avg", layout)


class GlobalAvgPool3D(_Pool):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, True, "avg", layout)


class ReflectionPad2D(HybridBlock):
    """≙ conv_layers.py ReflectionPad2D (src/operator/pad.cc reflect mode)."""

    def __init__(self, padding=0):
        super().__init__()
        if isinstance(padding, int):
            padding = (padding,) * 4  # (left, right, top, bottom) per ref
        self._padding = padding

    def forward(self, x):
        p = self._padding
        return mxnp.pad(x, ((0, 0), (0, 0), (p[2], p[3]), (p[0], p[1])),
                        mode="reflect")


from .transformer import (MultiHeadAttention, TransformerEncoderCell,
                          TransformerDecoderCell, PositionalEmbedding)

__all__ += ["MultiHeadAttention", "TransformerEncoderCell",
            "TransformerDecoderCell", "PositionalEmbedding"]
