"""gluon.Block / HybridBlock — the module system.

Reference: python/mxnet/gluon/block.py (Block:202 — child/param registration,
hooks:518, save/load; HybridBlock:997 — deferred-compute trace `_get_graph`,
`_build_cache`:1095 → CachedOp:1211, `hybridize(static_alloc,static_shape)`
:1379, `infer_shape`, `export`:1471).

TPU-native design — `hybridize()` ≙ `jax.jit` (SURVEY §7 table):
the reference traces forward with deferred compute into an nnvm graph and
executes it through CachedOp (memory planning + fusion passes). Here the
forward is traced by XLA itself: `_build_cache` constructs a *pure* function
  pure_fn(param_buffers, rng_key, *input_buffers) -> (outputs, aux_updates)
by temporarily binding traced buffers into the parameters' NDArrays, running
the user's `forward`, and collecting any parameter whose buffer was replaced
during the trace (BatchNorm running stats etc.) as explicit aux outputs —
functionalizing the reference's mutable-state ops. XLA then does what
MXPlanMemory + pointwise fusion + NVRTC did (memory planning, fusion) during
compilation. Autograd through a hybridized call tapes the whole cached op as
ONE node (≙ the _CachedOp node in the reference tape).
"""
from __future__ import annotations

import re
from collections import OrderedDict

import numpy as _np

from ..base import MXNetError
from .. import autograd
from .. import random as _random
from .parameter import Parameter, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


def _amp_fingerprint():
    """The active autocast target dtype, or None (part of the cached-op
    cache key: amp-on and amp-off traces are different XLA programs)."""
    import sys
    amp_mod = sys.modules.get("incubator_mxnet_tpu.amp")
    if amp_mod is None or not amp_mod.is_active():
        return None
    return amp_mod._state["target_dtype"]


def _fusion_fingerprint():
    """Whether the fused kernel tier is active (part of the cached-op
    cache key: a fusion-on trace bakes fused ops into the XLA program,
    a fusion-off trace must not reuse it)."""
    from ..ops import fused as _fused
    return _fused.fusion_enabled()


from contextlib import contextmanager as _contextmanager


@_contextmanager
def _amp_scope(amp_fp):
    """Re-enter (or force off) the autocast state a forward trace was built
    under — including the fingerprinted target dtype, which may have been
    re-inited globally since — so the backward's recompute trace bakes
    identical casts."""
    import sys
    amp_mod = sys.modules.get("incubator_mxnet_tpu.amp")
    if amp_fp is None:
        if amp_mod is None:
            yield
            return
        with amp_mod.autocast(False):
            yield
        return
    from .. import amp as amp_mod
    prev_dtype = amp_mod._state["target_dtype"]
    amp_mod._state["target_dtype"] = amp_fp
    try:
        with amp_mod.autocast(True):
            yield
    finally:
        amp_mod._state["target_dtype"] = prev_dtype


class _BlockScope:
    """Naming helper for programmatically-created children."""
    _count = {}

    @classmethod
    def create_name(cls, prefix):
        n = cls._count.get(prefix, 0)
        cls._count[prefix] = n + 1
        return f"{prefix}{n}"


class Block:
    """Base class for all layers and models (≙ gluon.Block, block.py:202)."""

    def __init__(self):
        self._children = OrderedDict()
        self._reg_params = OrderedDict()
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()
        self._hook_id = 0

    # ------------------------------------------------------------------
    # registration
    # ------------------------------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            params = self.__dict__.get("_reg_params")
            if params is not None:
                params[name] = value
                if value._name in (None, "param", "const"):
                    value._name = name
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block
        super().__setattr__(f"_child_{name}", block)

    def register_block(self, *a, **kw):
        return self.register_child(*a, **kw)

    # ------------------------------------------------------------------
    # parameter collection
    # ------------------------------------------------------------------
    def collect_params(self, select=None):
        """Dict of structural-name → Parameter (≙ Block.collect_params)."""
        out = OrderedDict()
        pat = re.compile(select) if select else None
        for name, p in self._iter_params(""):
            p._structural_name = name
            if pat is None or pat.match(name):
                out[name] = p
        return out

    @property
    def params(self):
        return dict(self._reg_params)

    def _iter_params(self, prefix):
        for name, p in self._reg_params.items():
            yield (prefix + name if prefix else name), p
        for cname, child in self._children.items():
            yield from child._iter_params(
                (prefix + cname + "." if prefix else cname + "."))

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def initialize(self, init=None, device=None, verbose=False,
                   force_reinit=False, ctx=None):
        """Initialize all parameters (≙ Block.initialize)."""
        for _, p in self.collect_params().items():
            p.initialize(init=None, device=device or ctx,
                         default_init=init, force_reinit=force_reinit)
        return self

    def hybridize(self, active=True, **kwargs):
        """Recursively activate hybrid (compiled) execution."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self._reg_params.values():
            p.cast(dtype)
        return self

    def reset_ctx(self, device):
        for _, p in self.collect_params().items():
            p.reset_ctx(device)

    reset_device = reset_ctx

    def zero_grad(self):
        for _, p in self.collect_params().items():
            p.zero_grad()

    def setattr(self, name, value):
        """Set an attribute on all parameters (≙ Block.setattr), e.g.
        net.setattr('grad_req', 'null')."""
        for _, p in self.collect_params().items():
            setattr(p, name, value)

    def share_parameters(self, shared):
        """Adopt parameters from `shared` dict by structural name
        (≙ Block.share_parameters)."""
        own = self.collect_params()
        for name, p in shared.items():
            if name in own:
                self._replace_param(name, p)
        return self

    def _replace_param(self, structural_name, new_param):
        parts = structural_name.split(".")
        blk = self
        for part in parts[:-1]:
            blk = blk._children[part]
        blk._reg_params[parts[-1]] = new_param
        object.__setattr__(blk, parts[-1], new_param)

    # ------------------------------------------------------------------
    # hooks (≙ block.py:518 register_forward_hook etc.)
    # ------------------------------------------------------------------
    def register_forward_hook(self, hook):
        self._hook_id += 1
        self._forward_hooks[self._hook_id] = hook
        return _HookHandle(self._forward_hooks, self._hook_id)

    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return _HookHandle(self._forward_pre_hooks, self._hook_id)

    def apply(self, fn):
        """Apply fn to self and all children recursively (≙ Block.apply)."""
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # ------------------------------------------------------------------
    # save / load (≙ Block.save_parameters / load_parameters)
    # ------------------------------------------------------------------
    def save_parameters(self, filename, deduplicate=False):
        params = self.collect_params()
        seen = {}
        payload = {}
        for name, p in params.items():
            if p._data is None:
                continue
            arr = p.data().asnumpy()
            if deduplicate and id(p) in seen:
                continue
            seen[id(p)] = name
            payload[name] = arr
        # temp write + atomic rename: a crash mid-save never truncates a
        # previously-good params file (see mx.fault)
        from .. import fault as _fault
        with _fault.atomic_output(filename) as f:
            _np.savez(f, **payload)

    def load_parameters(self, filename, device=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False, ctx=None):
        loaded = dict(_np.load(filename, allow_pickle=False))
        params = self.collect_params()
        for name, p in params.items():
            if name not in loaded:
                if not allow_missing:
                    raise MXNetError(
                        f"parameter {name} missing in file {filename}")
                continue
            arr = loaded[name]
            if cast_dtype:
                arr = arr.astype(p.dtype)
            p.shape = arr.shape
            from ..ndarray import array
            p.set_data(array(arr, device=device or ctx, dtype=str(arr.dtype)))
        if not ignore_extra:
            extra = set(loaded) - set(params)
            if extra:
                raise MXNetError(
                    f"file {filename} contains extra parameters {sorted(extra)}")

    load_params = load_parameters
    save_params = save_parameters

    def load_dict(self, param_dict, device=None, allow_missing=False,
                  ignore_extra=False):
        params = self.collect_params()
        for name, p in params.items():
            if name not in param_dict:
                if not allow_missing:
                    raise MXNetError(f"parameter {name} missing in dict")
                continue
            p.set_data(param_dict[name])

    # ------------------------------------------------------------------
    # call path
    # ------------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        if not self.__dict__.get("_params_ready", False):
            self._resolve_own_deferred(*args)
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def _resolve_own_deferred(self, *args):
        """Just-in-time shape inference for this block's own parameters
        (≙ HybridBlock._deferred_infer_shape): leaf layers override
        infer_shape; it runs on the first call when input shapes are known."""
        pending = [p for p in self._reg_params.values()
                   if p._deferred_init is not None]
        if pending:
            self.infer_shape(*args)
            for p in pending:
                p._finish_deferred_init()
        self._params_ready = True

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def infer_shape(self, *args):
        """Infer deferred parameter shapes from inputs. Layers that own
        deferred params override this (≙ HybridBlock.infer_shape)."""
        raise MXNetError(
            f"{type(self).__name__} has parameters with unknown shape but "
            "does not implement infer_shape(*inputs)")

    def summary(self, *inputs):
        """Print a per-layer summary (≙ Block.summary)."""
        rows = []

        def _hook(block, ins, outs):
            o = outs[0] if isinstance(outs, (list, tuple)) else outs
            n_params = sum(int(_np.prod(p.shape or ()))
                           for p in block._reg_params.values()
                           if p.shape is not None)
            rows.append((type(block).__name__, tuple(getattr(o, "shape", ())),
                         n_params))

        handles = []
        for block in _walk(self):
            handles.append(block.register_forward_hook(_hook))
        try:
            self(*inputs)
        finally:
            for h in handles:
                h.detach()
        total = sum(r[2] for r in rows)
        lines = [f"{'Layer':<28}{'Output shape':<24}{'Params':>12}",
                 "-" * 64]
        lines += [f"{n:<28}{str(s):<24}{p:>12}" for n, s, p in rows]
        lines += ["-" * 64, f"{'Total params':<52}{total:>12}"]
        print("\n".join(lines))
        return "\n".join(lines)

    def __repr__(self):
        lines = [type(self).__name__ + "("]
        for name, child in self._children.items():
            child_repr = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {child_repr}")
        lines.append(")")
        return "\n".join(lines)


def _walk(block):
    yield block
    for c in block._children.values():
        yield from _walk(c)


def _in_trace(args):
    """True when any input is a jax tracer (we're under an enclosing jit)."""
    import jax
    from ..ndarray import NDArray
    for a in args:
        raw = a._arr if isinstance(a, NDArray) else a
        if isinstance(raw, jax.core.Tracer):
            return True
    return False


class _HookHandle:
    def __init__(self, hooks, hid):
        self._hooks, self._hid = hooks, hid

    def detach(self):
        self._hooks.pop(self._hid, None)


# ---------------------------------------------------------------------------
# HybridBlock
# ---------------------------------------------------------------------------
class HybridBlock(Block):
    """A Block whose forward can be compiled as one XLA computation
    (≙ gluon.HybridBlock, block.py:997; hybridize ≙ CachedOp ≙ jax.jit)."""

    def __init__(self):
        super().__init__()
        self._active = False
        self._cached_graph = {}  # (training, amp_fp) -> (jit fn, meta, bwd)
        self._cached_params = None  # stable param order for the cache
        self._shapes_ready = False
        self._jit_kwargs = {}
        self._subgraph_backend = None  # optimize_for rewriter (subgraph.py)

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        """Activate compiled execution. static_alloc maps to XLA buffer
        donation (handled by jit automatically); static_shape means "don't
        re-specialize per shape" — jax.jit already caches per shape, so both
        flags are accepted for API compatibility (reference block.py:1379)."""
        self._active = active
        self._cached_graph = {}
        self._cached_params = None
        self._shapes_ready = False
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def __call__(self, *args, **kwargs):
        if not self._active or kwargs or _in_trace(args):
            # inside an enclosing trace the parent cache already captures this
            # block's ops (≙ child CachedOps fold into the parent graph)
            return super().__call__(*args, **kwargs)
        if not self._shapes_ready:
            # deferred params anywhere in the tree: run ONE eager pass so each
            # leaf resolves its shapes just in time, then cache from next call
            if any(p._deferred_init is not None
                   for _, p in self.collect_params().items()):
                return super().__call__(*args, **kwargs)
            self._shapes_ready = True
        return self._call_cached(*args)

    # ------------------------------------------------------------------
    # the CachedOp equivalent
    # ------------------------------------------------------------------
    def _call_cached(self, *args):
        import jax
        from ..ndarray import NDArray, _wrap

        for hook in self._forward_pre_hooks.values():
            hook(self, args)

        if self._cached_params is None:
            self._cached_params = [p for _, p in
                                   sorted(self.collect_params().items())]
        params = self._cached_params
        training = autograd.is_training()
        # cache key includes the autocast state (an amp-on trace bakes
        # bf16 casts into the XLA program) and the fused-tier state (a
        # fusion-on trace bakes fused ops) — neither may serve the other
        amp_fp = (_amp_fingerprint(), _fusion_fingerprint())
        cached = self._cached_graph.get((training, amp_fp))
        if cached is None:
            cached = self._build_cache(training, amp_fp)
            self._cached_graph[(training, amp_fp)] = cached
        jit_fn, meta = cached[0], cached[1]

        n_in = len(args)
        key = _random.next_key()

        from ..ops.registry import invoke

        def runner(key, *flat):
            inputs, pbufs = flat[:n_in], flat[n_in:]
            outs, aux, _ = jit_fn(pbufs, key, *inputs)
            return tuple(outs) + tuple(aux)

        get_bwd = cached[2]
        cached_vjp = lambda raw, cts: get_bwd(n_in)(raw[0], raw[1:], cts)
        results = invoke(runner,
                         (key,) + tuple(args)
                         + tuple(p.data() for p in params),
                         name=type(self).__name__, multi_out=True,
                         cached_vjp=cached_vjp)
        n_out = meta["n_out"]
        outs = results[:n_out]
        aux_new = results[n_out:]
        # write functionalized aux-state updates back into their parameters
        for p_idx, new_val in zip(meta["aux_indices"], aux_new):
            arr = params[p_idx].data()
            with autograd.pause():
                arr._set_arr(new_val._data)  # adopt without materializing
        out = meta["treedef"](outs)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def _build_cache(self, training, fp=None):
        """Construct + jit the pure function for this block (≙ _build_cache
        block.py:1095 building the CachedOp). `fp` is the
        (amp fingerprint, fusion fingerprint) pair from _call_cached."""
        import jax
        from ..ops import fused as _fused
        amp_fp, fusion_fp = fp if isinstance(fp, tuple) else (fp, False)
        params = self._cached_params
        block = self
        meta = {"n_out": None, "aux_indices": None, "treedef": None}

        def pure_fn(pbufs, rng_key, *inputs):
            from ..ndarray import NDArray, _wrap
            saved = []
            for p, buf in zip(params, pbufs):
                nd = p.data()
                saved.append(nd._data)
                nd._data = buf
                nd._version += 1
            mutated = {}
            try:
                # pin the fingerprinted fused-tier state: jit may retrace
                # this fn later (new shapes) under a different ambient
                # scope, and the cache entry's routing must not flip
                with autograd._Scope(recording=False, training=training), \
                        _random.trace_key_scope(rng_key), \
                        _fused.fusion_scope(fusion_fp):
                    wrapped = tuple(_wrap(x) for x in inputs)
                    out = block.forward(*wrapped)
                single = not isinstance(out, (list, tuple))
                outs = (out,) if single else tuple(out)
                out_raw = tuple(o._arr for o in outs)
                for i, (p, buf) in enumerate(zip(params, pbufs)):
                    cur = p.data()._data
                    if cur is not buf:
                        mutated[i] = cur
            finally:
                for p, old in zip(params, saved):
                    p.data()._data = old
            if meta["n_out"] is None:
                meta["n_out"] = len(out_raw)
                meta["aux_indices"] = sorted(mutated)
                meta["single"] = single
                if single:
                    meta["treedef"] = lambda outs: outs[0]
                else:
                    meta["treedef"] = lambda outs: tuple(outs)
            aux = tuple(mutated[i] for i in sorted(mutated))
            return out_raw, aux, None

        fwd = pure_fn
        if self._subgraph_backend is not None:
            # optimize_for plug-in point: the backend rewrites the traced
            # equations; the result still compiles as one XLA program.
            # The backward below recomputes through THIS wrapped forward,
            # so gradients flow through the rewritten math, not the
            # original equations.
            fwd = self._subgraph_backend.transform_callable(pure_fn)

        bwd_cache = {}

        def get_bwd(n_in):
            """Jitted recompute-based VJP, compiled ONCE per input arity.

            Per-call jax.vjp over the cached graph re-traces + transposes in
            Python every step (50ms-class overhead on a ResNet) and runs the
            backward through the eager transpose interpreter. Instead:
            recompute the forward inside ONE jitted backward — XLA fuses
            fwd-recompute + transpose into a single program, no residual
            storage, no per-step tracing. The rng key rides through as a
            jit argument so dropout masks replay identically.
            """
            bwd = bwd_cache.get(n_in)
            if bwd is not None:
                return bwd

            def bwd_fn(key, flat_args, cts):
                def flat_fn(*a):
                    inputs, pbufs = a[:n_in], a[n_in:]
                    outs, aux, _ = fwd(pbufs, key, *inputs)
                    return tuple(outs) + tuple(aux)

                # replay the forward's autocast AND fused-tier state:
                # backward runs with amp suspended, but the recompute must
                # bake the SAME bf16 casts and the SAME fused-op routing
                # the forward trace did or cotangent dtypes/graphs mismatch
                with _amp_scope(amp_fp), _fused.fusion_scope(fusion_fp):
                    _, vjp = jax.vjp(flat_fn, *flat_args)
                grads = vjp(tuple(cts))
                # None for the (integer) rng key slot + float0 -> None so
                # jit never returns float0 buffers
                clean = tuple(
                    None if (hasattr(g, "dtype")
                             and g.dtype == jax.dtypes.float0) else g
                    for g in grads)
                return (None,) + clean

            bwd = jax.jit(bwd_fn)
            bwd_cache[n_in] = bwd
            return bwd

        return jax.jit(fwd), meta, get_bwd

    # ------------------------------------------------------------------
    def optimize_for(self, x, *args, backend=None, **kwargs):
        """≙ HybridBlock.optimize_for (block.py:1272).

        On TPU the baseline graph optimization happens in XLA, so
        backend=None/'xla' hybridizes and warms the cache. Registered
        SUBGRAPH BACKENDS (gluon.subgraph.register_subgraph_backend, the
        plug-in point ≙ subgraph_property.h) additionally rewrite the
        traced equations before jit — third-party rewrites compose into
        the same compiled program. Unknown backends raise (reference
        semantics: partitioning for an unregistered backend is an error,
        not a silent no-op)."""
        _KNOWN = (None, "xla", "XLA", "tpu", "TPU")
        if backend not in _KNOWN:
            from .subgraph import get_subgraph_backend
            self._subgraph_backend = get_subgraph_backend(backend)
            self._cached_graph.clear()   # rebuild with the rewriter applied
        elif self._subgraph_backend is not None:
            # explicit revert to the baseline stack
            self._subgraph_backend = None
            self._cached_graph.clear()
        self.hybridize(True)
        self(x, *args)

    def export(self, path, epoch=0, remove_amp_cast=True,
               example_inputs=None):
        """Serialize the compiled graph + params (≙ HybridBlock.export,
        block.py:1471: model-symbol.json + model-0000.params).

        Always saves `<path>-<epoch>.params.npz` (weights by structural
        name); when `example_inputs` are given, additionally saves

          * `<path>-<epoch>.stablehlo.mlir` — StableHLO text of the
            inference forward (the portable artifact replacing the nnvm
            symbol JSON),
          * `<path>-<epoch>.jaxport` — the versioned `jax.export`
            serialization of the same module, lowered for cpu AND tpu
            (the *executable* artifact: `deploy.ExportedModel`, the C ABI
            in native/c_api.cc, and the C++ frontend all consume it),
          * `<path>-<epoch>.deploy.json` — manifest (param order, input
            avals, output arity) so a loader needs no Python model class.

        Returns the tuple of file paths written."""
        import jax
        from ..ndarray import NDArray
        params_file = f"{path}-{epoch:04d}.params.npz"
        self.save_parameters(params_file)
        outputs = [params_file]
        if example_inputs is not None:
            if not isinstance(example_inputs, (list, tuple)):
                example_inputs = (example_inputs,)
            if self._cached_params is None:
                self._cached_params = [p for _, p in
                                       sorted(self.collect_params().items())]
            params = self._cached_params
            cached = self._cached_graph.get((False, None))
            if cached is None:
                cached = self._build_cache(False)
                self._cached_graph[(False, None)] = cached
            jit_fn, meta = cached[0], cached[1]
            pbufs = tuple(p.data()._arr for p in params)
            in_raw = tuple(a._arr if isinstance(a, NDArray) else a
                           for a in example_inputs)
            # constant key: export must not advance the global RNG stream
            dummy_key = jax.random.PRNGKey(0)
            lowered = jit_fn.lower(pbufs, dummy_key, *in_raw)
            hlo_file = f"{path}-{epoch:04d}.stablehlo.mlir"
            with open(hlo_file, "w") as f:
                f.write(lowered.as_text(dialect="stablehlo"))
            outputs.append(hlo_file)

            import json
            import jax.export as _jexp
            exported = _jexp.export(jit_fn, platforms=("cpu", "tpu"))(
                pbufs, dummy_key, *in_raw)
            port_file = f"{path}-{epoch:04d}.jaxport"
            with open(port_file, "wb") as f:
                f.write(exported.serialize())
            outputs.append(port_file)

            manifest = {
                "format_version": 1,
                "params": [name for name, _ in
                           sorted(self.collect_params().items())],
                "n_out": meta["n_out"],
                "single_output": bool(meta.get("single", meta["n_out"] == 1)),
                "aux_indices": list(meta["aux_indices"]),
                "inputs": [{"shape": list(a.shape), "dtype": str(a.dtype)}
                           for a in in_raw],
            }
            man_file = f"{path}-{epoch:04d}.deploy.json"
            with open(man_file, "w") as f:
                json.dump(manifest, f, indent=1)
            outputs.append(man_file)
        return tuple(outputs)

    def forward(self, *args):
        raise NotImplementedError

    def reset_cache(self):
        self._cached_graph = {}
        self._cached_params = None
        self._shapes_ready = False


class SymbolBlock(HybridBlock):
    """≙ gluon.SymbolBlock (block.py:1638). The reference wraps a saved
    symbol graph; here a saved exported module (`HybridBlock.export`
    artifact triple) served by `deploy.ExportedModel`. `forward` executes
    the compiled program — no Python model class required."""

    def __init__(self, model):
        super().__init__()
        self._model = model

    def forward(self, *args):
        # Traceable path (call_arrays): works eagerly AND under a parent
        # hybridize trace — the exported program composes into the outer
        # XLA computation instead of forcing a host round-trip.
        from ..ndarray import NDArray, _wrap
        arrs = [a._arr if isinstance(a, NDArray) else a for a in args]
        out_raw = self._model.call_arrays(*arrs)
        outs = tuple(_wrap(o) for o in out_raw)
        return outs[0] if self._model.single_output else outs

    @staticmethod
    def imports(symbol_file, input_names=None, param_file=None, device=None):
        """Load a saved model artifact.

        Two formats are accepted (≙ gluon.SymbolBlock.imports):
        - a reference `*-symbol.json` legacy graph (+ `.params` checkpoint):
          parsed by mx.symbol and executed as a pure jax function
          (gluon/_legacy_symbol_block.py);
        - this framework's own export triple (`net-0000` prefix or the
          `.jaxport` path): served by deploy.ExportedModel.
        """
        if symbol_file.endswith(".json"):
            from ._legacy_symbol_block import build_legacy_block
            return build_legacy_block(symbol_file, input_names, param_file)
        from ..deploy import ExportedModel
        if symbol_file.endswith(".jaxport"):
            prefix = symbol_file[:-len(".jaxport")]
        elif symbol_file.endswith(".stablehlo.mlir"):
            prefix = symbol_file[:-len(".stablehlo.mlir")]
        else:
            prefix = symbol_file
        model = ExportedModel(
            prefix,
            params=param_file if param_file else None)
        return SymbolBlock(model)
