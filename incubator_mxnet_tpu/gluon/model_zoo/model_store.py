"""Pretrained-weight store: offline loading + reference-format conversion.

Reference: python/mxnet/gluon/model_zoo/model_store.py (download + cache of
pretrained .params). This environment has no network egress, so the store is
strictly local: `get_model_file(name, root)` resolves `<root>/<name>.npz`
(native container) or `<root>/<name>.params` (the reference's binary format,
parsed by `load_params_file`), and `pretrained=True` on any model-zoo
constructor loads from there. `tools/convert_model.py` converts a reference
checkpoint into the npz zoo.

Binary format (studied from /root/reference/src/ndarray/ndarray.cc:1852-2143
and include/mxnet/tuple.h:731 — reimplemented, not copied):

  file   := u64 0x112 | u64 0 | vec<ndarray> | vec<string names>
  vec<T> := u64 count | T...          (dmlc::Stream container serialization)
  string := u64 length | bytes
  ndarray(V2/V3) := u32 magic(0xF993fac9|0xF993faca) | i32 stype
                  | shape | ctx(i32 dev_type, i32 dev_id) | i32 type_flag
                  | raw data
  shape  := i32 ndim | i64 * ndim
  type_flag follows mshadow: 0=f32 1=f64 2=f16 3=u8 4=i32 5=i8 6=i64
                             7=bool 8=i16 9=u16 10=u32 11=u64 12=bf16
"""
from __future__ import annotations

import os
import struct

import numpy as _np

from ...base import MXNetError

__all__ = ["get_model_file", "load_params_file", "save_params_file",
           "convert_params_to_npz", "load_pretrained", "auto_name_map"]

_LIST_MAGIC = 0x112
_V2_MAGIC = 0xF993FAC9
_V3_MAGIC = 0xF993FACA
_V1_MAGIC = 0xF993FAC8

_DTYPE_OF_FLAG = {0: _np.float32, 1: _np.float64, 2: _np.float16,
                  3: _np.uint8, 4: _np.int32, 5: _np.int8, 6: _np.int64,
                  7: _np.bool_, 8: _np.int16, 9: _np.uint16,
                  10: _np.uint32, 11: _np.uint64}
_BF16_FLAG = 12   # mshadow kBfloat16: stored as raw uint16, widened to f32
_FLAG_OF_DTYPE = {_np.dtype(v): k for k, v in _DTYPE_OF_FLAG.items()}


def _widen_bf16(raw_u16):
    # numpy has no native bf16: place the 16 payload bits in the high half
    # of a float32 word (bf16 is f32 truncated to its top 16 bits)
    return (raw_u16.astype(_np.uint32) << 16).view(_np.float32)


def default_root():
    return os.path.expanduser(
        os.environ.get("MXNET_HOME",
                       os.path.join("~", ".mxnet")) + "/models")


def get_model_file(name, root=None):
    """Resolve a local pretrained-weight file for `name` (npz preferred,
    reference .params accepted). ≙ model_store.get_model_file minus the
    download: this build is offline by design."""
    root = root or default_root()
    for ext in (".npz", ".params"):
        p = os.path.join(root, name + ext)
        if os.path.exists(p):
            return p
    raise MXNetError(
        f"no pretrained weights for {name!r} under {root} "
        f"(looked for {name}.npz / {name}.params). This build has no "
        "network egress: place a weight file there, or convert a reference "
        "checkpoint with tools/convert_model.py")


# ---------------------------------------------------------------------------
# reference .params binary format
# ---------------------------------------------------------------------------
class _Reader:
    def __init__(self, data):
        self.d = data
        self.i = 0

    def take(self, n):
        if n < 0 or self.i + n > len(self.d):
            raise MXNetError("truncated .params file")
        out = self.d[self.i:self.i + n]
        self.i += n
        return out

    def u32(self):
        return struct.unpack("<I", self.take(4))[0]

    def i32(self):
        return struct.unpack("<i", self.take(4))[0]

    def u64(self):
        return struct.unpack("<Q", self.take(8))[0]


def _read_one_ndarray(r):
    magic = r.u32()
    if magic in (_V2_MAGIC, _V3_MAGIC):
        stype = r.i32()
        if stype != 0:    # kDefaultStorage
            raise MXNetError(
                "sparse arrays in .params are unsupported (dense-only TPU "
                "build; cast_storage the checkpoint first)")
        ndim = r.i32()
        if ndim == -1:
            # V3 "none" (uninitialized) record: nothing else follows
            return _np.zeros((), _np.float32)
        shape = struct.unpack(f"<{ndim}q", r.take(8 * ndim))
    elif magic == _V1_MAGIC:
        ndim = r.i32()
        shape = struct.unpack(f"<{ndim}q", r.take(8 * ndim))
    else:
        # legacy: magic IS ndim, dims are u32
        ndim = magic
        if ndim > 32:
            raise MXNetError(f"corrupt .params (ndim={ndim})")
        shape = struct.unpack(f"<{ndim}I", r.take(4 * ndim))
    if len(shape) == 0 and magic != _V3_MAGIC:
        # legacy/V2 ndim==0 encodes "none": no ctx/dtype/data follow.
        # V3 (np-shape semantics) saves real 0-d scalars WITH ctx, dtype,
        # and one element — falling through keeps the stream in sync.
        return _np.zeros((), _np.float32)
    r.i32()   # ctx dev_type
    r.i32()   # ctx dev_id
    flag = r.i32()
    n = int(_np.prod(shape)) if shape else 1
    if flag == _BF16_FLAG:
        raw = _np.frombuffer(r.take(n * 2), dtype=_np.uint16)
        return _widen_bf16(raw).reshape(shape).copy()
    if flag not in _DTYPE_OF_FLAG:
        raise MXNetError(f"unsupported dtype flag {flag} in .params")
    dt = _np.dtype(_DTYPE_OF_FLAG[flag])
    arr = _np.frombuffer(r.take(n * dt.itemsize), dtype=dt).reshape(shape)
    return arr.copy()


def load_params_file(path, keep_prefixes=False):
    """Parse a reference-format .params file -> dict {name: np.ndarray}.

    Module-era files carry "arg:"/"aux:" name prefixes; they strip by
    default (gluon load semantics) — keep_prefixes=True preserves them
    (auto_name_map needs the grouping)."""
    with open(path, "rb") as f:
        r = _Reader(f.read())
    if r.u64() != _LIST_MAGIC:
        raise MXNetError(f"{path}: not an NDArray list file (bad magic)")
    r.u64()                      # reserved
    n = r.u64()
    arrays = [_read_one_ndarray(r) for _ in range(n)]
    n_keys = r.u64()
    names = []
    for _ in range(n_keys):
        ln = r.u64()
        names.append(r.take(ln).decode())
    if names and len(names) != len(arrays):
        raise MXNetError(f"{path}: key/array count mismatch")
    if not names:
        names = [f"arg:arr_{i}" for i in range(len(arrays))]
    if keep_prefixes:
        return {nm: a for nm, a in zip(names, arrays)}
    # the reference prefixes "arg:"/"aux:" in Module-era files; strip
    return {nm.split(":", 1)[-1]: a for nm, a in zip(names, arrays)}


def save_params_file(path, params):
    """Write a reference-compatible .params (V2 records, cpu context)."""
    out = bytearray()
    out += struct.pack("<QQ", _LIST_MAGIC, 0)
    items = list(params.items())
    out += struct.pack("<Q", len(items))
    for _, arr in items:
        # asarray, not ascontiguousarray: the latter promotes 0-d to (1,)
        arr = _np.asarray(arr)
        if not arr.flags["C_CONTIGUOUS"]:
            arr = _np.copy(arr, order="C")
        if arr.dtype not in _FLAG_OF_DTYPE:
            arr = arr.astype(_np.float32)
        # 0-d records need V3 (np-shape semantics): under V2 a zero ndim
        # encodes "none" and carries no data, so scalars wouldn't round-trip
        out += struct.pack("<I", _V3_MAGIC if arr.ndim == 0 else _V2_MAGIC)
        out += struct.pack("<i", 0)                      # default storage
        out += struct.pack("<i", arr.ndim)
        out += struct.pack(f"<{arr.ndim}q", *arr.shape)
        out += struct.pack("<ii", 1, 0)                  # cpu:0
        out += struct.pack("<i", _FLAG_OF_DTYPE[arr.dtype])
        out += arr.tobytes()
    out += struct.pack("<Q", len(items))
    for nm, _ in items:
        b = nm.encode()
        out += struct.pack("<Q", len(b)) + b
    with open(path, "wb") as f:
        f.write(bytes(out))
    return path


def convert_params_to_npz(params_path, npz_path, name_map=None):
    """Convert a reference .params checkpoint into the npz zoo format.

    name_map: optional {reference_name: target_name} renaming (real
    reference zoo files use layer-name keys that may differ from this
    framework's structured names)."""
    params = load_params_file(params_path)
    if name_map:
        params = {name_map.get(k, k): v for k, v in params.items()}
    _np.savez(npz_path, **params)
    return npz_path


def load_pretrained(net, name, root=None):
    """Load pretrained weights into an initialized model-zoo net."""
    path = get_model_file(name, root)
    if path.endswith(".params"):
        arrays = load_params_file(path)
        import tempfile
        tmp = tempfile.NamedTemporaryFile(suffix=".npz", delete=False)
        _np.savez(tmp.name, **arrays)
        path = tmp.name
    net.load_parameters(path)
    return net


def auto_name_map(params_path, model_name):
    """Derive {checkpoint_name: framework_name} by aligning a reference
    checkpoint with the zoo architecture in construction order.

    Real reference zoo files use flat scoped names
    (`resnetv10_conv0_weight`, ...) while this framework uses structural
    names; both enumerate parameters in construction order for the same
    architecture, so a positional alignment with a SHAPE CHECK on every
    pair maps them without a hand-curated table. Raises on any mismatch
    (wrong architecture or variant)."""
    from . import vision

    factory = getattr(vision, model_name, None)
    if factory is None:
        raise MXNetError(f"unknown model-zoo architecture {model_name!r}")
    net = factory()
    net.initialize()
    # materialize deferred shapes with the architecture's standard input
    from ... import np as mxnp
    side = 299 if "inception" in model_name else 224
    net(mxnp.zeros((1, 3, side, side)))
    ours = list(net.collect_params().items())

    raw = load_params_file(params_path, keep_prefixes=True)
    has_prefixes = any(k.startswith(("arg:", "aux:")) for k in raw)
    if has_prefixes:
        # Module-era files group ALL args before ALL auxs; align each
        # group against the matching split of our construction order
        # (grad_req == 'null' marks auxiliary running stats)
        their_args = [(k.split(":", 1)[1], v) for k, v in raw.items()
                      if k.startswith("arg:")]
        their_auxs = [(k.split(":", 1)[1], v) for k, v in raw.items()
                      if k.startswith("aux:")]
        our_args = [(n, p) for n, p in ours if p.grad_req != "null"]
        our_auxs = [(n, p) for n, p in ours if p.grad_req == "null"]
        groups = [(their_args, our_args, "arg"),
                  (their_auxs, our_auxs, "aux")]
    else:
        groups = [(list(raw.items()), ours, "param")]

    mapping = {}
    for theirs, ours_group, kind in groups:
        if len(theirs) != len(ours_group):
            raise MXNetError(
                f"{params_path}: {len(theirs)} {kind} arrays vs "
                f"{len(ours_group)} in {model_name} — architecture "
                "mismatch (or extra/missing aux states)")
        for (their_name, their_arr), (our_name, our_p) in zip(theirs,
                                                              ours_group):
            if tuple(their_arr.shape) != tuple(our_p.data().shape):
                raise MXNetError(
                    f"shape mismatch aligning {their_name!r} "
                    f"{tuple(their_arr.shape)} -> {our_name!r} "
                    f"{tuple(our_p.data().shape)}; the checkpoint is not "
                    f"{model_name} (pass explicit --rename entries)")
            mapping[their_name] = our_name
    return mapping
