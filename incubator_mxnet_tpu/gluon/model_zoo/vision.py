"""gluon.model_zoo.vision — the reference CNN catalog.

Reference: python/mxnet/gluon/model_zoo/vision/{alexnet,densenet,inception,
mobilenet,resnet,squeezenet,vgg}.py. Same architectures and get_model()
registry; `pretrained=True` raises (no network egress — load weights from a
local file with load_parameters instead).

TPU note: the ResNet family accepts layout='NCHW' (reference default) or
'NHWC' (MXU-preferred; channels-last keeps the contraction dims minor for
the systolic array). Benchmarks use NHWC + bf16 + hybridize. Other
architectures are NCHW-only for now.
"""
from __future__ import annotations

from ...base import MXNetError
from .. import nn
from ..block import HybridBlock

__all__ = [
    "get_model", "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
    "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
    "resnet101_v2", "resnet152_v2", "ResNetV1", "ResNetV2",
    "alexnet", "AlexNet",
    "vgg11", "vgg13", "vgg16", "vgg19", "vgg11_bn", "vgg13_bn", "vgg16_bn",
    "vgg19_bn", "VGG",
    "squeezenet1_0", "squeezenet1_1", "SqueezeNet",
    "densenet121", "densenet161", "densenet169", "densenet201", "DenseNet",
    "mobilenet1_0", "mobilenet0_75", "mobilenet0_5", "mobilenet0_25",
    "mobilenet_v2_1_0", "mobilenet_v2_0_75", "mobilenet_v2_0_5",
    "mobilenet_v2_0_25", "MobileNet", "MobileNetV2",
    "inception_v3", "Inception3",
]


def _load_pretrained(net, name, pretrained, root=None):
    """pretrained=True loads local weights from the offline model store
    (≙ model_store.get_model_file download+cache, minus the download: this
    environment has no network egress; see
    gluon/model_zoo/model_store.py for the format + converter)."""
    if pretrained:
        from .model_store import load_pretrained
        load_pretrained(net, name, root=root)
    return net


# ---------------------------------------------------------------------------
# ResNet V1/V2 (≙ model_zoo/vision/resnet.py)
# ---------------------------------------------------------------------------
def _bn_axis(layout):
    return 1 if layout.startswith("NC") else -1


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW"):
        super().__init__()
        ax = _bn_axis(layout)
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels, 3, stride, 1, use_bias=False,
                                in_channels=in_channels, layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 3, 1, 1, use_bias=False,
                                in_channels=channels, layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, 1, stride, use_bias=False,
                                          in_channels=in_channels,
                                          layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None

    def forward(self, x):
        from ... import numpy_extension as npx
        from ...ops import fused as _fused
        residual = x
        if self.downsample is not None:
            residual = self.downsample(residual)
        if _fused.fusion_enabled():
            # kernel tier: each BN(+relu) is one fused pass, and the block
            # tail (BN + residual add + relu — the top memory-bound
            # offender class) is ONE op
            conv1, bn1, _act, conv2, bn2 = list(self.body)
            h = bn1.fused_forward(conv1(x), act_type="relu")
            return bn2.fused_forward(conv2(h), act_type="relu",
                                     residual=residual)
        x2 = self.body(x)
        return npx.relu(x2 + residual)


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW"):
        super().__init__()
        ax = _bn_axis(layout)
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels // 4, 1, stride, use_bias=False,
                                layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels // 4, 3, 1, 1, use_bias=False,
                                layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 1, 1, use_bias=False,
                                layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, 1, stride, use_bias=False,
                                          in_channels=in_channels,
                                          layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None

    def forward(self, x):
        from ... import numpy_extension as npx
        from ...ops import fused as _fused
        residual = x
        if self.downsample is not None:
            residual = self.downsample(residual)
        if _fused.fusion_enabled():
            (conv1, bn1, _a1, conv2, bn2, _a2,
             conv3, bn3) = list(self.body)
            h = bn1.fused_forward(conv1(x), act_type="relu")
            h = bn2.fused_forward(conv2(h), act_type="relu")
            return bn3.fused_forward(conv3(h), act_type="relu",
                                     residual=residual)
        x2 = self.body(x)
        return npx.relu(x2 + residual)


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW"):
        super().__init__()
        ax = _bn_axis(layout)
        self.bn1 = nn.BatchNorm(axis=ax)
        self.conv1 = nn.Conv2D(channels, 3, stride, 1, use_bias=False,
                               in_channels=in_channels, layout=layout)
        self.bn2 = nn.BatchNorm(axis=ax)
        self.conv2 = nn.Conv2D(channels, 3, 1, 1, use_bias=False,
                               in_channels=channels, layout=layout)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def forward(self, x):
        from ... import numpy_extension as npx
        from ...ops import fused as _fused
        residual = x
        if _fused.fusion_enabled():
            x = self.bn1.fused_forward(x, act_type="relu")
        else:
            x = npx.relu(self.bn1(x))
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        if _fused.fusion_enabled():
            x = self.bn2.fused_forward(x, act_type="relu")
        else:
            x = npx.relu(self.bn2(x))
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW"):
        super().__init__()
        ax = _bn_axis(layout)
        self.bn1 = nn.BatchNorm(axis=ax)
        self.conv1 = nn.Conv2D(channels // 4, 1, 1, use_bias=False,
                               layout=layout)
        self.bn2 = nn.BatchNorm(axis=ax)
        self.conv2 = nn.Conv2D(channels // 4, 3, stride, 1, use_bias=False,
                               layout=layout)
        self.bn3 = nn.BatchNorm(axis=ax)
        self.conv3 = nn.Conv2D(channels, 1, 1, use_bias=False, layout=layout)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def forward(self, x):
        from ... import numpy_extension as npx
        from ...ops import fused as _fused
        fuse = _fused.fusion_enabled()

        def bn_relu(bn, v):
            return bn.fused_forward(v, act_type="relu") if fuse \
                else npx.relu(bn(v))

        residual = x
        x = bn_relu(self.bn1, x)
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = bn_relu(self.bn2, x)
        x = self.conv2(x)
        x = bn_relu(self.bn3, x)
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    """≙ model_zoo/vision/resnet.py ResNetV1."""

    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 layout="NCHW"):
        super().__init__()
        assert len(layers) == len(channels) - 1
        ax = _bn_axis(layout)
        self.features = nn.HybridSequential()
        if thumbnail:
            self.features.add(nn.Conv2D(channels[0], 3, 1, 1, use_bias=False,
                                        layout=layout))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3, use_bias=False,
                                        layout=layout))
            self.features.add(nn.BatchNorm(axis=ax))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=channels[i], layout=layout))
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.output = nn.Dense(classes, in_units=channels[-1])

    @staticmethod
    def _make_layer(block, num_layers, channels, stride, in_channels=0,
                    layout="NCHW"):
        layer = nn.HybridSequential()
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels, layout=layout))
        for _ in range(num_layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels,
                            layout=layout))
        return layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    """≙ model_zoo/vision/resnet.py ResNetV2 (pre-activation)."""

    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 layout="NCHW"):
        super().__init__()
        ax = _bn_axis(layout)
        self.features = nn.HybridSequential()
        self.features.add(nn.BatchNorm(axis=ax, scale=False, center=False))
        if thumbnail:
            self.features.add(nn.Conv2D(channels[0], 3, 1, 1, use_bias=False,
                                        layout=layout))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3, use_bias=False,
                                        layout=layout))
            self.features.add(nn.BatchNorm(axis=ax))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
        in_channels = channels[0]
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=in_channels, layout=layout))
            in_channels = channels[i + 1]
        self.features.add(nn.BatchNorm(axis=ax))
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes, in_units=channels[-1])

    _make_layer = staticmethod(ResNetV1._make_layer)

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


_resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}


def get_resnet(version, num_layers, pretrained=False, root=None, **kwargs):
    block_type, layers, channels = _resnet_spec[num_layers]
    if version == 1:
        block = BasicBlockV1 if block_type == "basic_block" else BottleneckV1
        return _load_pretrained(ResNetV1(block, layers, channels, **kwargs),
                                f"resnet{num_layers}_v1", pretrained, root)
    block = BasicBlockV2 if block_type == "basic_block" else BottleneckV2
    return _load_pretrained(ResNetV2(block, layers, channels, **kwargs),
                            f"resnet{num_layers}_v2", pretrained, root)


def resnet18_v1(**kw): return get_resnet(1, 18, **kw)
def resnet34_v1(**kw): return get_resnet(1, 34, **kw)
def resnet50_v1(**kw): return get_resnet(1, 50, **kw)
def resnet101_v1(**kw): return get_resnet(1, 101, **kw)
def resnet152_v1(**kw): return get_resnet(1, 152, **kw)
def resnet18_v2(**kw): return get_resnet(2, 18, **kw)
def resnet34_v2(**kw): return get_resnet(2, 34, **kw)
def resnet50_v2(**kw): return get_resnet(2, 50, **kw)
def resnet101_v2(**kw): return get_resnet(2, 101, **kw)
def resnet152_v2(**kw): return get_resnet(2, 152, **kw)


# ---------------------------------------------------------------------------
# AlexNet (≙ model_zoo/vision/alexnet.py)
# ---------------------------------------------------------------------------
class AlexNet(HybridBlock):
    def __init__(self, classes=1000):
        super().__init__()
        self.features = nn.HybridSequential()
        self.features.add(nn.Conv2D(64, 11, 4, 2, activation="relu"))
        self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(nn.Conv2D(192, 5, padding=2, activation="relu"))
        self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(nn.Conv2D(384, 3, padding=1, activation="relu"))
        self.features.add(nn.Conv2D(256, 3, padding=1, activation="relu"))
        self.features.add(nn.Conv2D(256, 3, padding=1, activation="relu"))
        self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(nn.Flatten())
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def alexnet(pretrained=False, root=None, **kwargs):
    return _load_pretrained(AlexNet(**kwargs), "alexnet", pretrained, root)


# ---------------------------------------------------------------------------
# VGG (≙ model_zoo/vision/vgg.py)
# ---------------------------------------------------------------------------
class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False):
        super().__init__()
        self.features = nn.HybridSequential()
        for i, num in enumerate(layers):
            for _ in range(num):
                self.features.add(nn.Conv2D(filters[i], 3, padding=1))
                if batch_norm:
                    self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(2, 2))
        self.features.add(nn.Flatten())
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


_vgg_spec = {11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
             13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
             16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
             19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512])}


def get_vgg(num_layers, pretrained=False, root=None, **kwargs):
    layers, filters = _vgg_spec[num_layers]
    bn = "_bn" if kwargs.get("batch_norm") else ""
    return _load_pretrained(VGG(layers, filters, **kwargs),
                            f"vgg{num_layers}{bn}", pretrained, root)


def vgg11(**kw): return get_vgg(11, **kw)
def vgg13(**kw): return get_vgg(13, **kw)
def vgg16(**kw): return get_vgg(16, **kw)
def vgg19(**kw): return get_vgg(19, **kw)
def vgg11_bn(**kw): return get_vgg(11, batch_norm=True, **kw)
def vgg13_bn(**kw): return get_vgg(13, batch_norm=True, **kw)
def vgg16_bn(**kw): return get_vgg(16, batch_norm=True, **kw)
def vgg19_bn(**kw): return get_vgg(19, batch_norm=True, **kw)


# ---------------------------------------------------------------------------
# SqueezeNet (≙ model_zoo/vision/squeezenet.py)
# ---------------------------------------------------------------------------
def _fire(squeeze, expand):
    out = nn.HybridConcatenate(axis=1)
    left = nn.HybridSequential()
    right = nn.HybridSequential()
    out_pre = nn.HybridSequential()
    out_pre.add(nn.Conv2D(squeeze, 1, activation="relu"))
    left.add(nn.Conv2D(expand, 1, activation="relu"))
    right.add(nn.Conv2D(expand, 3, padding=1, activation="relu"))
    out.add(left)
    out.add(right)
    wrap = nn.HybridSequential()
    wrap.add(out_pre)
    wrap.add(out)
    return wrap


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000):
        super().__init__()
        if version not in ("1.0", "1.1"):
            raise MXNetError("version must be 1.0 or 1.1")
        self.features = nn.HybridSequential()
        if version == "1.0":
            self.features.add(nn.Conv2D(96, 7, 2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_fire(16, 64))
            self.features.add(_fire(16, 64))
            self.features.add(_fire(32, 128))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_fire(32, 128))
            self.features.add(_fire(48, 192))
            self.features.add(_fire(48, 192))
            self.features.add(_fire(64, 256))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_fire(64, 256))
        else:
            self.features.add(nn.Conv2D(64, 3, 2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_fire(16, 64))
            self.features.add(_fire(16, 64))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_fire(32, 128))
            self.features.add(_fire(32, 128))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_fire(48, 192))
            self.features.add(_fire(48, 192))
            self.features.add(_fire(64, 256))
            self.features.add(_fire(64, 256))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.HybridSequential()
        self.output.add(nn.Conv2D(classes, 1, activation="relu"))
        self.output.add(nn.GlobalAvgPool2D())
        self.output.add(nn.Flatten())

    def forward(self, x):
        return self.output(self.features(x))


def squeezenet1_0(pretrained=False, root=None, **kw):
    return _load_pretrained(SqueezeNet("1.0", **kw), "squeezenet1.0",
                            pretrained, root)


def squeezenet1_1(pretrained=False, root=None, **kw):
    return _load_pretrained(SqueezeNet("1.1", **kw), "squeezenet1.1",
                            pretrained, root)


# ---------------------------------------------------------------------------
# DenseNet (≙ model_zoo/vision/densenet.py)
# ---------------------------------------------------------------------------
class _DenseLayerConcat(HybridBlock):
    def __init__(self, growth_rate, bn_size, dropout):
        super().__init__()
        self.body = nn.HybridSequential()
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(bn_size * growth_rate, 1, use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(growth_rate, 3, padding=1, use_bias=False))
        if dropout:
            self.body.add(nn.Dropout(dropout))

    def forward(self, x):
        from ... import numpy as mxnp
        return mxnp.concatenate([x, self.body(x)], axis=1)


def _make_dense_block(num_layers, bn_size, growth_rate, dropout):
    out = nn.HybridSequential()
    for _ in range(num_layers):
        out.add(_DenseLayerConcat(growth_rate, bn_size, dropout))
    return out


def _make_transition(num_output_features):
    out = nn.HybridSequential()
    out.add(nn.BatchNorm())
    out.add(nn.Activation("relu"))
    out.add(nn.Conv2D(num_output_features, 1, use_bias=False))
    out.add(nn.AvgPool2D(2, 2))
    return out


class DenseNet(HybridBlock):
    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000):
        super().__init__()
        self.features = nn.HybridSequential()
        self.features.add(nn.Conv2D(num_init_features, 7, 2, 3,
                                    use_bias=False))
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.MaxPool2D(3, 2, 1))
        num_features = num_init_features
        for i, num_layers in enumerate(block_config):
            self.features.add(_make_dense_block(num_layers, bn_size,
                                                growth_rate, dropout))
            num_features += num_layers * growth_rate
            if i != len(block_config) - 1:
                num_features //= 2
                self.features.add(_make_transition(num_features))
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.AvgPool2D(7))
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


_densenet_spec = {121: (64, 32, [6, 12, 24, 16]),
                  161: (96, 48, [6, 12, 36, 24]),
                  169: (64, 32, [6, 12, 32, 32]),
                  201: (64, 32, [6, 12, 48, 32])}


def get_densenet(num_layers, pretrained=False, root=None, **kwargs):
    init_f, growth, cfg = _densenet_spec[num_layers]
    return _load_pretrained(DenseNet(init_f, growth, cfg, **kwargs),
                            f"densenet{num_layers}", pretrained, root)


def densenet121(**kw): return get_densenet(121, **kw)
def densenet161(**kw): return get_densenet(161, **kw)
def densenet169(**kw): return get_densenet(169, **kw)
def densenet201(**kw): return get_densenet(201, **kw)


# ---------------------------------------------------------------------------
# MobileNet v1/v2 (≙ model_zoo/vision/mobilenet.py)
# ---------------------------------------------------------------------------
def _add_conv(out, channels=1, kernel=1, stride=1, pad=0, num_group=1,
              active=True, relu6=False):
    out.add(nn.Conv2D(channels, kernel, stride, pad, groups=num_group,
                      use_bias=False))
    out.add(nn.BatchNorm())
    if active:
        out.add(nn.Activation("relu") if not relu6 else _ReLU6())


class _ReLU6(HybridBlock):
    def forward(self, x):
        from ... import numpy as mxnp
        return mxnp.clip(x, 0, 6)


def _add_conv_dw(out, dw_channels, channels, stride, relu6=False):
    _add_conv(out, dw_channels, 3, stride, 1, num_group=dw_channels,
              relu6=relu6)
    _add_conv(out, channels, relu6=relu6)


class LinearBottleneck(HybridBlock):
    def __init__(self, in_channels, channels, t, stride):
        super().__init__()
        self.use_shortcut = stride == 1 and in_channels == channels
        self.out = nn.HybridSequential()
        _add_conv(self.out, in_channels * t, relu6=True)
        _add_conv(self.out, in_channels * t, 3, stride, 1,
                  num_group=in_channels * t, relu6=True)
        _add_conv(self.out, channels, active=False)

    def forward(self, x):
        out = self.out(x)
        if self.use_shortcut:
            out = out + x
        return out


class MobileNet(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000):
        super().__init__()
        self.features = nn.HybridSequential()
        _add_conv(self.features, int(32 * multiplier), 3, 2, 1)
        dw_channels = [int(x * multiplier) for x in
                       [32, 64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024]]
        channels = [int(x * multiplier) for x in
                    [64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024] * 2]
        strides = [1, 2, 1, 2, 1, 2, 1, 1, 1, 1, 1, 2, 1]
        for dwc, c, s in zip(dw_channels, channels, strides):
            _add_conv_dw(self.features, dwc, c, s)
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


class MobileNetV2(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000):
        super().__init__()
        self.features = nn.HybridSequential()
        _add_conv(self.features, int(32 * multiplier), 3, 2, 1, relu6=True)
        in_channels_group = [int(x * multiplier) for x in
                             [32] + [16] + [24] * 2 + [32] * 3 + [64] * 4
                             + [96] * 3 + [160] * 3]
        channels_group = [int(x * multiplier) for x in
                          [16] + [24] * 2 + [32] * 3 + [64] * 4 + [96] * 3
                          + [160] * 3 + [320]]
        ts = [1] + [6] * 16
        strides = [1, 2, 1, 2, 1, 1, 2, 1, 1, 1, 1, 1, 1, 2, 1, 1, 1]
        for in_c, c, t, s in zip(in_channels_group, channels_group, ts,
                                 strides):
            self.features.add(LinearBottleneck(in_c, c, t, s))
        last_channels = int(1280 * multiplier) if multiplier > 1.0 else 1280
        _add_conv(self.features, last_channels, relu6=True)
        self.features.add(nn.GlobalAvgPool2D())
        self.output = nn.HybridSequential()
        self.output.add(nn.Conv2D(classes, 1, use_bias=False))
        self.output.add(nn.Flatten())

    def forward(self, x):
        return self.output(self.features(x))


def mobilenet1_0(pretrained=False, root=None, **kw):
    return _load_pretrained(MobileNet(1.0, **kw), "mobilenet1.0", pretrained, root)


def mobilenet0_75(pretrained=False, root=None, **kw):
    return _load_pretrained(MobileNet(0.75, **kw), "mobilenet0.75", pretrained, root)


def mobilenet0_5(pretrained=False, root=None, **kw):
    return _load_pretrained(MobileNet(0.5, **kw), "mobilenet0.5", pretrained, root)


def mobilenet0_25(pretrained=False, root=None, **kw):
    return _load_pretrained(MobileNet(0.25, **kw), "mobilenet0.25", pretrained, root)


def mobilenet_v2_1_0(pretrained=False, root=None, **kw):
    return _load_pretrained(MobileNetV2(1.0, **kw), "mobilenetv2_1.0", pretrained, root)


def mobilenet_v2_0_75(pretrained=False, root=None, **kw):
    return _load_pretrained(MobileNetV2(0.75, **kw), "mobilenetv2_0.75", pretrained, root)


def mobilenet_v2_0_5(pretrained=False, root=None, **kw):
    return _load_pretrained(MobileNetV2(0.5, **kw), "mobilenetv2_0.5", pretrained, root)


def mobilenet_v2_0_25(pretrained=False, root=None, **kw):
    return _load_pretrained(MobileNetV2(0.25, **kw), "mobilenetv2_0.25", pretrained, root)


# ---------------------------------------------------------------------------
# Inception v3 (≙ model_zoo/vision/inception.py)
# ---------------------------------------------------------------------------
def _conv_bn(channels, kernel, stride=1, pad=0):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(channels, kernel, stride, pad, use_bias=False))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


class _InceptionBranch(HybridBlock):
    """Concat of parallel branches, each a HybridSequential."""

    def __init__(self, *branches):
        super().__init__()
        for b in branches:
            self.register_child(b)

    def forward(self, x):
        from ... import numpy as mxnp
        return mxnp.concatenate([b(x) for b in self._children.values()],
                                axis=1)


def _branch(*specs):
    out = nn.HybridSequential()
    for spec in specs:
        if spec[0] == "pool_avg":
            out.add(nn.AvgPool2D(3, 1, 1))
        elif spec[0] == "pool_max":
            out.add(nn.MaxPool2D(spec[1], spec[2]))
        else:
            channels, kernel, stride, pad = spec
            out.add(_conv_bn(channels, kernel, stride, pad))
    return out


def _make_A(pool_features):
    return _InceptionBranch(
        _branch((64, 1, 1, 0)),
        _branch((48, 1, 1, 0), (64, 5, 1, 2)),
        _branch((64, 1, 1, 0), (96, 3, 1, 1), (96, 3, 1, 1)),
        _branch(("pool_avg",), (pool_features, 1, 1, 0)))


def _make_B():
    return _InceptionBranch(
        _branch((384, 3, 2, 0)),
        _branch((64, 1, 1, 0), (96, 3, 1, 1), (96, 3, 2, 0)),
        _branch(("pool_max", 3, 2)))


def _make_C(channels_7x7):
    c = channels_7x7
    return _InceptionBranch(
        _branch((192, 1, 1, 0)),
        _branch((c, 1, 1, 0), (c, (1, 7), 1, (0, 3)), (192, (7, 1), 1, (3, 0))),
        _branch((c, 1, 1, 0), (c, (7, 1), 1, (3, 0)), (c, (1, 7), 1, (0, 3)),
                (c, (7, 1), 1, (3, 0)), (192, (1, 7), 1, (0, 3))),
        _branch(("pool_avg",), (192, 1, 1, 0)))


def _make_D():
    return _InceptionBranch(
        _branch((192, 1, 1, 0), (320, 3, 2, 0)),
        _branch((192, 1, 1, 0), (192, (1, 7), 1, (0, 3)),
                (192, (7, 1), 1, (3, 0)), (192, 3, 2, 0)),
        _branch(("pool_max", 3, 2)))


class _SplitConcat(HybridBlock):
    """branch that splits into two convs then concats (inception E)."""

    def __init__(self, pre_specs, post_a, post_b):
        super().__init__()
        self.pre = _branch(*pre_specs) if pre_specs else None
        self.post_a = _conv_bn(*post_a)
        self.post_b = _conv_bn(*post_b)

    def forward(self, x):
        from ... import numpy as mxnp
        if self.pre is not None:
            x = self.pre(x)
        return mxnp.concatenate([self.post_a(x), self.post_b(x)], axis=1)


def _make_E():
    return _InceptionBranch(
        _branch((320, 1, 1, 0)),
        _SplitConcat([(384, 1, 1, 0)],
                     (384, (1, 3), 1, (0, 1)), (384, (3, 1), 1, (1, 0))),
        _SplitConcat([(448, 1, 1, 0), (384, 3, 1, 1)],
                     (384, (1, 3), 1, (0, 1)), (384, (3, 1), 1, (1, 0))),
        _branch(("pool_avg",), (192, 1, 1, 0)))


class Inception3(HybridBlock):
    """≙ model_zoo/vision/inception.py Inception3 (input 299x299)."""

    def __init__(self, classes=1000):
        super().__init__()
        self.features = nn.HybridSequential()
        self.features.add(_conv_bn(32, 3, 2, 0))
        self.features.add(_conv_bn(32, 3, 1, 0))
        self.features.add(_conv_bn(64, 3, 1, 1))
        self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(_conv_bn(80, 1, 1, 0))
        self.features.add(_conv_bn(192, 3, 1, 0))
        self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(_make_A(32))
        self.features.add(_make_A(64))
        self.features.add(_make_A(64))
        self.features.add(_make_B())
        self.features.add(_make_C(128))
        self.features.add(_make_C(160))
        self.features.add(_make_C(160))
        self.features.add(_make_C(192))
        self.features.add(_make_D())
        self.features.add(_make_E())
        self.features.add(_make_E())
        self.features.add(nn.AvgPool2D(8))
        self.features.add(nn.Dropout(0.5))
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def inception_v3(pretrained=False, root=None, **kw):
    return _load_pretrained(Inception3(**kw), "inceptionv3", pretrained, root)


# ---------------------------------------------------------------------------
# registry (≙ model_zoo/vision/__init__.py get_model)
# ---------------------------------------------------------------------------
_models = {
    "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
    "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
    "resnet152_v1": resnet152_v1,
    "resnet18_v2": resnet18_v2, "resnet34_v2": resnet34_v2,
    "resnet50_v2": resnet50_v2, "resnet101_v2": resnet101_v2,
    "resnet152_v2": resnet152_v2,
    "alexnet": alexnet,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
    "vgg19_bn": vgg19_bn,
    "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
    "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
    "mobilenetv2_1.0": mobilenet_v2_1_0, "mobilenetv2_0.75": mobilenet_v2_0_75,
    "mobilenetv2_0.5": mobilenet_v2_0_5, "mobilenetv2_0.25": mobilenet_v2_0_25,
    "inceptionv3": inception_v3,
}


def get_model(name, **kwargs):
    """≙ gluon.model_zoo.vision.get_model."""
    name = name.lower()
    if name not in _models:
        raise MXNetError(
            f"model {name!r} is not in the zoo ({sorted(_models)})")
    return _models[name](**kwargs)
