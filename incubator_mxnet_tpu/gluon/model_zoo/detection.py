"""Detection model zoo: SSD-300/VGG16 (≙ the reference's example/ssd
model definition, symbol/symbol_vgg16_reduced.py + the gluon-cv
ssd_300_vgg16_atrous preset) built on this framework's real multibox op
tail (multibox_prior/target/detection, ops/contrib.py).

TPU-native notes: NHWC-friendly convs via layout=, every head is a plain
HybridBlock (one jit for the whole detector under hybridize), anchors are
trace-time constants folded into the program.
"""
from __future__ import annotations

import numpy as _np

from ... import numpy_extension as npx
from ... import numpy as mxnp
from .. import nn
from ..block import HybridBlock

__all__ = ["SSD300", "ssd_300_vgg16", "ssd_anchor_sizes"]

# SSD paper scales for 300px input: s_min=0.2, s_max=0.9 over 6 maps,
# plus the geometric-mean extra size per map. Feature maps at 300px:
# 38, 19, 10, 5, 3, 1 (asserted by the canonical 8732-anchor test).
_RATIOS = ((1, 2, 0.5),
           (1, 2, 0.5, 3, 1.0 / 3),
           (1, 2, 0.5, 3, 1.0 / 3),
           (1, 2, 0.5, 3, 1.0 / 3),
           (1, 2, 0.5),
           (1, 2, 0.5))


def ssd_anchor_sizes(num_maps=6, s_min=0.2, s_max=0.9):
    """Per-map (s_k, sqrt(s_k * s_{k+1})) size pairs (SSD paper eq. 4)."""
    scales = [0.1] + [
        s_min + (s_max - s_min) * k / (num_maps - 1)
        for k in range(num_maps)]
    return [(scales[k], float(_np.sqrt(scales[k] * scales[k + 1])))
            for k in range(num_maps)]


def _vgg16_reduced(layout):
    """VGG16 through conv4_3 (the 38x38 SSD feature): three pooled stages
    then the conv4 block WITHOUT its pool (that pool opens the conv5
    branch). pool3 uses ceil (75 -> 38) — the SSD VGG16 variant's one
    quirk, required for the canonical 8732-anchor layout."""
    net = nn.HybridSequential()
    for bi, (blocks, ch) in enumerate([(2, 64), (2, 128), (3, 256)]):
        for _ in range(blocks):
            net.add(nn.Conv2D(ch, 3, padding=1, activation="relu",
                              layout=layout))
        net.add(nn.MaxPool2D(2, 2, layout=layout, ceil_mode=(bi == 2)))
    for _ in range(3):   # conv4_1..conv4_3 -> 38x38x512
        net.add(nn.Conv2D(512, 3, padding=1, activation="relu",
                          layout=layout))
    return net


class SSD300(HybridBlock):
    """SSD with a VGG16-reduced backbone at 300x300 (8732 anchors).

    forward(x) -> (anchors (1, 8732, 4), cls_preds (B, 8732, C+1),
    loc_preds (B, 8732*4)); `detect(x)` runs softmax + multibox_detection
    (NMS inside) and returns (B, N, 6) [cls, score, x1, y1, x2, y2].
    """

    def __init__(self, classes=20, layout="NCHW"):
        super().__init__()
        self._classes = classes
        self._layout = layout
        self._ch_axis = 1 if layout == "NCHW" else 3
        sizes = ssd_anchor_sizes()
        self._sizes = sizes
        self._num_anchors = [len(s) + len(r) - 1
                             for s, r in zip(sizes, _RATIOS)]

        self.stem = _vgg16_reduced(layout)          # -> 38x38x512
        self.conv5 = nn.HybridSequential()
        self.conv5.add(nn.MaxPool2D(2, 2, layout=layout))   # pool4: 19
        for _ in range(3):
            self.conv5.add(nn.Conv2D(512, 3, padding=1, activation="relu",
                                     layout=layout))
        self.conv5.add(nn.MaxPool2D(3, 1, padding=1,
                                    layout=layout))         # pool5: 19
        self.fc = nn.HybridSequential()
        self.fc.add(nn.Conv2D(1024, 3, padding=6, dilation=6,
                              activation="relu", layout=layout),  # fc6
                    nn.Conv2D(1024, 1, activation="relu",
                              layout=layout))                     # fc7
        # extra feature layers: 10, 5, 3, 1
        self.extras = nn.HybridSequential()
        for mid, out, stride, pad in ((256, 512, 2, 1), (128, 256, 2, 1),
                                      (128, 256, 1, 0), (128, 256, 1, 0)):
            blk = nn.HybridSequential()
            blk.add(nn.Conv2D(mid, 1, activation="relu", layout=layout),
                    nn.Conv2D(out, 3, strides=stride, padding=pad,
                              activation="relu", layout=layout))
            self.extras.add(blk)

        self.cls_heads = nn.HybridSequential()
        self.loc_heads = nn.HybridSequential()
        for na in self._num_anchors:
            self.cls_heads.add(nn.Conv2D(na * (classes + 1), 3, padding=1,
                                         layout=layout))
            self.loc_heads.add(nn.Conv2D(na * 4, 3, padding=1,
                                         layout=layout))

    # ------------------------------------------------------------------
    def _flatten_pred(self, p, per_anchor):
        # (B, C, H, W) or (B, H, W, C) -> (B, H*W*na, per_anchor)
        if self._layout == "NCHW":
            p = p.transpose(0, 2, 3, 1)
        b = p.shape[0]
        return p.reshape(b, -1, per_anchor)

    def forward(self, x):
        feats = []
        h = self.stem(x)
        feats.append(h)                       # 38
        h = self.conv5(h)
        h = self.fc(h)
        feats.append(h)                       # 19
        for blk in self.extras:
            h = blk(h)
            feats.append(h)                   # 10, 5, 3, 1
        anchors, cls_preds, loc_preds = [], [], []
        for i, f in enumerate(feats):
            anchors.append(npx.multibox_prior(
                f, sizes=self._sizes[i], ratios=_RATIOS[i],
                layout=self._layout))
            cls_preds.append(self._flatten_pred(
                self.cls_heads[i](f), self._classes + 1))
            loc_preds.append(self._flatten_pred(self.loc_heads[i](f), 4))
        anchors = mxnp.concatenate(anchors, axis=1)
        cls_preds = mxnp.concatenate(cls_preds, axis=1)
        loc_preds = mxnp.concatenate(loc_preds, axis=1)
        b = loc_preds.shape[0]
        return anchors, cls_preds, loc_preds.reshape(b, -1)

    def detect(self, x, nms_threshold=0.45, threshold=0.01):
        anchors, cls_preds, loc_preds = self(x)
        probs = npx.softmax(cls_preds, axis=-1).transpose(0, 2, 1)
        return npx.multibox_detection(
            probs, loc_preds, anchors, nms_threshold=nms_threshold,
            threshold=threshold)

    def targets(self, anchors, labels, cls_preds,
                negative_mining_ratio=3.0):
        """(loc_target, loc_mask, cls_target) ≙ MultiBoxTarget."""
        return npx.multibox_target(
            anchors, labels, cls_preds.transpose(0, 2, 1),
            negative_mining_ratio=negative_mining_ratio)


def ssd_300_vgg16(classes=20, layout="NCHW", pretrained=False, root=None):
    """The SSD-300/VGG16 preset (≙ gluon-cv ssd_300_vgg16_atrous)."""
    net = SSD300(classes=classes, layout=layout)
    if pretrained:
        from .model_store import load_pretrained
        net.initialize()
        x = mxnp.zeros((1, 3, 300, 300) if layout == "NCHW"
                       else (1, 300, 300, 3))
        net(x)
        load_pretrained(net, "ssd_300_vgg16", root)
    return net
