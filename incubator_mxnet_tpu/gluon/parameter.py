"""gluon.Parameter — a trainable tensor with deferred initialization.

Reference: python/mxnet/gluon/parameter.py:47 (Parameter: grad_req, lr_mult,
wd_mult, deferred init via shape with unknown dims, data()/grad()/set_data,
cast, zero_grad; DeferredInitializationError).

TPU-native notes: a Parameter owns ONE NDArray (SPMD sharding over a mesh
replaces the reference's per-device `list_data()` replication — see
mx.parallel). `list_data()`/`list_grad()` return 1-element lists for API
compatibility. Sparse stypes (`row_sparse`) are rejected: no sparse storage
on TPU (SURVEY §7 hard-part #4).
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError, name_to_dtype
from .. import initializer as init_mod

__all__ = ["Parameter", "Constant", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its shape is known
    (≙ gluon.parameter.DeferredInitializationError)."""


def _shape_known(shape):
    return shape is not None and all(s > 0 for s in shape)


class Parameter:
    """A trainable parameter (≙ gluon.Parameter, parameter.py:47)."""

    def __init__(self, shape=None, dtype="float32", init=None,
                 grad_req="write", lr_mult=1.0, wd_mult=1.0,
                 allow_deferred_init=True, differentiable=True,
                 stype="default", grad_stype="default", name=None):
        if stype != "default" or grad_stype != "default":
            raise MXNetError(
                "sparse parameter storage (row_sparse/csr) is unsupported on "
                "TPU; use dense parameters (reference: parameter.py stype)")
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.init = init
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.allow_deferred_init = allow_deferred_init
        self._grad_req = grad_req if differentiable else "null"
        self._data = None          # NDArray
        self._deferred_init = None  # (init, device, default_init) waiting for shape
        self._name = name or "param"
        self._structural_name = None  # set by Block registration

    # ------------------------------------------------------------------
    @property
    def name(self):
        return self._structural_name or self._name

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if new_shape is None:
            return
        if isinstance(new_shape, int):
            new_shape = (new_shape,)
        if self._shape is not None:
            if len(self._shape) != len(new_shape) or any(
                    s not in (0, -1, n) for s, n in zip(self._shape, new_shape)):
                raise MXNetError(
                    f"inferred shape {new_shape} incompatible with declared "
                    f"shape {self._shape} for parameter {self.name}")
        self._shape = tuple(new_shape)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {req!r}")
        self._grad_req = req
        if self._data is not None:
            self._data.attach_grad(req)

    # ------------------------------------------------------------------
    # initialization
    # ------------------------------------------------------------------
    def initialize(self, init=None, device=None, default_init=None,
                   force_reinit=False, ctx=None):
        """Materialize the parameter (≙ Parameter.initialize).

        With unknown shape dims the init is deferred until shape inference
        (HybridBlock.infer_shape) fills them in."""
        device = device or ctx
        if self._data is not None and not force_reinit:
            return
        if default_init is None:
            default_init = init_mod.Uniform()
        if not _shape_known(self._shape):
            if not self.allow_deferred_init:
                raise MXNetError(
                    f"cannot initialize parameter {self.name}: shape "
                    f"{self._shape} unknown and deferred init not allowed")
            self._deferred_init = (init, device, default_init)
            return
        self._finish_init(init, device, default_init)

    def _finish_init(self, init, device, default_init):
        import zlib
        from ..ndarray import NDArray
        from .. import random as _random
        # stable per-parameter seed: crc32 (NOT python hash(), which is
        # salted per process and would break cross-run reproducibility)
        name_key = zlib.crc32(self.name.encode("utf-8"))
        rng = _np.random.default_rng(
            (_random._global["seed"] + name_key) & 0x7FFFFFFF)
        initializer = init_mod.create(
            init if init is not None else (self.init if self.init is not None
                                           else default_init))
        dtype = name_to_dtype(self.dtype)
        value = initializer(init_mod.InitDesc(self.name), self._shape,
                            _np.float32, rng)
        value = _np.asarray(value, dtype=dtype)
        self._data = NDArray(value, device=device)
        self._data.attach_grad(self._grad_req)
        self._deferred_init = None

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        if not _shape_known(self._shape):
            raise DeferredInitializationError(
                f"parameter {self.name} shape {self._shape} still unknown")
        init, device, default_init = self._deferred_init
        self._finish_init(init, device, default_init)

    # ------------------------------------------------------------------
    # access
    # ------------------------------------------------------------------
    def _check_initialized(self):
        if self._data is not None:
            return
        if self._deferred_init is not None:
            raise DeferredInitializationError(
                f"parameter {self.name} has deferred init pending shape "
                f"inference (shape={self._shape})")
        raise MXNetError(
            f"parameter {self.name} has not been initialized; call "
            f".initialize() on the Block")

    def data(self, device=None, ctx=None):
        """The parameter value on `device` (≙ Parameter.data)."""
        self._check_initialized()
        return self._data

    def list_data(self):
        self._check_initialized()
        return [self._data]

    def grad(self, device=None, ctx=None):
        self._check_initialized()
        if self._grad_req == "null":
            raise MXNetError(
                f"cannot get gradient of parameter {self.name}: grad_req='null'")
        return self._data.grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        self._check_initialized()
        return [self._data.device]

    list_device = list_ctx

    def set_data(self, data):
        """Replace the value on all devices (≙ Parameter.set_data)."""
        from ..ndarray import NDArray, _as_nd
        data = _as_nd(data)
        if self._data is None:
            # setting data also resolves a deferred init
            self.shape = data.shape
            self._data = data.copy()
            self._data.attach_grad(self._grad_req)
            self._deferred_init = None
            return
        if tuple(data.shape) != tuple(self._data.shape):
            raise MXNetError(
                f"set_data shape {data.shape} != parameter shape "
                f"{self._data.shape} for {self.name}")
        self._data[:] = data

    def zero_grad(self):
        """Zero the gradient buffer (≙ Parameter.zero_grad / reset_arrays)."""
        if self._data is not None and self._data.grad is not None:
            self._data.grad[:] = 0

    def cast(self, dtype):
        """Cast value (and grad buffer) to dtype (≙ Parameter.cast)."""
        self.dtype = dtype
        if self._data is not None:
            self._data = self._data.astype(dtype)
            self._data.attach_grad(self._grad_req)

    def reset_ctx(self, device):
        if self._data is not None:
            self._data = self._data.as_in_context(device)
            self._data.attach_grad(self._grad_req)

    reset_device = reset_ctx

    @property
    def var_entry(self):
        return self._data

    def __repr__(self):
        return (f"Parameter {self.name} (shape={self._shape}, "
                f"dtype={self.dtype})")


class Constant(Parameter):
    """Non-trainable parameter holding a fixed value (≙ gluon.Constant)."""

    def __init__(self, value, name=None):
        if not isinstance(value, _np.ndarray):
            value = _np.asarray(value, dtype=_np.float32)
        self.value = value
        super().__init__(shape=value.shape,
                         dtype=str(value.dtype),
                         init=init_mod.Constant(0.0), grad_req="null",
                         name=name or "const")
        self.init = _ConstInit(value)


class _ConstInit(init_mod.Initializer):
    def __init__(self, value):
        super().__init__()
        self._value = value

    def __call__(self, name, shape, dtype, rng):
        return self._value.astype(dtype, copy=False)
