"""mx.context — legacy Context API (≙ python/mxnet/context.py).

The reference deprecated Context in favor of Device in 2.0; both names are
kept here. Devices map to PJRT devices (tpu ≙ gpu slots)."""
from .device import (Device, Context, cpu, gpu, tpu, num_gpus, num_tpus,
                     current_device, current_context, device_memory_info,
                     gpu_memory_info)

__all__ = ["Device", "Context", "cpu", "gpu", "tpu", "num_gpus", "num_tpus",
           "current_device", "current_context", "device_memory_info",
           "gpu_memory_info"]
