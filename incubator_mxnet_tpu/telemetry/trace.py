"""mx.telemetry.trace — end-to-end request tracing + crash flight recorder.

Two halves, one module, because they share the same question — "what was
this process doing?" — asked live (tracing) and post-mortem (flight
recorder):

  * **Trace context.** A `TraceContext` is the (trace_id, span_id, name,
    parent) tuple that makes spans recorded on different threads — or in
    different processes — reconstruct into ONE request tree. The active
    context lives in a `contextvars.ContextVar`, so `telemetry.span`
    nesting works without an explicit stack, and crossing an execution
    boundary is two calls:

        ctx = trace.current_context()        # capture on the producer side
        token = trace.attach(ctx)            # restore on the consumer side
        ...
        trace.detach(token)

    Process boundaries serialize through `ctx.to_dict()` /
    `TraceContext.from_dict(d)` (~100 bytes of JSON — a serve `Request`,
    a shm-worker command, an RPC header). `MXNET_TRACE_SAMPLE` (0..1,
    default 1) head-samples ROOT trace creation: a sampled-out request
    still serves, still counts in every metric, just mints no trace ids.

  * **Flight recorder.** A bounded in-memory ring (`MXNET_FLIGHTREC_EVENTS`
    entries, default 512) of recent structured events — span opens/closes,
    fault injections, worker restarts, collective timeouts, nonfinite
    skips, overload sheds — appended from `telemetry.span`, `record_span`,
    and `fault._log_event`, so every subsystem that already logs feeds the
    black box for free. `flightrec_dump()` snapshots the ring as one JSON
    file (wired into the fault watchdog, elastic `StragglerTimeout`, serve
    overload shedding, bench's phase crash handler, and an atexit/SIGTERM
    hook). For SIGKILL parity — where no handler can run — setting
    `MXNET_FLIGHTREC_DIR` additionally SPOOLS each event as one flushed
    JSONL line to `<dir>/flightrec-<pid>.jsonl`: a `write()` that reached
    the kernel survives the process, so a dead worker's spool tail names
    the in-flight span/step/rank (`tools/crashtest.py --flightrec` proves
    it under a real SIGKILL).

No jax, no numpy: this module stays importable on the mxlint/bench
orchestrator path like the registry it feeds.
"""
from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque

from ..base import _register_env, get_env
from .registry import REGISTRY

__all__ = [
    "TraceContext", "current_context", "attach", "detach", "attached",
    "new_context", "child_context", "FlightRecorder", "FLIGHTREC",
    "flightrec_record", "flightrec_dump", "flightrec_maybe_dump",
    "flightrec_events", "install_crash_hooks",
]

_register_env("MXNET_TRACE_SAMPLE", float, 1.0,
              "Head-sampling rate (0..1) for NEW root trace contexts "
              "(serve requests, root spans). Sampled-out work still runs "
              "and still counts in every metric; it just mints no trace "
              "ids. Deterministic 1-in-k, not random")
_register_env("MXNET_FLIGHTREC_EVENTS", int, 512,
              "Flight-recorder ring capacity (recent events retained "
              "in memory; older events count in flightrec.dropped)")
_register_env("MXNET_FLIGHTREC_DIR", str, None,
              "When set: spool every flight-recorder event as a flushed "
              "JSONL line to <dir>/flightrec-<pid>.jsonl (SIGKILL-durable "
              "black box) and enable the watchdog/atexit dump files there")

# -- metrics (docs/OBSERVABILITY.md catalog; exercised in tests) ------------
# lock-free GIL-atomic stats groups, NOT registry Counter objects: a mint
# (and its sampled-out twin) happens per REQUEST on every submitter
# thread at once, and a registry-lock `inc()` measured 15us under
# 16-thread contention (lock convoy) vs ~0.1us for a plain dict add —
# the documented DISPATCH_STATS tradeoff (rare lost increments are
# acceptable for diagnostics counters; snapshot(reset) stays atomic
# under the group's private lock). Snapshot names are identical to the
# object-metric form: `trace.traces`, `flightrec.events`, ...
TRACE_STATS = REGISTRY.stats_group("trace", {
    "traces": 0,        # root trace contexts minted
    "spans": 0,         # spans recorded carrying a trace context
    "attaches": 0,      # contexts attached across a thread/process hop
    "sampled_out": 0,   # root traces skipped by MXNET_TRACE_SAMPLE
}, lock=None, help="request-tracing counters (lock-free hot path)")
FLIGHTREC_STATS = REGISTRY.stats_group("flightrec", {
    "events": 0,        # events appended to the flight-recorder ring
    "dropped": 0,       # ring-capacity evictions (oldest overwritten)
    "dumps": 0,         # black-box dump files written
}, lock=None, help="flight-recorder counters")


# ---------------------------------------------------------------------------
# ids + context
# ---------------------------------------------------------------------------
_ids = itertools.count(1)
# os.getpid() is a real syscall (~0.5us) and ids mint per request: cache
# the prefix, refreshed in fork children so ids stay process-unique
_pid_prefix = [f"{os.getpid():x}-"]
if hasattr(os, "register_at_fork"):
    os.register_at_fork(
        after_in_child=lambda: _pid_prefix.__setitem__(
            0, f"{os.getpid():x}-"))


def _new_id():
    # pid-prefixed monotonic counter: unique within a process tree without
    # randomness (scripts/workflows stay deterministic and replayable)
    return _pid_prefix[0] + format(next(_ids), "x")


class TraceContext:
    """One node of a request tree: immutable, ~free to mint, serializable.

    `trace_id` names the whole request; `span_id` this node; `parent_*`
    the enclosing node (None at the root). Spans recorded under an
    attached context stamp all three into their Chrome-trace args, so a
    viewer (or a test) can reassemble the cross-thread tree."""

    __slots__ = ("trace_id", "span_id", "name", "parent_span_id",
                 "parent_name")

    def __init__(self, trace_id, span_id, name, parent_span_id=None,
                 parent_name=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.name = name
        self.parent_span_id = parent_span_id
        self.parent_name = parent_name

    def to_dict(self):
        """JSON-safe form for process boundaries (serve requests, worker
        commands). `from_dict` is the inverse."""
        d = {"trace_id": self.trace_id, "span_id": self.span_id,
             "name": self.name}
        if self.parent_span_id is not None:
            d["parent_span_id"] = self.parent_span_id
        if self.parent_name is not None:
            d["parent_name"] = self.parent_name
        return d

    @classmethod
    def from_dict(cls, d):
        if not d or "trace_id" not in d:
            return None
        return cls(d["trace_id"], d.get("span_id"), d.get("name"),
                   d.get("parent_span_id"), d.get("parent_name"))

    def __repr__(self):
        return (f"TraceContext({self.name!r}, trace={self.trace_id}, "
                f"span={self.span_id}, parent={self.parent_name!r})")


_CTX = contextvars.ContextVar("mx_trace_ctx", default=None)

# sentinel pushed by a root span whose trace was SAMPLED OUT: descendants
# must inherit the decision (no ids, no fresh root per inner span) instead
# of each rolling their own sampling draw and minting orphan mid-request
# roots. `current_context()` renders it as None; only the span class and
# request_root look at the raw value.
NOT_SAMPLED = TraceContext("", "", "<not-sampled>")

# deterministic 1-in-k head sampler (no random: replayable, lint-clean)
_sample_lock = threading.Lock()
_sample_n = [0]
# root-mint env read: cache keyed on the RAW env string, so a
# monkeypatched value still takes effect immediately (mints only happen
# while a collector is active, so this read is off the default hot path)
_sample_memo = [object(), 1.0]


def _sample_rate():
    raw = os.environ.get("MXNET_TRACE_SAMPLE")
    if raw != _sample_memo[0]:
        try:
            _sample_memo[1] = 1.0 if raw is None else float(raw)
        except ValueError:
            _sample_memo[1] = 1.0
        _sample_memo[0] = raw
    return _sample_memo[1]


def _sampled():
    rate = _sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    with _sample_lock:
        _sample_n[0] += 1
        n = _sample_n[0]
    return int(n * rate) != int((n - 1) * rate)


# MXNET_TELEMETRY / MXNET_TRACE_SAMPLE presence are consulted on the
# per-request serve path; `os.environ.get` of an UNSET key costs ~1us
# (internal KeyError) and it adds up at 10k req/s under a saturated GIL,
# so both are TTL-cached (50ms — env toggles still land promptly; the
# paired A/B harness and env-monkeypatching tests call
# _expire_env_memo() for an immediate re-read)
_ENV_TTL_S = 0.05
_env_deadline = [0.0]
_env_memo = {"enabled": True, "explicit_sample": False}


def _expire_env_memo():
    _env_deadline[0] = 0.0


def _env_refresh():
    raw = os.environ.get("MXNET_TELEMETRY")
    _env_memo["enabled"] = raw not in ("0", "false", "False", "")
    _env_memo["explicit_sample"] = \
        os.environ.get("MXNET_TRACE_SAMPLE") is not None


def enabled():
    """Tracing rides the MXNET_TELEMETRY master switch: `0` disables
    span recording AND context minting (counters stay live). TTL-cached
    (see above) — hot-path callers pay a clock read, not an env parse."""
    now = time.monotonic()
    if now > _env_deadline[0]:
        _env_deadline[0] = now + _ENV_TTL_S
        _env_refresh()
    return _env_memo["enabled"]


def collector_active():
    """True when something can actually CONSUME per-request trace ids:
    the profiler is collecting a Chrome trace, the flight-recorder spool
    is armed (`MXNET_FLIGHTREC_DIR`), or `MXNET_TRACE_SAMPLE` is
    explicitly set (an operator forcing request tracing, e.g. for the
    slowest-requests table). The per-REQUEST root-mint hot path (serve
    submit) gates on this: at ~10k req/s even a few microseconds of
    mint work per request measurably taxes a GIL-saturated server, and
    ids nobody can see are pure cost. Step-scale spans
    (`telemetry.span`) are NOT gated — their rate is harmless and their
    ids feed the flight-recorder ring either way."""
    now = time.monotonic()
    if now > _env_deadline[0]:
        _env_deadline[0] = now + _ENV_TTL_S
        _env_refresh()
    if _env_memo["explicit_sample"]:
        return True
    f = FLIGHTREC
    if f._ring is None:
        if f._spool_dir() is not None:      # first call: sized under lock
            return True
    elif f._spool_dir_memo is not None:     # immutable once sized
        return True
    return _profiler_running()


# the profiler module, resolved once: `from .. import profiler` per call
# runs the import machinery (~1us + import-lock traffic) on a
# per-request path
_profiler_mod = [None]


def _profiler_running():
    p = _profiler_mod[0]
    if p is None:
        from .. import profiler as p
        _profiler_mod[0] = p
    return p._state["running"]


def request_root(name):
    """Mint a request-root context iff tracing is enabled AND a
    collector is active — `enabled() and collector_active()` fused into
    ONE TTL check for the per-request serve hot path. Returns None
    otherwise (and None when the root is sampled out)."""
    now = time.monotonic()
    if now > _env_deadline[0]:
        _env_deadline[0] = now + _ENV_TTL_S
        _env_refresh()
    if not _env_memo["enabled"]:
        return None
    if not _env_memo["explicit_sample"]:
        f = FLIGHTREC
        if f._ring is None:
            if f._spool_dir() is None and not _profiler_running():
                return None
        elif f._spool_dir_memo is None and not _profiler_running():
            return None
    parent = _CTX.get()
    if parent is NOT_SAMPLED:   # a request is its own sampling domain
        parent = None
    return child_context(parent, name)


def current_context():
    """The TraceContext active on this thread of execution, or None
    (a sampled-out subtree reads as None — no ids exist there)."""
    ctx = _CTX.get()
    return None if ctx is NOT_SAMPLED else ctx


def _raw_context():
    """Internal: like current_context but exposing the NOT_SAMPLED
    sentinel, so span entry can inherit a sampled-out decision."""
    return _CTX.get()


def new_context(name, sampled=None):
    """Mint a ROOT context (a new trace). Subject to MXNET_TRACE_SAMPLE
    unless `sampled` forces the decision; returns None when sampled out."""
    if not (_sampled() if sampled is None else sampled):
        TRACE_STATS["sampled_out"] += 1  # mxlint: disable=lock-shared-mutation -- documented lock-free diagnostics (DISPATCH_STATS pattern)
        return None
    TRACE_STATS["traces"] += 1  # mxlint: disable=lock-shared-mutation -- documented lock-free diagnostics (DISPATCH_STATS pattern)
    # convention: the ROOT span's id IS the trace id (one mint per root —
    # this runs per request on the serve path)
    tid = _new_id()
    return TraceContext(tid, tid, name)


def child_context(parent, name, sampled=None):
    """Mint a child of `parent` (same trace, fresh span id). With
    `parent=None` this starts a new root trace (sampling applies)."""
    if parent is None:
        return new_context(name, sampled=sampled)
    return TraceContext(parent.trace_id, _new_id(), name,
                        parent_span_id=parent.span_id,
                        parent_name=parent.name)


def attach(ctx):
    """Make `ctx` current on THIS thread (the consumer side of a hop);
    returns a token for `detach`. Counted in `trace.attaches`."""
    if ctx is not None:
        TRACE_STATS["attaches"] += 1  # mxlint: disable=lock-shared-mutation -- documented lock-free diagnostics (DISPATCH_STATS pattern)
    return _CTX.set(ctx)


def detach(token):
    """Undo an `attach` (tolerates tokens from a dead context)."""
    try:
        _CTX.reset(token)
    except ValueError:
        pass


class attached:
    """`with trace.attached(ctx):` — scoped attach/detach."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx):
        self._ctx = ctx
        self._token = None

    def __enter__(self):
        self._token = attach(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        detach(self._token)
        return False


def _push(ctx):
    """Internal: set the current context WITHOUT counting an attach —
    span entry/exit, not a cross-boundary hop."""
    return _CTX.set(ctx)


_reset = detach


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
def _now_us():
    return time.perf_counter_ns() // 1000


class FlightRecorder:
    """Bounded ring of recent structured events + optional SIGKILL-durable
    JSONL spool (see module docstring). `record` is the only hot call:
    one lock, one deque append, and — only when `MXNET_FLIGHTREC_DIR` is
    set — one flushed line write."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ring = None            # sized lazily from the env knob
        self._spool = None
        self._spool_path = None
        self._spool_failed = False
        self._spool_dir_memo = None  # read once per ring life (reset hook)
        self._last_dump = {}         # reason -> monotonic seconds

    # -- setup ----------------------------------------------------------
    def _ensure_locked(self):
        if self._ring is None:
            cap = max(16, get_env("MXNET_FLIGHTREC_EVENTS", 512, typ=int))
            self._ring = deque(maxlen=cap)
            self._spool_dir_memo = get_env("MXNET_FLIGHTREC_DIR", typ=str)

    def _spool_dir(self):
        # cached with the ring (one env read per recorder life, not per
        # event); _reset_for_tests re-reads
        with self._lock:
            self._ensure_locked()
            return self._spool_dir_memo

    def _spool_file_locked(self):
        if self._spool is not None or self._spool_failed:
            return self._spool
        d = self._spool_dir_memo
        if not d:
            return None
        try:
            os.makedirs(d, exist_ok=True)
            self._spool_path = os.path.join(
                d, f"flightrec-{os.getpid()}.jsonl")
            self._spool = open(self._spool_path, "a", encoding="utf-8")
        except OSError:
            # a broken spool dir must never take the traced workload down
            self._spool_failed = True
            self._spool = None
        return self._spool

    # -- the one hot call ------------------------------------------------
    def record(self, kind, name, /, **fields):
        """Append one event. `kind` is the event class (`span_open`,
        `span`, `fault`, `watchdog`, `collective_timeout`, `serve.shed`,
        ...), `name` the subsystem-specific symbol (span name, fault
        point). The active trace context's ids ride along."""
        ev = {"ts_us": _now_us(), "kind": kind, "name": name}
        ctx = _CTX.get()
        if ctx is not None:
            ev["trace_id"] = ctx.trace_id
            ev["span_id"] = ctx.span_id
        for k, v in fields.items():
            if k == "trace_id" and v is None:
                continue            # "no trace" is absence, not null
            # caller fields must not clobber the envelope (fault events
            # carry their own `kind="kill"` etc.) — prefix collisions
            ev[("f_" + k) if k in ("ts_us", "kind", "name", "thread")
               else k] = v
        ev["thread"] = threading.current_thread().name
        with self._lock:
            self._ensure_locked()
            if len(self._ring) == self._ring.maxlen:
                FLIGHTREC_STATS["dropped"] += 1  # mxlint: disable=lock-shared-mutation -- under self._lock; group is lock-free by design
            self._ring.append(ev)
            f = self._spool_file_locked()
            if f is not None:
                try:
                    # flush per line: data handed to the kernel survives a
                    # SIGKILL (fsync would only add power-loss durability
                    # at ~100x the cost)
                    f.write(json.dumps(ev, default=str) + "\n")
                    f.flush()
                except (OSError, ValueError):
                    self._spool_failed = True
                    self._spool = None
        FLIGHTREC_STATS["events"] += 1  # mxlint: disable=lock-shared-mutation -- documented lock-free diagnostics (DISPATCH_STATS pattern)

    # -- inspection / dump ----------------------------------------------
    def events(self):
        """Copy of the ring, oldest first."""
        with self._lock:
            self._ensure_locked()
            return list(self._ring)

    @property
    def spool_path(self):
        return self._spool_path

    def dump(self, path=None, reason=""):
        """Write the ring as one JSON black-box file and return its path
        (None on failure — dump sits on crash paths and must never raise).
        Default location: `MXNET_FLIGHTREC_DIR` (or the cwd) /
        `flightrec-<pid>.json`; an existing file is atomically replaced,
        so the newest dump wins."""
        try:
            events = self.events()
            if path is None:
                d = self._spool_dir() or "."
                os.makedirs(d, exist_ok=True)
                path = os.path.join(d, f"flightrec-{os.getpid()}.json")
            payload = {
                "pid": os.getpid(),
                "reason": reason,
                "dumped_ts_us": _now_us(),
                "n_events": len(events),
                "events": events,
            }
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
            FLIGHTREC_STATS["dumps"] += 1  # mxlint: disable=lock-shared-mutation -- documented lock-free diagnostics (DISPATCH_STATS pattern)
            return path
        except Exception:
            return None

    def maybe_dump(self, reason, min_interval_s=5.0):
        """Rate-limited dump for recurring triggers (overload shedding,
        watchdogs): at most one file per `reason` per interval, and a
        NO-OP unless MXNET_FLIGHTREC_DIR is set (no surprise files)."""
        if not self._spool_dir():
            return None
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(reason)
            if last is not None and now - last < min_interval_s:
                return None
            self._last_dump[reason] = now
        return self.dump(reason=reason)

    def _reset_for_tests(self):
        """Drop the ring and spool so a test can re-read the env knobs."""
        with self._lock:
            self._ring = None
            self._spool_dir_memo = None
            if self._spool is not None:
                try:
                    self._spool.close()
                except OSError:
                    pass
            self._spool = None
            self._spool_path = None
            self._spool_failed = False
            self._last_dump.clear()


FLIGHTREC = FlightRecorder()
flightrec_record = FLIGHTREC.record
flightrec_dump = FLIGHTREC.dump
flightrec_maybe_dump = FLIGHTREC.maybe_dump
flightrec_events = FLIGHTREC.events


# ---------------------------------------------------------------------------
# crash hooks (atexit + SIGTERM): best-effort dump on orderly-ish deaths;
# the JSONL spool covers SIGKILL, where nothing can run
# ---------------------------------------------------------------------------
_hooks_lock = threading.Lock()
_atexit_armed = [False]
_sigterm_armed = [False]


def _atexit_dump():
    try:
        FLIGHTREC.maybe_dump("atexit", min_interval_s=0.0)
    except Exception:
        pass


def install_crash_hooks():
    """Idempotent: register an atexit dump and a SIGTERM handler that
    dumps then re-raises the default disposition. Both are no-ops unless
    `MXNET_FLIGHTREC_DIR` is set. The signal hook only installs from the
    main thread and only while SIGTERM still has the default handler (a
    user handler is never displaced) — the two halves latch SEPARATELY,
    so a first call from a worker thread (which can only arm atexit)
    does not block a later main-thread call from arming the signal
    hook."""
    with _hooks_lock:
        arm_atexit = not _atexit_armed[0]
        _atexit_armed[0] = True
        arm_sigterm = (not _sigterm_armed[0]
                       and threading.current_thread()
                       is threading.main_thread())
        if arm_sigterm:
            _sigterm_armed[0] = True
    if arm_atexit:
        import atexit
        atexit.register(_atexit_dump)
    if not arm_sigterm:
        return
    try:
        import signal

        if signal.getsignal(signal.SIGTERM) is not signal.SIG_DFL:
            return

        def _on_term(signum, frame):
            try:
                FLIGHTREC.record("signal", "SIGTERM")
                FLIGHTREC.maybe_dump("sigterm", min_interval_s=0.0)
            except Exception:
                pass
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass
