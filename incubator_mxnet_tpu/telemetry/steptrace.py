"""Step-timeline attribution: spans, per-step breakdown, live-counter MFU.

Before this module the Chrome-trace lanes (`serve.batch`, `io.feed`,
`feed.stage`) each hand-rolled their `profiler.record_event` call and no
single object could answer "where did this step's time go?". Now:

  * `span(name, **attrs)` — nesting-aware tracer. Nesting rides the trace
    ContextVar (`telemetry.trace`), so it follows `trace.attach(ctx)`
    across thread hops and every span carries trace/span/parent ids.
    Every span lands in the profiler's Chrome-trace buffer (cat "span",
    with its parent's name in args so the tree reconstructs), in the
    registry histogram `span.duration_us{name=...}`, and in the flight
    recorder, so `profiler.dump()` shows the lane and
    `telemetry.snapshot()` shows the aggregate without re-parsing traces.

  * `StepTimeline` — the per-step breakdown a train loop or server wants:
    wall time split into data-stall vs compute vs H2D-staging vs allreduce,
    pulled from live counters (DeviceFeed stall/staging counters, kvstore
    bucket timings) around each step, not hand-math after the fact. With
    `flops_per_step` it also reports MFU against a peak.

  * `model_flops` / `block_fwd_flops` — the MFU numerator computed ONCE
    from XLA's own cost analysis (`jax.jit(...).lower().cost_analysis()`),
    the same MAC=2 convention as the chip spec, so every reporter
    (bench.py, estimator.fit) shares one number instead of three copies of
    3.86-GMAC hand-math.

Attribution semantics (documented, not magic): `data_stall_us` and the
collective clocks (`allreduce_us`, and the ZeRO lanes `reduce_scatter_us`
/ `allgather_us`) are time the CONSUMER thread provably spent inside the
step window waiting (empty feed buffer; collective dispatch).
`h2d_stage_us` is feeder-thread staging time — it overlaps compute by
design, so it is reported alongside, never subtracted. `compute_us` is the
remainder: `total - data_stall - allreduce - reduce_scatter - allgather`.
"""
from __future__ import annotations

import weakref
from collections import OrderedDict

from ..base import MXNetError, get_env
from .registry import REGISTRY
from . import trace as _trace

__all__ = ["span", "current_span", "record_span", "StepTimeline",
           "model_flops", "block_fwd_flops", "cost_flops",
           "device_peak_flops", "SPAN_DURATION", "SPAN_COUNT"]

# one histogram family for every span name: static registration (lint +
# docs cover it), dynamic span names become label values, not new metrics
SPAN_DURATION = REGISTRY.histogram(
    "span.duration_us", help="telemetry.span durations by span name",
    labels=("name",))
SPAN_COUNT = REGISTRY.counter(
    "span.count", help="telemetry.span completions by span name",
    labels=("name",))

# process-wide memory high-water over every StepTimeline/MemoryMonitor
# sample (mx.inspect.memory catalog); the python cell avoids a locked
# gauge read per step
MEM_PEAK = REGISTRY.gauge(
    "mem.peak_hbm_bytes", help="high-water bytes_in_use across every "
    "step-timeline / memory-monitor sample this process took "
    "(source per profiler.read_memory_sample: device HBM, or host RSS "
    "on backends without memory_stats)")
_mem_peak_seen = [0]


def _note_memory_sample(b):
    if b > _mem_peak_seen[0]:
        _mem_peak_seen[0] = b
        MEM_PEAK.set(b)

_enabled = _trace.enabled


# span name -> (bound duration histogram, bound counter): the label-value
# resolution is a dict+tuple build per call — memoized off the hot path
# (span names are a small closed set; labeled children are never removed)
_bound_memo = {}

# flight-recorder duration floor for CLOSED spans (see record_span): only
# spans at least this long are black-box-worthy; span_open events are
# never floored
FLIGHTREC_SPAN_FLOOR_US = 50_000.0


def current_span():
    """Name of the innermost open span on this execution context (follows
    an attached TraceContext across thread hops), or None."""
    ctx = _trace.current_context()
    return ctx.name if ctx is not None else None


def record_span(name, dur_us, ts_us=None, cat="span", ctx=None, **attrs):
    """Record an externally-timed span: the one implementation behind
    every Chrome-trace lane (`serve.batch`, `io.feed`, `feed.stage`, and
    `with span(...)` itself). Feeds the `span.duration_us{name=...}`
    histogram always (when telemetry is on), the flight-recorder ring,
    and the profiler's Chrome-trace buffer when the profiler is running.

    Trace linkage: pass `ctx` (a TraceContext) to record AS that node of
    a request tree; with no `ctx`, the ambient `trace.current_context()`
    — if any — becomes the parent and a fresh child id is minted. Either
    way the trace/span/parent ids land in the event args, so the
    cross-thread tree reassembles from the exported trace JSON."""
    if not _enabled():
        return
    bounds = _bound_memo.get(name)
    if bounds is None:
        bounds = _bound_memo[name] = (SPAN_DURATION.labels(name=name),
                                      SPAN_COUNT.labels(name=name))
    bounds[0].observe(dur_us)
    bounds[1].inc()
    if ctx is None:
        ambient = _trace.current_context()
        if ambient is not None:
            ctx = _trace.child_context(ambient, name)
    if ctx is not None:
        attrs.setdefault("trace_id", ctx.trace_id)
        attrs.setdefault("span_id", ctx.span_id)
        if ctx.parent_span_id is not None:
            attrs.setdefault("parent_span_id", ctx.parent_span_id)
        if ctx.parent_name is not None:
            attrs.setdefault("parent", ctx.parent_name)
        _trace.TRACE_STATS["spans"] += 1  # mxlint: disable=lock-shared-mutation -- documented lock-free diagnostics (DISPATCH_STATS pattern)
    if dur_us >= FLIGHTREC_SPAN_FLOOR_US:
        # duration floor: step/request-scale spans are black-box-worthy;
        # sub-50ms spans at thousands/sec would evict the interesting
        # history from the bounded ring in well under a second (and cost
        # a spool write each when MXNET_FLIGHTREC_DIR is set). Span OPEN
        # events (the in-flight marker) are not floored — only `span`
        # class entries emit them, at step scale.
        _trace.flightrec_record("span", name, dur_us=round(dur_us, 1),
                                **attrs)
    # cached module ref, not `from .. import profiler`: record_span runs
    # per batch on the serving path and the import machinery costs ~1us
    # + import-lock traffic per call
    if _trace._profiler_running():
        _trace._profiler_mod[0].record_event(name, cat, dur_us,
                                             ts_us=ts_us, args=attrs)


class span:
    """`with telemetry.span("train.step", step=n):` — time a region.

    Nesting is tracked through the trace ContextVar: entering mints a
    child `TraceContext` of whatever is current (starting a new trace at
    the root, subject to MXNET_TRACE_SAMPLE), so the Chrome-trace event
    carries the enclosing span's name in `args["parent"]` PLUS the
    trace/span/parent ids, and — after `trace.attach(ctx)` on a worker
    thread — nesting survives thread hops. The registry histogram
    `span.duration_us{name=...}` aggregates durations, and the open/close
    pair feeds the flight recorder (an in-flight span at process death is
    named by its `span_open` spool line). A span is cheap when
    `MXNET_TELEMETRY=0` (no clock reads, no records) and never touches
    jax. Reentrant and exception-safe (the span closes on the error path
    too — ContextVar tokens reset correctly even when an inner span
    leaked open, so traces stay balanced)."""

    __slots__ = ("name", "attrs", "_t0", "_parent", "_armed", "_dur",
                 "_ctx", "_token")

    def __init__(self, name, **attrs):
        self.name = name
        self.attrs = attrs
        self._t0 = None
        self._parent = None
        self._armed = False
        self._dur = None
        self._ctx = None
        self._token = None

    def __enter__(self):
        self._armed = _enabled()
        if not self._armed:
            return self
        from .. import profiler
        raw = _trace._raw_context()
        if raw is _trace.NOT_SAMPLED:
            # inside a sampled-out trace: inherit the decision — minting
            # a fresh root per inner span would fill the Chrome trace
            # with orphan mid-request fragments and count one "trace"
            # per span (head sampling samples TREES, not spans)
            self._parent = None
            self._ctx = None
        else:
            self._parent = raw.name if raw is not None else None
            self._ctx = _trace.child_context(raw, self.name)
            if self._ctx is not None:
                self._token = _trace._push(self._ctx)
                _trace.flightrec_record("span_open", self.name,
                                        **self.attrs)
            elif raw is None:
                # root draw came up sampled-out: mark the subtree
                self._token = _trace._push(_trace.NOT_SAMPLED)
        self._t0 = profiler._now_us()
        return self

    def __exit__(self, *exc):
        if not self._armed:
            return False
        from .. import profiler
        t1 = profiler._now_us()
        if self._token is not None:
            _trace._reset(self._token)
            self._token = None
        attrs = dict(self.attrs)
        if self._parent is not None:
            attrs["parent"] = self._parent
        self._dur = t1 - self._t0
        record_span(self.name, self._dur, ts_us=self._t0, ctx=self._ctx,
                    **attrs)
        return False

    @property
    def duration_us(self):
        """Set only after exit (None while open or telemetry disabled)."""
        return self._dur

    @property
    def context(self):
        """The span's TraceContext (None before entry, when telemetry is
        off, or when the root was sampled out)."""
        return self._ctx


def _stall_counters():
    """One consistent read of the cross-subsystem counters a step window
    diffs: (feed stall/staging, kvstore allreduce). Missing subsystems
    read as zeros so a loop with no feed or no kvstore still reports."""
    out = {"data_stall_us": 0.0, "h2d_stage_us": 0.0, "host_transfers": 0,
           "allreduce_us": 0.0, "allreduce_buckets": 0,
           "reduce_scatter_us": 0.0, "reduce_scatter_buckets": 0,
           "allgather_us": 0.0, "allgather_buckets": 0}
    try:
        from ..io.device_feed import feed_stats
        f = feed_stats()
        out["data_stall_us"] = f.get("stall_data_us", 0.0)
        out["h2d_stage_us"] = f.get("stage_us", 0.0)
        out["host_transfers"] = f.get("host_transfers", 0)
    except Exception:
        pass
    try:
        from ..kvstore import KV_STATS
        out["allreduce_us"] = KV_STATS.get("allreduce_us", 0.0)
        out["allreduce_buckets"] = KV_STATS.get("allreduce_buckets", 0)
        # ZeRO collective lanes (mx.fault.elastic): dispatch-side clocks
        # of the bucketed reduce-scatter / all-gather, same semantics as
        # the allreduce clock
        out["reduce_scatter_us"] = KV_STATS.get("reduce_scatter_us", 0.0)
        out["reduce_scatter_buckets"] = KV_STATS.get(
            "reduce_scatter_buckets", 0)
        out["allgather_us"] = KV_STATS.get("allgather_us", 0.0)
        out["allgather_buckets"] = KV_STATS.get("allgather_buckets", 0)
    except Exception:
        pass
    return out


class StepTimeline:
    """Per-step time attribution over a training (or serving) loop.

    ::

        tl = telemetry.StepTimeline(flops_per_step=fl, peak_flops=peak)
        for batch in feed:
            with tl.step():
                loss = train_step(*batch)
        report = tl.report()
        # {'steps', 'total_us', 'data_stall_us', 'compute_us',
        #  'h2d_stage_us', 'allreduce_us', 'stall_pct', 'compute_pct',
        #  'mfu', 'achieved_flops_per_sec', ...}

    Attribution runs over the LOOP WINDOW — first `step()` entry to the
    latest `step()` exit — with the cross-subsystem counters (DeviceFeed
    stall clock, kvstore allreduce clock) diffed continuously across it.
    That window deliberately includes the time BETWEEN steps, because that
    is where a `for batch in feed:` loop blocks on data — a per-step-only
    window would attribute an input-bound loop as pure compute. The report
    divides:

      data_stall_us   consumer blocked on an empty feed buffer (input-bound)
      allreduce_us    gradient-collective dispatch time inside the window
      reduce_scatter_us / allgather_us
                      ZeRO collective dispatch time (mx.fault.elastic
                      bucketed reduce-scatter / param all-gather)
      compute_us      total - data_stall - allreduce - reduce_scatter -
                      allgather (the XLA side)
      h2d_stage_us    feeder staging incl. async H2D dispatch — overlapped
                      work, reported for visibility, never subtracted
      step_time_us    sum of the in-step spans (loop-body time only)

    MFU = flops_per_step * steps / total_seconds / peak_flops — the same
    live-counter number bench.py reports, available to any fit loop.
    """

    def __init__(self, flops_per_step=None, peak_flops=None,
                 name="train.step"):
        self.name = name
        self.flops_per_step = flops_per_step
        self.peak_flops = (peak_flops if peak_flops is not None
                           else device_peak_flops())
        self.steps = 0
        self.step_time_us = 0.0
        # memory lane: per-step-exit bytes_in_use samples, high-water
        # over the loop window (profiler.read_memory_sample provenance:
        # "device" on accelerators, "host_rss" on CPU backends)
        self.peak_hbm_bytes = 0
        self.mem_source = None
        self.deltas = {"data_stall_us": 0.0, "h2d_stage_us": 0.0,
                       "allreduce_us": 0.0, "host_transfers": 0,
                       "allreduce_buckets": 0,
                       "reduce_scatter_us": 0.0,
                       "reduce_scatter_buckets": 0,
                       "allgather_us": 0.0, "allgather_buckets": 0}
        self._base = None        # counters at first step entry
        self._t_first = None
        self._t_last = None

    class _Step:
        __slots__ = ("tl", "span")

        def __init__(self, tl):
            self.tl = tl
            self.span = span(tl.name, step=tl.steps)

        def __enter__(self):
            from .. import profiler
            tl = self.tl
            if tl._base is None:
                tl._base = _stall_counters()
                tl._t_first = profiler._now_us()
            self.span.__enter__()
            return self

        def __exit__(self, *exc):
            from .. import profiler
            t0 = self.span._t0
            self.span.__exit__(*exc)
            tl = self.tl
            tl.steps += 1
            if t0 is None:       # telemetry disabled: count steps only
                return False
            now = profiler._now_us()
            tl._t_last = now
            tl.step_time_us += now - t0
            after = _stall_counters()
            for k in tl.deltas:
                tl.deltas[k] = after[k] - tl._base[k]
            # memory lane: one cheap sample per step exit (PJRT
            # memory_stats / one /proc read) — the loop high-water folds
            # into the report AND the process-wide mem.peak_hbm_bytes
            try:
                b, source = profiler.read_memory_sample()
                if b > tl.peak_hbm_bytes:
                    tl.peak_hbm_bytes = b
                tl.mem_source = source
                _note_memory_sample(b)
            except Exception:
                pass
            return False

    def step(self):
        """Context manager for one step of the loop."""
        return self._Step(self)

    @property
    def total_us(self):
        """The loop window: first step entry to latest step exit."""
        if self._t_first is None or self._t_last is None:
            return 0.0
        return float(self._t_last - self._t_first)

    def report(self):
        """Plain-data breakdown; safe to json.dumps."""
        total = self.total_us
        stall = self.deltas["data_stall_us"]
        allred = self.deltas["allreduce_us"]
        rs = self.deltas["reduce_scatter_us"]
        ag = self.deltas["allgather_us"]
        compute = max(0.0, total - stall - allred - rs - ag)
        out = {
            "name": self.name,
            "steps": self.steps,
            "total_us": round(total, 1),
            "step_time_us": round(self.step_time_us, 1),
            "step_mean_us": round(self.step_time_us / self.steps, 1)
            if self.steps else 0.0,
            "data_stall_us": round(stall, 1),
            "allreduce_us": round(allred, 1),
            "reduce_scatter_us": round(rs, 1),
            "allgather_us": round(ag, 1),
            "compute_us": round(compute, 1),
            "h2d_stage_us": round(self.deltas["h2d_stage_us"], 1),
            "host_transfers": self.deltas["host_transfers"],
            "allreduce_buckets": self.deltas["allreduce_buckets"],
            "reduce_scatter_buckets":
                self.deltas["reduce_scatter_buckets"],
            "allgather_buckets": self.deltas["allgather_buckets"],
            "stall_pct": round(100.0 * stall / total, 2) if total else 0.0,
            "compute_pct": round(100.0 * compute / total, 2) if total
            else 0.0,
            "peak_hbm_bytes": int(self.peak_hbm_bytes),
            "mem_source": self.mem_source,
        }
        if self.flops_per_step and total > 0:
            achieved = self.flops_per_step * self.steps / (total * 1e-6)
            out["achieved_flops_per_sec"] = achieved
            if self.peak_flops:
                # 6 decimals: tiny test-scale MFUs must not round to 0.0
                out["mfu"] = round(achieved / self.peak_flops, 6)
        return out


# ---------------------------------------------------------------------------
# MFU numerator: XLA-counted model FLOPs, computed once per (fn, shapes)
# ---------------------------------------------------------------------------
# key -> (fn, flops): the cached callable is held STRONGLY so its id can
# never be recycled by a different function while the entry lives; BOUNDED
# (FIFO) so per-call closures (block_fwd_flops' `fwd`) cannot pin models
# without limit
_flops_cache = OrderedDict()
_FLOPS_CACHE_CAP = 128


def _sig(a):
    """Structural signature of one argument: shapes/dtypes recurse through
    lists/tuples/dicts (a param-buffer list must contribute its shapes to
    the memo key, not collapse to 'list')."""
    if hasattr(a, "shape") and hasattr(a, "dtype"):
        return (tuple(a.shape), str(a.dtype))
    if isinstance(a, (list, tuple)):
        return (type(a).__name__, tuple(_sig(v) for v in a))
    if isinstance(a, dict):
        return ("dict", tuple((k, _sig(v)) for k, v in sorted(a.items())))
    return ("scalar", type(a).__name__, a if isinstance(
        a, (int, float, bool, str, type(None))) else None)


def cost_flops(lowered, what="program"):
    """FLOPs from a lowered jit program's XLA cost analysis (MAC = 2 —
    the same convention as accelerator peak specs), with the older-jax
    quirks handled once for every MFU numerator (`model_flops`,
    `FusedTrainStep.flops_per_call`): pre-compile fallback when
    `.compile().cost_analysis()` raises, list-wrapped results unwrapped."""
    import numpy as _np
    try:
        ca = lowered.compile().cost_analysis()
    except Exception:
        ca = lowered.cost_analysis()
    if isinstance(ca, (list, tuple)):   # older jax returns [dict]
        ca = ca[0]
    if not ca or "flops" not in ca:
        raise MXNetError(
            f"XLA cost analysis returned no flops for {what}")
    return float(_np.asarray(ca["flops"]))


def model_flops(fn, *args):
    """FLOPs of ONE execution of `fn(*args)` per XLA's cost analysis of the
    compiled program (`jax.jit(fn).lower(...).compile().cost_analysis()`),
    MAC = 2 flops — the same convention as accelerator peak specs. Memoized
    on (fn identity, structural arg signature); the entry holds `fn`
    strongly so a recycled id can never alias another function. The
    lowering+compile lands in jax's jit cache, so a subsequent real
    `jax.jit(fn)` call with the same avals does not recompile."""
    import jax

    key = (id(fn), tuple(_sig(a) for a in args))
    hit = _flops_cache.get(key)
    if hit is not None and hit[0] is fn:
        return hit[1]
    flops = cost_flops(jax.jit(fn).lower(*args), what=repr(fn))
    _flops_cache[key] = (fn, flops)
    while len(_flops_cache) > _FLOPS_CACHE_CAP:
        _flops_cache.popitem(last=False)
    return flops


# net (weak) -> {(param shapes, input sig): flops} — repeat calls on the
# SAME net skip the relowering entirely, and a dead net drops its entries
_block_flops_memo = weakref.WeakKeyDictionary()


def block_fwd_flops(net, x):
    """FLOPs of one compiled FORWARD of an initialized HybridBlock on batch
    `x` (total for the batch, MAC=2). Train-step flops are conventionally
    ~3x this (fwd + 2x bwd). Uses the same parameter buffer-swap trick the
    fused paths use so the traced function is pure in its buffers.
    Memoized per net (weakly — the model is never pinned): the cost
    analysis runs once per (net, param shapes, batch signature)."""
    import jax
    from .. import autograd, random as _random
    from ..ndarray import NDArray, _wrap

    params = [p for _, p in sorted(net.collect_params().items())]
    for p in params:
        if p._data is None:
            raise MXNetError("block_fwd_flops needs an initialized net: "
                             "run one forward first")
    raw0 = x._arr if isinstance(x, NDArray) else x
    memo_key = (tuple(_sig(p.data()._arr) for p in params), _sig(raw0))
    try:
        memo = _block_flops_memo.setdefault(net, {})
    except TypeError:          # unweakrefable net: skip the memo
        memo = {}
    if memo_key in memo:
        return memo[memo_key]

    def fwd(pbufs, xr):
        saved = []
        for p, b in zip(params, pbufs):
            nd = p.data()
            saved.append(nd._data)
            nd._data = b
            nd._version += 1
        try:
            key = jax.random.PRNGKey(0)
            with autograd._Scope(recording=False, training=False), \
                    _random.trace_key_scope(key):
                out = net(_wrap(xr))
        finally:
            for p, old in zip(params, saved):
                # trace-time buffer swap, restored before tracing ends
                p.data()._data = old  # mxlint: disable=trace-closure-mutation
        return out._arr

    pbufs = [p.data()._arr for p in params]
    # cost_flops directly, NOT model_flops: the per-call `fwd` closure can
    # never hit model_flops' id-keyed cache again, and caching it there
    # would strongly pin `net` and its buffers until FIFO eviction — the
    # weak per-net memo above is the only cache this path needs
    flops = cost_flops(jax.jit(fwd).lower(pbufs, raw0),
                       what=f"forward of {type(net).__name__}")
    memo[memo_key] = flops
    return flops


# (platform, device-kind substring) -> advertised bf16 peak FLOP/s (MAC=2).
# The honest denominator is still a measured attainable (bench.py calib
# phase); these are the spec fallbacks when no calibration ran.
_PEAKS = (
    ("tpu", "v5 lite", 197e12),
    ("tpu", "v5e", 197e12),
    ("tpu", "v4", 275e12),
    ("tpu", "v3", 123e12),
    ("tpu", "v2", 45e12),
)


def device_peak_flops(device=None):
    """Spec bf16 peak FLOP/s for the attached accelerator, or None when
    unknown (CPU, exotic chips): MFU is then omitted rather than wrong."""
    try:
        import jax
        d = device or jax.devices()[0]
        plat = d.platform.lower()
        kind = getattr(d, "device_kind", "").lower()
        for p, sub, peak in _PEAKS:
            if plat == p and sub in kind:
                return peak
    except Exception:
        pass
    return None
