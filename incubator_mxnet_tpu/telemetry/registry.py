"""mx.telemetry registry — ONE process-wide metrics surface.

The observability story grew bottom-up: three ad-hoc module counter dicts
(`DISPATCH_STATS`, `SERVE_STATS`, `FEED_STATS`) with three bespoke snapshot
functions and no common exposition. The reference answered the same problem
with a first-class profiler/metrics layer (`MXNET_PROFILER_MODE`, per-op
profiling hooks, KVStore server profiling — PAPER.md layer map); this module
is our equivalent: typed Counter / Gauge / Histogram metrics with labels,
one lock discipline, one `snapshot(reset=...)`, and JSON + Prometheus-text
exposition.

Two metric tiers, deliberately:

  * `Counter`/`Gauge`/`Histogram` objects — registered by name, mutated
    under the single registry lock. For everything OFF the per-op hot path
    (spans, serving, bench phases, step timelines).
  * `StatsGroup` — a dict subclass that ADOPTS a legacy `*_STATS` counter
    dict into the registry without changing its hot path: `d[k] += 1`
    stays a native dict write (GIL-atomic read-modify-write hazards are
    the owning module's documented contract — DISPATCH_STATS is lock-free
    by design, SERVE/FEED take their module lock). The group only adds
    atomic `snapshot(reset=...)` and registry membership, so
    `telemetry.snapshot()` / `prometheus_text()` see every counter in the
    process through one pane of glass.

Lock discipline: `Registry._lock` guards registration, object-metric
mutation, and snapshot assembly. `StatsGroup` mutation stays under its
owner's lock (or the GIL where the owner documents lock-free); group
snapshot/reset takes the owner lock, never the registry lock, so the only
cross-lock order is registry -> group and no cycle can form.

This module imports neither jax nor numpy: the mxlint import path and the
bench orchestrator stay accelerator-free.
"""
from __future__ import annotations

import json as _json
import math as _math
import threading

__all__ = ["Counter", "Gauge", "Histogram", "StatsGroup", "Registry",
           "REGISTRY", "counter", "gauge", "histogram", "stats_group",
           "snapshot", "snapshot_json", "prometheus_text",
           "DEFAULT_BUCKETS"]

# histogram upper bounds, microsecond-oriented (span durations): 1us..10s
DEFAULT_BUCKETS = (1.0, 10.0, 100.0, 1e3, 1e4, 1e5, 1e6, 1e7)


def _prom_name(name):
    """`serve.batch.duration_us` -> `mx_serve_batch_duration_us`."""
    return "mx_" + name.replace(".", "_")


def _prom_label_value(v):
    """Escape a label value per the 0.0.4 exposition spec: backslash,
    double-quote, and newline — one malformed value must not invalidate
    the whole scrape."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _prom_labels(labels, values):
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_prom_label_value(v)}"'
                     for k, v in zip(labels, values))
    return "{" + inner + "}"


class _Metric:
    """Base: a named metric family with optional label dimensions."""

    kind = "untyped"

    def __init__(self, name, help="", labels=(), _registry=None):
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self._registry = _registry
        self._children = {}      # label-value tuple -> child state

    def _lock(self):
        return self._registry._lock

    def labels(self, **kv):
        """Bound view for one label-value combination."""
        if set(kv) != set(self.label_names):
            raise ValueError(
                f"metric {self.name} has labels {self.label_names}, "
                f"got {tuple(kv)}")
        key = tuple(str(kv[k]) for k in self.label_names)
        return _Bound(self, key)

    def _slot(self, key):
        slot = self._children.get(key)
        if slot is None:
            slot = self._children[key] = self._new_slot()
        return slot


class _Bound:
    """A metric bound to concrete label values; proxies the mutators."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric, key):
        self._metric = metric
        self._key = key

    def inc(self, n=1):
        self._metric._inc(self._key, n)

    def dec(self, n=1):
        self._metric._inc(self._key, -n)

    def set(self, v):
        self._metric._set(self._key, v)

    def observe(self, v):
        self._metric._observe(self._key, v)

    def get(self):
        return self._metric._get(self._key)


class Counter(_Metric):
    """Monotonically increasing count. `inc(n)` with n >= 0."""

    kind = "counter"

    def _new_slot(self):
        return [0.0]

    def _inc(self, key, n=1):
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock():
            self._slot(key)[0] += n

    def _get(self, key):
        with self._lock():
            return self._slot(key)[0]

    def inc(self, n=1):
        self._inc((), n)

    def get(self):
        return self._get(())


class Gauge(_Metric):
    """Point-in-time value; survives `snapshot(reset=True)` (a reset
    zeroes flows, not levels)."""

    kind = "gauge"

    def _new_slot(self):
        return [0.0]

    def _inc(self, key, n=1):
        with self._lock():
            self._slot(key)[0] += n

    def _set(self, key, v):
        with self._lock():
            self._slot(key)[0] = float(v)

    def _get(self, key):
        with self._lock():
            return self._slot(key)[0]

    def inc(self, n=1):
        self._inc((), n)

    def dec(self, n=1):
        self._inc((), -n)

    def set(self, v):
        self._set((), v)

    def get(self):
        return self._get(())


class Histogram(_Metric):
    """Distribution over fixed upper-bound buckets (+Inf implicit):
    per-bucket cumulative counts, sum, count, min, max."""

    kind = "histogram"

    def __init__(self, name, help="", labels=(), buckets=DEFAULT_BUCKETS,
                 _registry=None):
        super().__init__(name, help, labels, _registry=_registry)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def _new_slot(self):
        # [bucket_counts..., +Inf], count, sum, min, max
        return {"buckets": [0] * (len(self.buckets) + 1),
                "count": 0, "sum": 0.0,
                "min": float("inf"), "max": float("-inf")}

    def _observe(self, key, v):
        v = float(v)
        with self._lock():
            s = self._slot(key)
            i = len(self.buckets)
            for j, ub in enumerate(self.buckets):
                if v <= ub:
                    i = j
                    break
            s["buckets"][i] += 1
            s["count"] += 1
            s["sum"] += v
            if v < s["min"]:
                s["min"] = v
            if v > s["max"]:
                s["max"] = v

    def observe(self, v):
        self._observe((), v)

    def _get(self, key):
        with self._lock():
            s = self._slot(key)
            return dict(s, buckets=list(s["buckets"]))

    def get(self):
        return self._get(())


class StatsGroup(dict):
    """A legacy `*_STATS` counter dict adopted into the registry.

    Subclasses dict and overrides NOTHING on the read/write path, so the
    owning module's hot-path contract (`d[k] += 1` under its own lock, or
    lock-free under the GIL where documented) is unchanged to the byte.
    Adds atomic `snapshot(reset=...)` (the owner-lock-guarded copy+zero the
    three bespoke `*_stats()` functions used to hand-roll) and registry
    membership: the group's keys surface in `telemetry.snapshot()` as
    `<family>.<key>` and in Prometheus text as `mx_<family>_<key>`.

    Reset restores each value to `type(value)()` — ints to 0, floats to
    0.0 — preserving the per-key numeric type like the originals did.
    """

    def __init__(self, family, initial, lock=None, help=""):
        super().__init__(initial)
        self.family = family
        self.help = help
        # lock=None: mutation relies on the GIL (owner documents why);
        # snapshot still needs SOME mutual exclusion against reset, so a
        # private lock guards the snapshot+zero step either way.
        self._owner_lock = lock if lock is not None else threading.Lock()
        self._initial_types = {k: type(v) for k, v in initial.items()}

    def snapshot(self, reset=False):
        """Atomic copy (and optional zero) under the owner lock: no
        increment is ever lost between the copy and the reset."""
        with self._owner_lock:
            snap = dict(self)
            if reset:
                for k in self:
                    self[k] = self._initial_types.get(k, int)()
        return snap


class Registry:
    """Name -> metric. get-or-create constructors are type-checked: asking
    for an existing name with a different kind/labels is a bug, not a
    silent second family."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics = {}        # name -> _Metric
        self._groups = {}         # family -> StatsGroup

    # -- registration ---------------------------------------------------
    def _get_or_create(self, cls, name, help, labels, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(m).__name__}{m.label_names}")
                return m
            m = cls(name, help=help, labels=labels, _registry=self, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labels=()):
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name, help="", labels=()):
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=(), buckets=DEFAULT_BUCKETS):
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def stats_group(self, family, initial, lock=None, help=""):
        """Adopt (or return the already-adopted) legacy counter dict."""
        with self._lock:
            g = self._groups.get(family)
            if g is not None:
                return g
            g = StatsGroup(family, initial, lock=lock, help=help)
            self._groups[family] = g
            return g

    def names(self):
        """Every registered metric name, object metrics and group keys."""
        with self._lock:
            out = sorted(self._metrics)
            for fam, g in sorted(self._groups.items()):
                out.extend(f"{fam}.{k}" for k in g)
        return out

    # -- exposition -----------------------------------------------------
    def snapshot(self, reset=False):
        """Flat {name: value} over the whole surface. Counter values are
        numbers; labeled metrics key as `name{a=x,b=y}`; histograms map to
        a {count,sum,min,max,mean} dict. `reset=True` zeroes counters,
        histograms, and group counters (gauges are levels — they keep
        their value)."""
        out = {}
        with self._lock:
            for name, m in sorted(self._metrics.items()):
                for key in sorted(m._children):
                    slot = m._children[key]
                    full = name + _prom_labels(m.label_names, key) \
                        if m.label_names else name
                    if m.kind == "histogram":
                        mean = slot["sum"] / slot["count"] \
                            if slot["count"] else 0.0
                        out[full] = {
                            "count": slot["count"],
                            "sum": slot["sum"],
                            "mean": mean,
                            "min": slot["min"] if slot["count"] else 0.0,
                            "max": slot["max"] if slot["count"] else 0.0,
                        }
                        if reset:
                            m._children[key] = m._new_slot()
                    else:
                        out[full] = slot[0]
                        if reset and m.kind == "counter":
                            slot[0] = 0.0
            groups = list(self._groups.items())
        # group snapshots take each owner lock OUTSIDE the registry lock
        # order registry -> group is the only order used anywhere
        for fam, g in sorted(groups):
            for k, v in g.snapshot(reset=reset).items():
                out[f"{fam}.{k}"] = v
        return out

    def snapshot_json(self, reset=False):
        return _json.dumps(self.snapshot(reset=reset), sort_keys=True)

    def prometheus_text(self):
        """Prometheus text exposition format 0.0.4 of the whole surface."""
        lines = []
        with self._lock:
            metrics = sorted(self._metrics.items())
            groups = sorted(self._groups.items())
        for name, m in metrics:
            pname = _prom_name(name)
            if m.help:
                lines.append(f"# HELP {pname} {m.help}")
            lines.append(f"# TYPE {pname} {m.kind}")
            with self._lock:
                # deep-copy slot state under the lock: a concurrent
                # observe() mutates buckets/count/sum as separate writes,
                # and a lock-free read could emit a histogram whose count
                # disagrees with its +Inf cumulative bucket
                if m.kind == "histogram":
                    children = [
                        (key, dict(slot, buckets=list(slot["buckets"])))
                        for key, slot in sorted(m._children.items())]
                else:
                    children = [(key, list(slot))
                                for key, slot in sorted(m._children.items())]
            for key, slot in children:
                lab = _prom_labels(m.label_names, key)
                if m.kind == "histogram":
                    cum = 0
                    for ub, c in zip(m.buckets, slot["buckets"]):
                        cum += c
                        le = _prom_labels(
                            m.label_names + ("le",), key + (_fmt(ub),))
                        lines.append(f"{pname}_bucket{le} {cum}")
                    cum += slot["buckets"][-1]
                    le = _prom_labels(m.label_names + ("le",),
                                      key + ("+Inf",))
                    lines.append(f"{pname}_bucket{le} {cum}")
                    lines.append(f"{pname}_sum{lab} {_fmt(slot['sum'])}")
                    lines.append(f"{pname}_count{lab} {slot['count']}")
                else:
                    lines.append(f"{pname}{lab} {_fmt(slot[0])}")
        for fam, g in groups:
            if g.help:
                lines.append(f"# HELP {_prom_name(fam)} {g.help}")
            for k, v in g.snapshot().items():
                lines.append(f"{_prom_name(fam + '.' + k)} {_fmt(v)}")
        return "\n".join(lines) + "\n"

    def _reset_all_for_tests(self):
        """Test hook: zero every metric including gauges and label sets."""
        with self._lock:
            for m in self._metrics.values():
                m._children.clear()
        for g in list(self._groups.values()):
            g.snapshot(reset=True)


def _fmt(v):
    if isinstance(v, float):
        if not _math.isfinite(v):
            # Prometheus spells non-finite values +Inf/-Inf/NaN; one bad
            # series must not crash the whole exposition
            return "+Inf" if v > 0 else ("-Inf" if v < 0 else "NaN")
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    return str(v)


# the process-global registry — the single pane of glass
REGISTRY = Registry()

# module-level conveniences bound to the global registry
counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
stats_group = REGISTRY.stats_group
snapshot = REGISTRY.snapshot
snapshot_json = REGISTRY.snapshot_json
prometheus_text = REGISTRY.prometheus_text
