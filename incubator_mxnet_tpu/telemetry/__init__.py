"""mx.telemetry — unified metrics registry + step-timeline attribution.

One observability surface for the whole process (≙ the reference's
profiler/metrics layer: `MXNET_PROFILER_MODE`, per-op profiling hooks,
KVStore server profiling):

  telemetry.counter/gauge/histogram   typed metrics with labels on the
                                      process-global REGISTRY
  telemetry.snapshot(reset=False)     flat dict over EVERY counter in the
                                      process — dispatch, serve, feed,
                                      kvstore, spans, bench — one call
  telemetry.prometheus_text()         Prometheus text exposition (0.0.4)
  telemetry.span("train.step", n=1)   nesting-aware tracer: Chrome-trace
                                      lane + duration histogram
  telemetry.StepTimeline              per-step data-stall / compute / H2D /
                                      allreduce breakdown from live counters
  telemetry.model_flops(...)          XLA-counted MFU numerator
  telemetry.start_metrics_server(port)  /metrics HTTP endpoint
  telemetry.trace                     end-to-end request tracing
                                      (TraceContext / attach) + the crash
                                      flight recorder (flightrec_*)

The legacy surfaces keep working: `profiler.dispatch_stats()`,
`profiler.serve_stats()` and `profiler.feed_stats()` are shims over
registry-adopted `StatsGroup`s with identical keys and reset semantics.

Knobs: `MXNET_TELEMETRY` (default on; `0` makes spans and step timelines
no-ops — counters stay live, they are free), `MXNET_METRICS_PORT` (when
set, `mx.serve.Server.start()` also starts the /metrics endpoint).
"""
from __future__ import annotations

import threading as _threading

from ..base import _register_env
from .registry import (Counter, Gauge, Histogram, StatsGroup, Registry,
                       REGISTRY, counter, gauge, histogram, stats_group,
                       snapshot, snapshot_json, prometheus_text,
                       DEFAULT_BUCKETS)
from . import trace
from .trace import (TraceContext, current_context, attach, detach,
                    attached, new_context, child_context,
                    flightrec_record, flightrec_dump, flightrec_maybe_dump,
                    flightrec_events, install_crash_hooks, FLIGHTREC)
from .steptrace import (span, current_span, record_span, StepTimeline,
                        model_flops, block_fwd_flops, cost_flops,
                        device_peak_flops)

__all__ = [
    "Counter", "Gauge", "Histogram", "StatsGroup", "Registry", "REGISTRY",
    "counter", "gauge", "histogram", "stats_group", "snapshot",
    "snapshot_json", "prometheus_text", "DEFAULT_BUCKETS",
    "span", "current_span", "record_span", "StepTimeline", "model_flops",
    "block_fwd_flops", "cost_flops", "device_peak_flops",
    "metrics_text", "scalar_snapshot", "start_metrics_server",
    "ensure_metrics_server", "mem_on_oom", "mem_install_oom_hook",
    "trace", "TraceContext", "current_context", "attach", "detach",
    "attached", "new_context", "child_context", "flightrec_record",
    "flightrec_dump", "flightrec_maybe_dump", "flightrec_events",
    "install_crash_hooks", "FLIGHTREC",
]

_register_env("MXNET_TELEMETRY", bool, True,
              "0 disables span recording and step-timeline collection "
              "(counters stay live — plain increments are free)")
_register_env("MXNET_METRICS_PORT", int, None,
              "When set, serve.Server.start() also serves the telemetry "
              "/metrics endpoint on this port (0 = ephemeral)")
# bench.py (outside the package) reads these via os.environ; registered
# here so env_flags() introspection and the ENV_VARS.md table know them
_register_env("MXNET_BENCH_PHASE_TIMEOUT", float, None,
              "Per-phase subprocess timeout override for bench.py, "
              "seconds (a killed phase lands in phase_errors; the rest "
              "of the run continues)")
_register_env("MXNET_BENCH_FAULT_PHASE", str, None,
              "Deterministic bench-phase crash injection: "
              "'<phase>[:dtype|hang|exit]'")


def mem_on_oom(error, where=""):
    """Crash-path-safe proxy to `inspect.memory.on_oom`: the ONE shared
    wrapper every driver (run_resilient / run_elastic / serve batcher /
    continuous engine) calls from its exception path. Guards the IMPORT
    too — a failure to load the inspect package (interpreter teardown,
    broken install) must never replace the original error on a crash
    path. Returns the dump path or None; never raises."""
    try:
        from ..inspect.memory import on_oom
        return on_oom(error, where=where)
    except Exception:
        return None


def mem_install_oom_hook():
    """Crash-path-safe proxy to `inspect.memory.install_oom_hook` (the
    sys.excepthook chain for uncaught OOMs), armed next to
    `install_crash_hooks` by the same drivers. Never raises."""
    try:
        from ..inspect.memory import install_oom_hook
        install_oom_hook()
    except Exception:
        pass


def metrics_text():
    """The full registry in Prometheus text format — what /metrics serves."""
    return prometheus_text()


def scalar_snapshot(nonzero=True):
    """Scalar metrics only (histogram dicts dropped), by default nonzero —
    the compact registry form the bench artifacts (bench.py phase children,
    io_bench, serve_bench) embed. One implementation so the artifact shape
    cannot drift between emitters."""
    out = {}
    for k, v in snapshot().items():
        if isinstance(v, dict) or (nonzero and not v):
            continue
        out[k] = v
    return out


def start_metrics_server(port=0, host="127.0.0.1"):
    """Serve `/metrics` (Prometheus text) and `/metrics.json` (snapshot)
    on a daemon thread. Returns the HTTPServer; `server.server_address`
    carries the bound (host, port) — pass port=0 for an ephemeral port —
    and `server.shutdown()` stops it."""
    import json as _json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?")[0]
            if path == "/metrics":
                body = prometheus_text().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/metrics.json":
                body = snapshot_json().encode()
                ctype = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):      # no stderr chatter per scrape
            pass

    server = ThreadingHTTPServer((host, int(port)), _Handler)
    t = threading.Thread(target=server.serve_forever,
                         name="mx-metrics", daemon=True)
    t.start()
    return server


# process-wide /metrics endpoint (the MXNET_METRICS_PORT integration):
# one per process no matter how many Servers start, and no Server's close()
# tears it down under the others — it lives until process exit
_shared_metrics = {"server": None}
_shared_metrics_lock = _threading.Lock()


def ensure_metrics_server(port=0, host="127.0.0.1"):
    """Start (once) and return the process-wide /metrics endpoint. Repeat
    calls return the existing server regardless of port — the registry is
    process-global, so one endpoint serves every subsystem."""
    with _shared_metrics_lock:
        if _shared_metrics["server"] is None:
            _shared_metrics["server"] = start_metrics_server(port, host)
        return _shared_metrics["server"]
